#!/usr/bin/env bash
# Tier-1 verification gate — the EXACT command from ROADMAP.md ("Tier-1
# verify"). Keep the two in sync verbatim: CI, reviewers, and the driver all
# key off this line. `-m 'not slow'` plus pytest's default test-file pattern
# (test_*.py / *_test.py) means nothing under tests/perf/ is ever collected
# here — tests/unit/test_tier1_collection.py guards that invariant.
# The static-analysis gate rides along inside this run: tests/unit/
# test_lint_programs.py::test_shipped_registry_lints_clean and the AST
# baseline test in test_lint_ast.py execute the same passes `ds-tpu lint`
# runs. scripts/lint.sh is the standalone CLI variant (emits the JSON
# report for CI artifact upload); it needs no separate tier-1 slot.
# Timeout raised 870 -> 1080 at PR 19: the suite grew to 940+ tests over 18
# PRs and a clean full run takes ~880 s on the reference container — the old
# budget was killing green runs at ~98%.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1080 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
