#!/usr/bin/env bash
# Static-analysis gate: `ds-tpu lint --json` over the whole package (AST
# passes) and the representative engine registry (program passes on
# AOT-lowered HLO). Exits nonzero on any non-allowlisted violation OR any
# stale allowlist entry, so CI fails closed in both directions.
#
# The JSON report lands in /tmp/_lint.json (deterministic bytes — diff two
# runs to prove a change is lint-neutral). Environment is pinned to the same
# 8-virtual-device CPU mesh the tier-1 tests use; `bin/ds-tpu lint` re-pins
# it too, so running this on a TPU host is safe.
#
# tests/unit/test_lint_programs.py::test_shipped_registry_lints_clean and
# tests/unit/test_lint_ast.py::test_package_ast_baseline_is_clean_modulo_shipped_allowlist
# run the same two surfaces inside tier-1; this script is the standalone CLI
# entry for CI pipelines that want the JSON artifact.
set -o pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
# deterministic JSON report on stdout (CI log) and in the --out artifact;
# engine-build INFO lines go to stderr so stdout stays parseable
timeout -k 10 300 "$REPO/bin/ds-tpu" lint --json --out /tmp/_lint.json
lint_rc=$?
# comm-sim: two-level ICI+DCN schedule replay — per-level wire-byte manifest
# (incl. the >= 8x compressed cross-slice reduction floor); /tmp/_comm_sim.json
# is byte-stable, diff two runs to prove a change is schedule-neutral
timeout -k 10 300 "$REPO/bin/ds-tpu" comm-sim --out /tmp/_comm_sim.json
comm_rc=$?
# serve-sim: seeded 64-request serving replay, SLO-gated (generous wall-clock
# limits so the gate trips on starvation regressions, not machine speed), with
# the request-trace ledger dumped and its Perfetto export byte-compared
# against the committed golden — any schedule or exporter drift fails CI
timeout -k 10 300 "$REPO/bin/ds-tpu" serve-sim --no-mirror \
    --slo-ttft-ms 60000 --slo-tpot-ms 60000 \
    --dump-ledger /tmp/_serve_ledger.json --json /tmp/_serve_sim.json \
    --output /tmp/_serve_sim_telemetry
serve_rc=$?
if [ "$serve_rc" -eq 0 ]; then
    timeout -k 10 60 "$REPO/bin/ds-tpu" serve-timeline /tmp/_serve_ledger.json \
        -o /tmp/_serve_timeline.trace.json \
    && cmp "$REPO/tests/unit/golden/serve_timeline_64.trace.json" \
           /tmp/_serve_timeline.trace.json
    serve_rc=$?
fi
# prefix-cache gate: seeded shared-system-prompt trace run cache-off AND
# cache-on — token identity plus a STRICT cache-on p50 TTFT improvement in
# the deterministic iteration domain, hit-rate in the JSON report; any
# regression in the cache's ability to buy TTFT fails CI
timeout -k 10 300 "$REPO/bin/ds-tpu" serve-sim --shared-prefix 96 \
    --compare-prefix-cache --slo-ttft-ms 60000 --slo-tpot-ms 60000 \
    --json /tmp/_serve_prefix_cache.json \
    --output /tmp/_serve_prefix_cache_telemetry
cache_rc=$?
# speculative-decoding gate: the same seeded shared-prefix trace run
# speculation-off AND speculation-on (self-draft) — emitted tokens must be
# byte-identical, the speculative run must execute STRICTLY fewer target-model
# steps with target_steps_per_token under the 0.75 budget (PERF.md defines the
# metric), and every spec program must compile exactly once
timeout -k 10 300 "$REPO/bin/ds-tpu" serve-sim --shared-prefix 96 \
    --compare-speculate --spec-steps-budget 0.75 \
    --slo-ttft-ms 60000 --slo-tpot-ms 60000 \
    --json /tmp/_serve_spec.json \
    --output /tmp/_serve_spec_telemetry
spec_rc=$?
# sharded-decode gate: the same seeded 64-request trace (greedy + beam)
# through the 2-way model-axis head-sharded engine AND a single-chip engine —
# outputs must be token-identical and every sharded program must still
# compile exactly once (zero recompiles after warmup)
timeout -k 10 300 "$REPO/bin/ds-tpu" serve-sim --sharding 2 \
    --verify-unsharded --json /tmp/_serve_sharded.json \
    --output /tmp/_serve_sharded_telemetry
shard_rc=$?
# anatomy: roofline ledger + overlap analysis over the comm-mode registry
# entries, with the flat-vs-hierarchical-vs-overlap exposed-DCN comparison
# byte-compared against the committed golden — any pricing or exchange drift
# fails CI. (`ds-tpu anatomy` itself exits nonzero when the two-level modes
# stop strictly beating flat, when bucketed overlap stops strictly beating
# the monolithic hierarchical exchange or its grad-ICI exposure leaves zero,
# or when any overlap-enabled entry reports a zero-overlap bucketed grad
# collective — the overlap gate.) Full report in /tmp/_anatomy.json
# (deterministic bytes); /tmp/_anatomy.trace.json is the predicted-schedule
# Perfetto view.
timeout -k 10 300 "$REPO/bin/ds-tpu" anatomy --json --out /tmp/_anatomy.json \
    --entry standard --entry comm_hierarchical --entry comm_compressed \
    --entry comm_overlap --entry comm_overlap_compressed \
    --timeline /tmp/_anatomy.trace.json \
    --comm-compare-out /tmp/_anatomy_comm.json \
&& cmp "$REPO/tests/unit/golden/anatomy_comm_compare.json" \
       /tmp/_anatomy_comm.json
anatomy_rc=$?
# hbm: memory-observatory gate — per-buffer attribution parsed from every
# lint-registry program's entry layout, reconciled against the analytic ZeRO
# memory model within the pinned tolerance ON EVERY ENTRY (`ds-tpu hbm`
# exits 1 on any drift), plus the round-5 OOM-frontier forecast re-derived
# offline (every OOMed PERF.md config predicted infeasible, the winner
# feasible, no compile executed). The stable projection (parsed/modeled
# bytes + verdicts, no XLA-scheduler-dependent watermarks) is byte-compared
# against the committed golden so any attribution drift fails CI.
timeout -k 10 300 "$REPO/bin/ds-tpu" hbm --json --out /tmp/_hbm.json \
    --golden-out /tmp/_hbm_golden.json \
&& cmp "$REPO/tests/unit/golden/hbm_registry_sweep.json" \
       /tmp/_hbm_golden.json \
&& timeout -k 10 60 "$REPO/bin/ds-tpu" hbm --forecast round5 \
    --json --out /tmp/_hbm_round5.json
hbm_rc=$?
# crash-sim: seeded kill-point sweep (mid-save, between shard writes,
# auto-resume selection, mid-decode, post-preemption) — every scenario must
# recover (bit-equal retrain / warm token-identical restart), and the
# recovery transcript is byte-compared against the committed golden so any
# drift in recovery behavior (chunk counts, resume selection) fails CI
timeout -k 10 600 "$REPO/bin/ds-tpu" crash-sim --json /tmp/_crash_sim.json \
&& cmp "$REPO/tests/unit/golden/crash_sim_transcript.json" \
       /tmp/_crash_sim.json
crash_rc=$?
# goodput attribution: fault-injected stalls with known ground-truth
# durations (checkpoint fence, kill/restore replay, watchdog hang, rank
# sleep) — the run-lifecycle ledger must bill each to the correct badput
# class within tolerance, and the boolean transcript is byte-compared
# against the committed golden so any attribution drift fails CI
timeout -k 10 300 "$REPO/bin/ds-tpu" crash-sim --goodput \
    --json /tmp/_goodput_attr.json \
&& cmp "$REPO/tests/unit/golden/goodput_attribution.json" \
       /tmp/_goodput_attr.json
goodput_rc=$?
# hang-sim: deterministic two-host hang/watchdog rehearsal — host 1 stalls in
# a grad-bucket scope, host 0 can only dump via the peer marker; transcript is
# byte-compared against the committed golden, and the merged two-host Perfetto
# timeline (clock-offset-corrected) against its golden, so any drift in
# detection, cross-host signalling, or the merge/export path fails CI
timeout -k 10 120 "$REPO/bin/ds-tpu" hang-sim --json /tmp/_hang_sim.json \
    --dump-dir /tmp/_hang_sim_dumps \
&& cmp "$REPO/tests/unit/golden/hang_sim_transcript.json" /tmp/_hang_sim.json \
&& timeout -k 10 60 "$REPO/bin/ds-tpu" timeline --cluster /tmp/_hang_sim_dumps \
    --run hangsim -o /tmp/_cluster_timeline.trace.json \
&& cmp "$REPO/tests/unit/golden/cluster_timeline_2host.trace.json" \
       /tmp/_cluster_timeline.trace.json
hang_rc=$?
# profile: measured-time observatory gate — run a traced CPU-mesh window
# through the comm_overlap lint entry and reconcile measured (trace) vs
# predicted (compile-time catalog) vs derived (step counters) per class
# (`ds-tpu profile --reconcile` exits 1 on any drift verdict). The stable
# projection (verdicts, collective execution counts, wire bytes, flops,
# scope/bucket coverage — no wall-clock fields) is byte-compared against the
# committed golden so any attribution or schedule drift fails CI.
timeout -k 10 300 "$REPO/bin/ds-tpu" profile --reconcile --json \
    --out /tmp/_profile.json --golden-out /tmp/_profile_golden.json \
&& cmp "$REPO/tests/unit/golden/profile_reconcile.json" \
       /tmp/_profile_golden.json
profile_rc=$?
# alert-sim: alert attribution harness — four injected ground-truth
# regressions (MFU drop via step-wall inflation, fleet shed spike via
# Poisson arrivals at 2x capacity, loss-scale stuck streak via forced
# overflow, heartbeat dispatch skew), each asserted to fire exactly its own
# default-ruleset rule and nothing else, plus the two-host fleet merge
# naming the first-firing host+rule; transcript is byte-compared against
# the committed golden so any rule/threshold drift fails CI
timeout -k 10 120 "$REPO/bin/ds-tpu" alert-sim --json /tmp/_alert_sim.json \
&& cmp "$REPO/tests/unit/golden/alert_attribution.json" \
       /tmp/_alert_sim.json
alert_rc=$?
# fleet gate: seeded 3-replica shared-prefix fleet with two mid-flight kills —
# affinity routing must emit byte-identical tokens to round-robin while doing
# STRICTLY fewer prefill chunks and a strictly better fleet p50 TTFT, warm
# failover must beat cold on prefill chunks with no request lost (conservation
# via request-trace identity) and the merged goodput_fleet fraction above the
# pinned floor, the fleet percentiles must stay bitwise-equal the
# single-stream sketch, the SLO gate reads the fleet-MERGED percentiles, and
# the iteration-domain run transcript is byte-compared against the committed
# golden so any routing/failover schedule drift fails CI
timeout -k 10 600 "$REPO/bin/ds-tpu" serve-sim --fleet 3 --requests 24 \
    --shared-prefix 96 --compare-affinity \
    --kill 10:0 --kill 30:1 --compare-cold-failover \
    --fleet-goodput-floor 0.8 \
    --slo-ttft-ms 60000 --slo-tpot-ms 60000 \
    --transcript /tmp/_fleet_transcript.json \
    --json /tmp/_serve_fleet.json \
    --output /tmp/_serve_fleet_telemetry \
&& cmp "$REPO/tests/unit/golden/fleet_transcript_24.json" \
       /tmp/_fleet_transcript.json
fleet_rc=$?
[ "$lint_rc" -ne 0 ] && exit "$lint_rc"
[ "$comm_rc" -ne 0 ] && exit "$comm_rc"
[ "$serve_rc" -ne 0 ] && exit "$serve_rc"
[ "$cache_rc" -ne 0 ] && exit "$cache_rc"
[ "$spec_rc" -ne 0 ] && exit "$spec_rc"
[ "$shard_rc" -ne 0 ] && exit "$shard_rc"
[ "$anatomy_rc" -ne 0 ] && exit "$anatomy_rc"
[ "$hbm_rc" -ne 0 ] && exit "$hbm_rc"
[ "$crash_rc" -ne 0 ] && exit "$crash_rc"
[ "$goodput_rc" -ne 0 ] && exit "$goodput_rc"
[ "$hang_rc" -ne 0 ] && exit "$hang_rc"
[ "$profile_rc" -ne 0 ] && exit "$profile_rc"
[ "$alert_rc" -ne 0 ] && exit "$alert_rc"
exit "$fleet_rc"
