"""Config helpers: scalar/list getters and duplicate-key-rejecting JSON object hook.

Mirrors ``deepspeed/runtime/config_utils.py`` (get_scalar_param, dict_raise_error_on_duplicate_keys).
"""


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing a JSON config (reference config.py:455-457)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d
