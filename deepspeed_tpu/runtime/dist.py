"""Multi-host distributed bootstrap.

The reference hardcoded ``dist.init_process_group('nccl')`` in the engine
(``deepspeed/runtime/engine.py:134-149``) with env-var rendezvous set by the
launcher, plus MPI discovery (``engine.py:198-235``). The TPU-native equivalent is
``jax.distributed.initialize``: every host joins a coordination service on node 0,
after which ``jax.devices()`` spans the whole pod and all collectives ride ICI/DCN
automatically — there are no process groups to manage.

Identity is discovered in priority order:
1. explicit arguments,
2. DS_* / standard env set by ``deepspeed_tpu.launcher.launch`` (DS_COORDINATOR_ADDRESS,
   DS_NUM_PROCESSES, DS_PROCESS_ID — with MASTER_ADDR/PORT + WORLD_SIZE/RANK fallbacks),
3. OpenMPI env (OMPI_COMM_WORLD_SIZE/RANK) for `mpirun` launches (reference _mpi_check),
4. Cloud TPU metadata via argument-less ``jax.distributed.initialize()`` when the
   platform is TPU and more than one host is expected.
"""

import os
from typing import Optional

from ..utils import logger

_initialized = False


def is_initialized() -> bool:
    return _initialized


def _env_identity():
    from ..launcher.constants import DEFAULT_COORDINATOR_PORT
    coord = os.environ.get("DS_COORDINATOR_ADDRESS")
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', DEFAULT_COORDINATOR_PORT)}"
    nprocs = os.environ.get("DS_NUM_PROCESSES") or os.environ.get("WORLD_SIZE")
    pid = os.environ.get("DS_PROCESS_ID") or os.environ.get("RANK")
    if coord and nprocs is not None and pid is not None:
        return coord, int(nprocs), int(pid)
    # MPI launch without the per-node launcher (reference engine.py:198-235):
    # OpenMPI exposes OMPI_COMM_WORLD_*, MVAPICH exposes MV2_COMM_WORLD_* / PMI_*.
    for size_key, rank_key in (("OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK"),
                               ("MV2_COMM_WORLD_SIZE", "MV2_COMM_WORLD_RANK"),
                               ("PMI_SIZE", "PMI_RANK")):
        if os.environ.get(size_key) is not None:
            nprocs = int(os.environ[size_key])
            pid = int(os.environ[rank_key])
            if nprocs <= 1:
                # single-rank mpirun: no world to join, no coordinator needed
                return coord or "", nprocs, pid
            # ALL ranks run the bcast even when some have the env set locally —
            # OpenMPI does not forward user env by default, so a conditional
            # collective would deadlock the ranks that lack it. Rank 0's view
            # (env if set, else derived) wins everywhere.
            coord = _mpi_negotiate_coordinator(coord)
            return coord, nprocs, pid
    return None


def _routable_host_address() -> str:
    """First address of `hostname -I` (the launcher's inference, runner.py) with a
    UDP-connect fallback: socket.gethostbyname(hostname) resolves to 127.0.1.1 on
    stock Debian/Ubuntu /etc/hosts, which remote ranks cannot reach."""
    import socket
    import subprocess
    try:
        out = subprocess.run(["hostname", "-I"], capture_output=True, text=True,
                             timeout=5).stdout.split()
        if out:
            return out[0]
    except (OSError, subprocess.SubprocessError):
        pass
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.connect(("8.8.8.8", 80))  # no packet sent; just picks the egress interface
        return s.getsockname()[0]


def _mpi_negotiate_coordinator(local_coord):
    """Rank 0 broadcasts the coordinator address over MPI, like the reference's
    _mpi_check (engine.py:198-235 bcast's master_addr from rank 0). Every rank
    must call this (it is a collective). Needs mpi4py; without it the caller must
    export DS_COORDINATOR_ADDRESS on every rank."""
    from ..launcher.constants import DEFAULT_COORDINATOR_PORT
    try:
        from mpi4py import MPI
    except ImportError as e:
        if local_coord:
            return local_coord  # best effort: hope every rank has it exported
        raise RuntimeError(
            "MPI launch detected but DS_COORDINATOR_ADDRESS is unset and mpi4py is "
            "unavailable to negotiate one; export DS_COORDINATOR_ADDRESS=<rank0-host:port> "
            "on every rank (mpirun -x DS_COORDINATOR_ADDRESS) or launch via the "
            "deepspeed_tpu runner") from e
    comm = MPI.COMM_WORLD
    if comm.Get_rank() == 0:
        coord = local_coord or f"{_routable_host_address()}:{DEFAULT_COORDINATOR_PORT}"
    else:
        coord = None
    coord = comm.bcast(coord, root=0)
    logger.info(f"coordinator address negotiated over MPI: {coord}")
    return coord


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> bool:
    """Join the multi-host world if one is configured. Returns True when a
    multi-process jax.distributed world is (or already was) live; False for
    plain single-process runs (the overwhelmingly common dev path)."""
    global _initialized
    import jax

    if _initialized:
        return True

    if coordinator_address is None:
        ident = _env_identity()
        if ident is None:
            return False
        coordinator_address, env_nprocs, env_pid = ident
        num_processes = num_processes if num_processes is not None else env_nprocs
        process_id = process_id if process_id is not None else env_pid

    if num_processes is not None and num_processes <= 1:
        return False

    # Multi-process CPU worlds (the launcher tests / multichip dry run) need a
    # cross-host collectives transport: jaxlib's CPU client defaults to 'none'
    # and then refuses to compile any computation spanning processes. Gloo-TCP
    # must be selected BEFORE the first backend touch creates the client —
    # init_distributed is the one place guaranteed to run that early. On TPU
    # the platform is not 'cpu' and collectives ride ICI/DCN natively.
    platforms = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS", ""))
    if platforms.split(",")[0].strip().lower() == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # jaxlib built without gloo
            logger.warning("CPU multi-process world without gloo collectives: "
                           "cross-process computations will fail to compile")

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)
    _initialized = True
    logger.info(f"jax.distributed initialized: process {process_id}/{num_processes} "
                f"via {coordinator_address}; global devices: {jax.device_count()}")
    return True


def get_rank() -> int:
    import jax
    return jax.process_index()


def get_world_size() -> int:
    import jax
    return jax.process_count()
