"""Pipeline instruction schedules.

Mirrors ``deepspeed/runtime/pipe/schedule.py`` exactly at the instruction-stream level:
``TrainSchedule`` produces the PipeDream-flush (1F1B) interleave via the even/odd
step-to-micro-batch mapping (reference schedule.py:249-289), ``InferenceSchedule`` the
two-buffer forward stream, and the instruction classes are the atomic vocabulary the
engine executes. Streams are pure Python and deterministic — they are also what the SPMD
executor lowers into its in-graph loop structure, and what the schedule-parity unit tests
assert against.
"""

from abc import ABC, abstractmethod

from ..utils import call_to_str


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0


class PipeSchedule(ABC):
    """Generates sequences of PipeInstruction lists; each yielded list is one atomic step
    (barrier-safe between steps)."""

    def __init__(self, micro_batches, stages, stage_id):
        super().__init__()
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield one list of PipeInstruction per schedule step."""
        raise NotImplementedError()

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only stream with two alternating buffers; even/odd stages order their
    send/recv oppositely so the blocking p2p pairs rendezvous without deadlock."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id

            if _is_even(self.stage_id):
                recv_buf = step_id % 2
                send_buf = (step_id + 1) % 2
            else:
                recv_buf = (step_id + 1) % 2
                send_buf = step_id % 2

            if self.is_first_stage or self.is_last_stage:
                if self._valid_micro_batch(micro_batch_id):
                    cmds.append(LoadMicroBatch(recv_buf))

            if _is_even(self.stage_id):
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
            else:
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))

            if self._valid_micro_batch(micro_batch_id):
                cmds.append(ForwardPass(recv_buf))

            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B training stream: forwards and backwards interleave once the pipe fills, so
    at most ``stages - stage_id + 1`` activations are live per stage.

    The whole schedule follows from two latencies (see ``_step_to_micro_batch``):
    micro-batch 0's forward reaches stage s at step s, and its backward returns to
    stage s at step ``2*stages - s - 1``; every stage then alternates F/B locally.
    Stream-level behavior is pinned to the reference's
    (deepspeed/runtime/pipe/schedule.py TrainSchedule) by the schedule parity tests.
    """

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        last_mb = -1  # micro-batch this stage touched on the previous step
        for step_id in range(total_steps):
            mb, fwd = self._step_to_micro_batch(step_id)
            live = self._valid_micro_batch(mb)
            retiring = self._valid_micro_batch(last_mb)
            cmds = []

            # Boundary traffic first, pairing this step's recv with the LAST
            # micro-batch's opposite-direction send: both sides of a stage boundary
            # then issue their matching transfer within the same merged step, which
            # is what lets blocking pairwise exchanges rendezvous.
            if fwd and self._valid_stage(self.prev_stage):
                if live:
                    cmds.append(RecvActivation(self._buffer_idx(mb)))
                if retiring:
                    cmds.append(SendGrad(self._buffer_idx(last_mb)))
            elif not fwd and self._valid_stage(self.next_stage):
                if retiring:
                    cmds.append(SendActivation(self._buffer_idx(last_mb)))
                if live:
                    cmds.append(RecvGrad(self._buffer_idx(mb)))

            if live:
                # only the pipe endpoints touch the dataloader (inputs at stage 0,
                # labels at the loss stage)
                if fwd and (self.is_first_stage or self.is_last_stage):
                    cmds.append(LoadMicroBatch(self._buffer_idx(mb)))
                cmds.append((ForwardPass if fwd else BackwardPass)(self._buffer_idx(mb)))

            if step_id == total_steps - 1:  # whole batch drained: reduce + step
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            last_mb = mb
            yield cmds

    def num_pipe_buffers(self):
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        """(micro_batch_id, is_forward) for this stage at a global step.

        Two closed forms cover the whole interleave. Forwards: micro-batch f's
        activation reaches stage s at step ``s + 2f`` (one step of fill latency per
        stage, one F and one B per micro-batch thereafter), so on steps with the
        stage's own parity ``f = (step - s) / 2``. Backwards: micro-batch 0's
        gradient returns to stage s at step ``2*stages - s - 1`` (down the pipe and
        back), so on opposite-parity steps ``b = (step - (2*stages - s - 1)) / 2``.
        Out-of-range ids simply mean the stage idles that step.
        """
        offset = step_id - self.stage_id
        if offset % 2 == 0:
            return offset // 2, True
        return (step_id - (2 * self.stages - self.stage_id - 1)) // 2, False


class DataParallelSchedule(PipeSchedule):
    """Plain DP with gradient accumulation, expressed as a pipeline schedule."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:
    """Atomic engine instruction; kwargs become attributes (namedtuple-style)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        # sorted kwargs: two equal instructions built with different keyword
        # orders must print identically (schedule goldens / lint diffs)
        return call_to_str(self.name, **{k: self.kwargs[k] for k in sorted(self.kwargs)})

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass
