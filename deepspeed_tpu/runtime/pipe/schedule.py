"""Pipeline instruction schedules.

Mirrors ``deepspeed/runtime/pipe/schedule.py`` exactly at the instruction-stream level:
``TrainSchedule`` produces the PipeDream-flush (1F1B) interleave via the even/odd
step-to-micro-batch mapping (reference schedule.py:249-289), ``InferenceSchedule`` the
two-buffer forward stream, and the instruction classes are the atomic vocabulary the
engine executes. Streams are pure Python and deterministic — they are also what the SPMD
executor lowers into its in-graph loop structure, and what the schedule-parity unit tests
assert against.
"""

from abc import ABC, abstractmethod

from ..utils import call_to_str


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0


class PipeSchedule(ABC):
    """Generates sequences of PipeInstruction lists; each yielded list is one atomic step
    (barrier-safe between steps)."""

    def __init__(self, micro_batches, stages, stage_id):
        super().__init__()
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield one list of PipeInstruction per schedule step."""
        raise NotImplementedError()

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only stream with two alternating buffers; even/odd stages order their
    send/recv oppositely so the blocking p2p pairs rendezvous without deadlock."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id

            if _is_even(self.stage_id):
                recv_buf = step_id % 2
                send_buf = (step_id + 1) % 2
            else:
                recv_buf = (step_id + 1) % 2
                send_buf = step_id % 2

            if self.is_first_stage or self.is_last_stage:
                if self._valid_micro_batch(micro_batch_id):
                    cmds.append(LoadMicroBatch(recv_buf))

            if _is_even(self.stage_id):
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
            else:
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))

            if self._valid_micro_batch(micro_batch_id):
                cmds.append(ForwardPass(recv_buf))

            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B training stream: forwards and backwards interleave once the pipe fills, so
    at most ``stages - stage_id + 1`` activations are live per stage."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)

            prev_buffer = curr_buffer = None
            if self._valid_micro_batch(prev_micro_batch_id):
                prev_buffer = self._buffer_idx(prev_micro_batch_id)
            if self._valid_micro_batch(micro_batch_id):
                curr_buffer = self._buffer_idx(micro_batch_id)

            cmds = []

            # Activation/gradient exchange. A forward step pairs its activation recv with
            # the previous micro-batch's grad send (and vice versa) so adjacent stages'
            # blocking p2p calls always match up.
            if is_forward:
                if self._valid_micro_batch(micro_batch_id) and self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(curr_buffer))
                if self._valid_micro_batch(prev_micro_batch_id) and self._valid_stage(self.prev_stage):
                    cmds.append(SendGrad(prev_buffer))
            else:
                if self._valid_micro_batch(prev_micro_batch_id) and self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(prev_buffer))
                if self._valid_micro_batch(micro_batch_id) and self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(curr_buffer))

            # First/last stage loads the micro-batch
            if self.stage_id == 0 or self.stage_id == self.stages - 1:
                if is_forward and self._valid_micro_batch(micro_batch_id):
                    cmds.append(LoadMicroBatch(curr_buffer))

            # Computation
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    cmds.append(ForwardPass(curr_buffer))
                else:
                    cmds.append(BackwardPass(curr_buffer))

            # Model step at the end of the batch
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        """Map a global step to (micro_batch_id, is_forward) for this stage.

        Even stages run forwards on even steps; odd stages on odd steps — the two
        populations interleave 1F1B without further coordination.
        """
        if _is_even(step_id) and _is_even(self.stage_id):
            return self._even_step_forward_id(step_id), True
        if _is_odd(step_id) and _is_odd(self.stage_id):
            return self._odd_step_forward_id(step_id), True
        if _is_even(step_id) and _is_odd(self.stage_id):
            return self._even_step_backward_id(step_id), False
        if _is_odd(step_id) and _is_even(self.stage_id):
            return self._odd_step_backward_id(step_id), False
        raise AssertionError("unreachable")

    def _even_step_forward_id(self, step_id):
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        return (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2


class DataParallelSchedule(PipeSchedule):
    """Plain DP with gradient accumulation, expressed as a pipeline schedule."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


class PipeInstruction:
    """Atomic engine instruction; kwargs become attributes (namedtuple-style)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass
