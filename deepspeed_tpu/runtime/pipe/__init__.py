from .schedule import (PipeSchedule, InferenceSchedule, TrainSchedule, DataParallelSchedule,
                       PipeInstruction, OptimizerStep, ReduceGrads, ReduceTiedGrads,
                       LoadMicroBatch, ForwardPass, BackwardPass, SendActivation,
                       RecvActivation, SendGrad, RecvGrad)
