"""Pipeline engine: SPMD ppermute executor with an instruction-stream fallback.

TPU-native re-design of ``deepspeed/runtime/pipe/engine.py`` (PipelineEngine l.45).
``deepspeed.initialize(model=PipelineModule)`` — the reference's production multi-GPU
pipelining entry point (deepspeed/__init__.py:111-133) — routes onto ONE of two
executors:

1. **SPMD mode** (default when eligible): homogeneous stages (the layout
   ``partition_balanced`` yields for transformer stacks — an optional stage-0 prefix
   like an embedding, S identical core blocks, an optional last-stage suffix like a
   head) lower onto ``parallel/pipeline_spmd.py``: core stage params are STACKED on a
   leading axis sharded over the ``pipe`` mesh axis, micro-batches stream through a
   ``lax.scan`` whose stage→stage hand-off is a single ``lax.ppermute`` riding ICI,
   and the whole 1F1B-equivalent window compiles into ONE jitted train step (XLA
   derives the backward pipeline — see pipeline_spmd.py). This is the path that runs
   the pipe axis of a real multi-chip mesh; the base engine supplies fp16/ZeRO/
   monitoring unchanged (the accumulation window folds into the scan, so the base
   sees ``gradient_accumulation_steps == 1``).
2. **Instruction mode** (fallback / ``{"pipeline": {"spmd": false}}``): the
   single-controller executor below, which interprets the reference's exact
   instruction vocabulary and 1F1B stream (schedule.py) with jitted per-stage
   forwards/backwards — the debug/heterogeneous-stage path, parity-tested against
   the schedule semantics.

Checkpoints are layer-keyed in BOTH modes (the SPMD stacking is undone on save via
``_ckpt_export``), so stage boundaries and executor modes can change between save
and load exactly like the reference (pipe/module.py:536-567).

Instruction-mode execution model vs the reference:

- The reference runs one process per stage, eager autograd per micro-batch, and blocking
  p2p broadcasts (pipe/p2p.py). Here a single controller executes every stage's stream
  (merged by step index) with **jitted per-stage forward/backward functions**; the p2p
  sends/recvs become buffer hand-offs whose device placement XLA manages, and each
  micro-batch is sharded over the mesh ``data`` axis so DP gradient reduction is emitted
  by XLA (no NCCL allreduce). Within one merged step all Sends execute before any Recv —
  the scheduling invariant that lets the reference's blocking broadcasts rendezvous.
- BackwardPass recomputes the stage forward inside the jitted VJP (activation
  checkpointing per stage — the JAX analog of the reference's retained autograd graphs
  per pipe buffer; SURVEY §7 "hard parts").
- Tied layers (TiedLayerSpec) share one parameter entry; their gradient contributions sum
  during the backward merge — ``ReduceTiedGrads`` (reference pipe/module.py:405-474)
  needs no separate collective.
- ``OptimizerStep`` reuses the base engine's jitted sharded update (ZeRO over ``data``).

``forward``/``backward``/``step`` are blocked in pipeline mode exactly like the reference
(pipe/engine.py:1034-1044): use ``train_batch``/``eval_batch``.

For *multi-chip pipe-axis* execution with homogeneous transformer stages, see
``parallel/pipeline_spmd.py`` (shard_map + ppermute inside one jit).
"""

import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.mesh import DATA_AXIS, PIPE_AXIS, build_mesh
from ...parallel.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from ...parallel.pipeline_spmd import pipeline_apply
from ...utils import log_dist, logger
from ..engine import DeepSpeedEngine
from . import schedule

# params-dict key holding the pipe-stacked core stage parameters in SPMD mode
# (namespaced so it can never collide with canonical 'layer_N' / 'tied::' keys)
STACKED_KEY = "pipe_stages::stacked"


def _raw_config_dict(args, config_params):
    """The raw JSON config dict before DeepSpeedConfig exists — the SPMD routing
    decision must happen before super().__init__ parses the config."""
    if isinstance(config_params, dict):
        return config_params
    path = getattr(args, "deepspeed_config", None) if args is not None else None
    if path:
        try:
            import json
            with open(path) as f:
                return json.load(f)
        except Exception:
            return {}
    return {}


def _spec_signature(spec):
    """Comparable identity of a layer spec for stage-homogeneity checks. None marks
    a spec that cannot be proven identical across stages (tied layers — their shared
    storage cannot stack — or specs whose constructor args defeat comparison)."""
    if isinstance(spec, TiedLayerSpec):
        return None
    if isinstance(spec, LayerSpec):
        try:
            return ("spec", id(spec.typename), repr(spec))
        except Exception:
            return None
    if callable(spec):
        return ("callable", id(spec))
    return None



def _assert_ring_bound(chan, src_stage, receiver_ring, direction):
    """The reference's per-stage buffer-ring memory contract
    (deepspeed/runtime/pipe/engine.py:133-148) as a tested invariant: payloads
    in flight from ``src_stage`` never exceed the RECEIVER's num_pipe_buffers()."""
    in_flight = sum(1 for (src, _) in chan if src == src_stage)
    assert in_flight <= receiver_ring, (
        f"stage {src_stage} {direction} channel holds {in_flight} payloads "
        f"> receiver num_pipe_buffers()={receiver_ring}")


class PipelineError(Exception):
    """Errors related to the use of deepspeed.PipelineEngine."""


_SEND_CMDS = (schedule.SendActivation, schedule.SendGrad, schedule.LoadMicroBatch)


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, args=None, model=None, optimizer=None, model_parameters=None,
                 training_data=None, lr_scheduler=None, mpu=None, dist_init_required=None,
                 collate_fn=None, config_params=None, mesh=None):
        assert isinstance(model, PipelineModule), "model must be a PipelineModule"
        self.pipe_module = model
        self.num_stages = model.num_stages

        canonical, layer_keys = self._canonicalize_params(model, model_parameters)
        self._layer_keys = layer_keys

        # ---- executor selection (SPMD ppermute path vs instruction fallback) ----
        self._spmd = False
        self._spmd_decomp = None
        raw_cfg = _raw_config_dict(args, config_params)
        spmd_opt = (raw_cfg.get("pipeline") or {}).get("spmd", "auto")
        opt_name = str(((raw_cfg.get("optimizer") or {}).get("type") or "")).lower()
        has_param_groups = bool(((raw_cfg.get("optimizer") or {}).get("params") or {})
                                .get("param_groups"))
        n_dev = (int(np.prod(list(mesh.shape.values()))) if mesh is not None
                 else len(jax.devices()))
        eligible = (spmd_opt in (True, "auto")
                    and self.num_stages > 1
                    and model.loss_fn is not None
                    and n_dev % self.num_stages == 0
                    # 1-bit Adam needs replicated params; param-group regex patterns
                    # are written against canonical layer paths
                    and opt_name != "onebitadam"
                    and not has_param_groups
                    and (mesh is None or mesh.shape.get(PIPE_AXIS, 1) == self.num_stages))
        if eligible:
            self._spmd_decomp = self._find_spmd_decomposition(model, layer_keys, canonical)
            if self._spmd_decomp is None and spmd_opt is True:
                raise ValueError(
                    "pipeline.spmd=true but the stage partition is not homogeneous "
                    f"(parts={model.parts}): the SPMD executor needs S identical core "
                    "blocks (plus optional stage-0 prefix / last-stage suffix)")

        if self._spmd_decomp is not None:
            self._spmd = True
            if mesh is None:
                mesh = build_mesh(pipe=self.num_stages)
            spmd_params = self._canonical_to_spmd(canonical)
            shardings = self._spmd_shardings(mesh, spmd_params)
            model_fn = self._build_spmd_model_fn(mesh)
            super().__init__(args=args, model=model_fn, optimizer=optimizer,
                             model_parameters=spmd_params, training_data=training_data,
                             lr_scheduler=lr_scheduler, mpu=None,
                             dist_init_required=dist_init_required, collate_fn=collate_fn,
                             config_params=config_params, mesh=mesh,
                             param_shardings=shardings)
            self._spmd_treedef = jax.tree_util.tree_structure(self.master_params)
            # the canonical dict built above has exactly the round-trip structure —
            # no need to materialize an unstack just for its treedef
            self._canonical_treedef = jax.tree_util.tree_structure(canonical)
        else:
            super().__init__(args=args, model=self._whole_model_fn, optimizer=optimizer,
                             model_parameters=canonical, training_data=training_data,
                             lr_scheduler=lr_scheduler, mpu=None,
                             dist_init_required=dist_init_required,
                             collate_fn=collate_fn, config_params=config_params, mesh=mesh)
        assert self._offload is None, \
            "cpu_offload is not supported with pipeline parallelism (the pipeline " \
            "optimizer step runs on device; reference pairs offload with plain ZeRO-2 only)"

        # the REAL accumulation window (SPMD mode reports 1 to the base engine — the
        # window folds into the jitted scan; see gradient_accumulation_steps)
        self.micro_batches = self.config.gradient_accumulation_steps
        if not self._spmd:
            self._compile_stage_fns()
        self.agg_train_loss = None

        # ---- pipeline schedule observatory (docs/pipeline-trace.md) ----
        # Disabled (the default) leaves ``pipe_trace`` as None: the executor
        # takes the untraced branch and the compiled stage programs are
        # HLO-instruction-identical to a build without the subsystem.
        self.pipe_trace = None
        if getattr(self.config, "pipeline_trace_enabled", False):
            if self._spmd:
                logger.warning(
                    "[deepspeed_tpu] telemetry.pipeline_trace: the SPMD executor "
                    "folds the whole schedule into one jitted scan — there is no "
                    "instruction stream to trace; set pipeline.spmd=false to "
                    "record spans")
            else:
                from ...utils.pipeline_trace import PipelineTracer
                self.pipe_trace = PipelineTracer(
                    stages=self.num_stages,
                    capacity=self.config.pipeline_trace_capacity,
                    dump_dir=self.config.pipeline_trace_dump_dir or None,
                    host_id=jax.process_index())
                rec = getattr(self._numerics, "recorder", None) if self._numerics else None
                if rec is not None:
                    rec.pipeline_trace = self.pipe_trace

        d = self._spmd_decomp
        log_dist(
            f"PipelineEngine[{'SPMD' if self._spmd else 'instruction'}]: "
            f"{self.num_stages} stages, parts={model.parts}"
            + (f", core={d['L']} layers/stage, prefix={len(d['prefix'])}, "
               f"suffix={len(d['suffix'])}, mesh={dict(self.mesh.shape)}"
               if self._spmd else ""),
            ranks=[0])

    def gradient_accumulation_steps(self):
        # SPMD mode folds the whole micro-batch window into ONE jitted call (the
        # scan inside pipeline_apply): the base engine sees a window of 1 so each
        # train_batch is exactly one forward/backward/step.
        if getattr(self, "_spmd", False):
            return 1
        return super().gradient_accumulation_steps()

    # ------------------------------------------------------------- params
    def _canonicalize_params(self, module: PipelineModule, model_parameters):
        """Per-layer params list → dict keyed by layer id; tied layers collapse onto one
        'tied::<key>' entry (shared storage, summed grads)."""
        if model_parameters is None:
            raise ValueError("PipelineEngine requires model_parameters: the list returned "
                             "by PipelineModule.init_params(rng, sample_input)")
        assert len(model_parameters) == module.num_layers(), \
            f"expected {module.num_layers()} per-layer param entries"
        canonical: Dict[str, Any] = {}
        layer_keys: List[Optional[str]] = []
        for idx, (spec, p) in enumerate(zip(module._layer_specs, model_parameters)):
            if p is None:
                layer_keys.append(None)
                continue
            key = f"tied::{spec.key}" if isinstance(spec, TiedLayerSpec) else f"layer_{idx}"
            if key not in canonical:
                canonical[key] = p
            layer_keys.append(key)
        return canonical, layer_keys

    # ------------------------------------------------------------- SPMD executor
    def _find_spmd_decomposition(self, module, layer_keys, canonical):
        """Homogeneity detection: can the stage partition be expressed as
        ``[prefix] + S x (identical core block stack) + [suffix]``?

        Returns ``{"starts": per-stage core start index, "L": core length,
        "prefix": stage-0-only layer indices, "suffix": last-stage-only indices}``
        or None when the partition is heterogeneous (→ instruction fallback).
        Matching is by layer-spec identity (same class + constructor args) AND
        param-tree structure/shape/dtype at every core position, so stacking over
        the pipe axis is guaranteed well-formed."""
        S = module.num_stages
        parts = module.parts
        counts = [parts[s + 1] - parts[s] for s in range(S)]
        sigs = [_spec_signature(spec) for spec in module._layer_specs]

        def try_core(L):
            if counts[0] < L or counts[-1] < L:
                return None
            if any(counts[s] != L for s in range(1, S - 1)):
                return None
            starts = [parts[1] - L] + [parts[s] for s in range(1, S)]
            pattern = sigs[starts[0]:starts[0] + L]
            if any(p is None for p in pattern):
                return None
            for s in range(1, S):
                if sigs[starts[s]:starts[s] + L] != pattern:
                    return None
            for j in range(L):
                keys = [layer_keys[starts[s] + j] for s in range(S)]
                if any((k is None) != (keys[0] is None) for k in keys):
                    return None
                if keys[0] is None:
                    continue
                trees = [canonical[k] for k in keys]
                t0 = jax.tree_util.tree_structure(trees[0])
                leaves0 = jax.tree_util.tree_leaves(trees[0])
                for t in trees[1:]:
                    if jax.tree_util.tree_structure(t) != t0:
                        return None
                    for a, b in zip(leaves0, jax.tree_util.tree_leaves(t)):
                        if a.shape != b.shape or a.dtype != b.dtype:
                            return None
            return starts

        if S > 2:
            candidates = [counts[1]]  # middle stages fix the core length
        else:
            candidates = range(min(counts), 0, -1)  # S=2: maximal core first
        for L in candidates:
            starts = try_core(L)
            if starts is not None:
                return {"starts": starts, "L": L,
                        "prefix": list(range(0, parts[1] - L)),
                        "suffix": list(range(parts[S - 1] + L, parts[S]))}
        return None

    def _canonical_to_spmd(self, canonical):
        """Layer-keyed dict -> SPMD layout: core stage params stack on a leading
        S axis (one entry under STACKED_KEY); prefix/suffix keep canonical keys."""
        d = self._spmd_decomp
        S, L, starts = self.num_stages, d["L"], d["starts"]
        out = {}
        for idx in d["prefix"] + d["suffix"]:
            k = self._layer_keys[idx]
            if k is not None:
                out[k] = canonical[k]
        stacked = []
        for j in range(L):
            if self._layer_keys[starts[0] + j] is None:
                stacked.append(None)
                continue
            per_stage = [canonical[self._layer_keys[starts[s] + j]] for s in range(S)]
            stacked.append(jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage))
        out[STACKED_KEY] = tuple(stacked)
        return out

    def _spmd_to_canonical(self, spmd):
        """Inverse of _canonical_to_spmd (works on any tree with the params
        structure — Adam moments included)."""
        d = self._spmd_decomp
        S, starts = self.num_stages, d["starts"]
        out = {k: v for k, v in spmd.items() if k != STACKED_KEY}
        for j, ent in enumerate(spmd[STACKED_KEY]):
            if ent is None:
                continue
            for s in range(S):
                out[self._layer_keys[starts[s] + j]] = jax.tree_util.tree_map(
                    lambda a, s=s: a[s], ent)
        return out

    def _spmd_shardings(self, mesh, spmd_params):
        """Core stacks shard their leading (stage) axis over ``pipe``; prefix/suffix
        params replicate (ZeRO composes on top via merge_zero_into)."""
        repl = NamedSharding(mesh, P())

        def leaf(a):
            return NamedSharding(mesh, P(*([PIPE_AXIS] + [None] * (a.ndim - 1))))

        out = {k: jax.tree_util.tree_map(lambda _: repl, v)
               for k, v in spmd_params.items() if k != STACKED_KEY}
        out[STACKED_KEY] = jax.tree_util.tree_map(leaf, spmd_params[STACKED_KEY])
        return out

    def _build_spmd_model_fn(self, mesh):
        """``(params, x_microbatches, labels_microbatches) -> mean loss`` through the
        ppermute pipeline. The prefix runs as pipeline_apply's first_stage_fn, the
        suffix + loss as its last_stage_fn; both draw their params from the SAME
        params dict the core stack lives in, so tied prefix/suffix layers (shared
        canonical entry) get their gradient contributions summed by autodiff."""
        d = self._spmd_decomp
        layers = self.pipe_module._built_layers
        keys = self._layer_keys
        core_idx0 = [d["starts"][0] + j for j in range(d["L"])]
        core_keys = [keys[i] for i in core_idx0]
        prefix, suffix = d["prefix"], d["suffix"]
        pkeys = list(dict.fromkeys(k for i in prefix
                                   if (k := keys[i]) is not None))
        skeys = list(dict.fromkeys(k for i in suffix
                                   if (k := keys[i]) is not None))
        loss_fn = self.pipe_module.loss_fn
        apply_layer = self._apply_layer

        def stage_body(stage_params, x):
            with jax.named_scope("ds_pipe_stage"):
                for j, idx in enumerate(core_idx0):
                    x = (layers[idx](x) if core_keys[j] is None
                         else layers[idx].apply(stage_params[j], x))
            return x

        # remat the stage body: backward recomputes the stage forward per scan step,
        # the same memory/compute trade the instruction executor's jitted VJPs make
        stage_fn = jax.checkpoint(stage_body)

        first_fn = None
        if prefix:
            def first_fn(x, *pvals):
                with jax.named_scope("ds_pipe_first"):
                    env = dict(zip(pkeys, pvals))
                    for idx in prefix:
                        x = apply_layer(idx, env, x)
                return x

        def last_fn(y, labels_all, *rest):
            with jax.named_scope("ds_pipe_last"):
                svals, mb = rest[:-1], rest[-1]
                env = dict(zip(skeys, svals))
                for idx in suffix:
                    y = apply_layer(idx, env, y)
                return loss_fn(y, labels_all[mb])

        def model_fn(params, x_mb, labels_mb):
            last_args = (labels_mb,) + tuple(params[k] for k in skeys)
            lspecs = ((P(*([None, DATA_AXIS] + [None] * (labels_mb.ndim - 2))),)
                      + tuple(P() for _ in skeys))
            return pipeline_apply(
                stage_fn, params[STACKED_KEY], x_mb, mesh=mesh,
                last_stage_fn=last_fn, last_stage_args=last_args,
                first_stage_fn=first_fn,
                first_stage_args=tuple(params[k] for k in pkeys),
                last_stage_args_specs=lspecs,
                first_stage_args_specs=tuple(P() for _ in pkeys))

        return model_fn

    # canonical (layer-keyed) <-> runtime layout for checkpoints; reference parity:
    # pipeline checkpoints reload under a different stage count (module.py:536-567)
    def _map_opt(self, opt, fn, params_treedef):
        def conv(field):
            return (fn(field)
                    if jax.tree_util.tree_structure(field) == params_treedef else field)
        if hasattr(opt, "_fields"):
            return type(opt)(*[conv(f) for f in opt])
        return conv(opt)

    def _ckpt_export(self, tree, kind):
        if not self._spmd:
            return tree
        if kind == "opt":
            return self._map_opt(tree, self._spmd_to_canonical, self._spmd_treedef)
        return self._spmd_to_canonical(tree)

    def _ckpt_import(self, tree, kind):
        if not self._spmd:
            return tree
        if kind == "opt":
            return self._map_opt(tree, self._canonical_to_spmd, self._canonical_treedef)
        return self._canonical_to_spmd(tree)

    def canonical_master_params(self):
        """fp32 master params keyed by layer (the checkpoint representation)
        regardless of executor mode — SPMD mode stores core stages pipe-stacked."""
        return self._ckpt_export(self.master_params, "master")

    def _apply_layer(self, idx: int, params, x):
        layer = self.pipe_module._built_layers[idx]
        key = self._layer_keys[idx]
        spec = self.pipe_module._layer_specs[idx]
        if key is None:
            return layer(x)
        fwd = spec.forward_fn if isinstance(spec, TiedLayerSpec) and spec.forward_fn else None
        if fwd is not None:
            return fwd(layer, params[key], x)
        return layer.apply(params[key], x)

    def _whole_model_fn(self, params, *batch):
        """Sequential full-model apply (eval path / reference semantics; accepts
        either the canonical or the SPMD params layout)."""
        if getattr(self, "_spmd", False) and STACKED_KEY in params:
            params = self._spmd_to_canonical(params)
        x = batch[0]
        for idx in range(self.pipe_module.num_layers()):
            x = self._apply_layer(idx, params, x)
        if self.pipe_module.loss_fn is not None and len(batch) > 1:
            return self.pipe_module.loss_fn(x, batch[1])
        return x

    # ------------------------------------------------------------- stage functions
    def _stage_fn(self, stage_id: int) -> Callable:
        lo, hi = self.pipe_module.parts[stage_id], self.pipe_module.parts[stage_id + 1]
        interval = self.pipe_module.activation_checkpoint_interval

        def run_range(start, end):
            def range_fn(stage_params, x):
                for idx in range(start, end):
                    x = self._apply_layer(idx, stage_params, x)
                return x
            return range_fn

        if interval and interval > 0:
            # remat each interval-sized chunk (reference PipelineModule.forward,
            # pipe/module.py:292-346: exec_range_func wrapped per interval)
            from ..activation_checkpointing.checkpointing import checkpoint_wrapper
            chunks = [(s, min(s + interval, hi)) for s in range(lo, hi, interval)]

            def fn(stage_params, x):
                for start, end in chunks:
                    x = checkpoint_wrapper(run_range(start, end))(stage_params, x)
                return x
            return fn

        return run_range(lo, hi)

    def _stage_param_keys(self, stage_id: int) -> List[str]:
        lo, hi = self.pipe_module.parts[stage_id], self.pipe_module.parts[stage_id + 1]
        keys = []
        for idx in range(lo, hi):
            k = self._layer_keys[idx]
            if k is not None and k not in keys:
                keys.append(k)
        return keys

    def _compile_stage_fns(self):
        self._stage_fwd = []
        self._stage_bwd = []
        self._stage_last_bwd = None
        loss_fn = self.pipe_module.loss_fn
        for s in range(self.num_stages):
            fn = self._stage_fn(s)
            self._stage_fwd.append(jax.jit(fn))

            def bwd(stage_params, x, g, _fn=fn):
                _, vjp = jax.vjp(_fn, stage_params, x)
                dparams, dx = vjp(g)
                return dparams, dx

            self._stage_bwd.append(jax.jit(bwd))

            if s == self.num_stages - 1 and loss_fn is not None:
                def last_bwd(stage_params, x, labels, scale, _fn=fn):
                    # ``scale`` folds 1/micro_batches AND the fp16 loss scale: grads
                    # leave every stage loss-scaled (the dx flowing upstream carries
                    # the factor), and _jit_apply_update unscales by cur_scale with
                    # the overflow check intact (reference loss_scaler.py:51-53).
                    def f(p, xx):
                        return loss_fn(_fn(p, xx), labels) * scale
                    loss, (dparams, dx) = jax.value_and_grad(f, argnums=(0, 1))(stage_params, x)
                    return loss / scale, dparams, dx

                self._stage_last_bwd = jax.jit(last_bwd)

                def last_eval(stage_params, x, labels, _fn=fn):
                    return loss_fn(_fn(stage_params, x), labels)

                self._stage_last_eval = jax.jit(last_eval)

    # ------------------------------------------------------------------ lint hooks
    def lint_programs(self, sample_batch):
        """Pipeline manifests for the lint suite (docs/lint.md).

        SPMD path: the base-engine programs, with the forward/backward budget
        extended by the collective-permute traffic that moves activations over
        the pipe axis (the reference's p2p.send/recv). Instruction-executor
        path: the per-stage jits are LOCAL programs — zero large collectives
        is the invariant — chained through ``jax.eval_shape`` so each stage's
        input aval is the previous stage's output.
        """
        if self._spmd:
            progs = []
            for name, jitted, args, man in super().lint_programs(sample_batch):
                if name in ("loss_and_grad", "fused_step"):
                    man = dict(man)
                    coll = dict(man.get("collectives", {}))
                    coll["collective-permute"] = {"min": 1}
                    man["collectives"] = coll
                progs.append((name, jitted, args, man))
            return progs

        compute = self._lint_dtype_name(self.compute_dtype)
        local_man = {"compute_dtype": compute, "strict": True,
                     "donation": {"check_unusable": True}}
        x = sample_batch[0]
        labels = sample_batch[1] if len(sample_batch) > 1 else None

        def sds(a):
            a = np.asarray(a)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        scale = self.scaler_state.cur_scale
        progs = []
        x_in = sds(x)
        for s in range(self.num_stages):
            p_s = self._select_params(s)
            last = s == self.num_stages - 1
            progs.append((f"stage{s}_fwd", self._stage_fwd[s], (p_s, x_in),
                          dict(local_man)))
            x_out = jax.eval_shape(self._stage_fwd[s], p_s, x_in)
            if last and self._stage_last_bwd is not None and labels is not None:
                progs.append((f"stage{s}_last_bwd", self._stage_last_bwd,
                              (p_s, x_in, sds(labels), scale), dict(local_man)))
            else:
                progs.append((f"stage{s}_bwd", self._stage_bwd[s],
                              (p_s, x_in, x_out), dict(local_man)))
            x_in = x_out
        return progs

    def memory_manifest(self):
        """SPMD path: the base-engine manifest (the step programs are the
        base programs). Instruction-executor path: the per-stage jits are
        LOCAL programs, so the live param working set of any one program is
        the largest stage subtree, not the full tree — the manifest keeps the
        full tree for classification (every stage's leaves must classify as
        params) and declares the per-stage maximum for the model."""
        if self._spmd:
            return super().memory_manifest()
        from ...utils import hbm as _hbm
        stage_bytes = []
        for s in range(self.num_stages):
            leaves = jax.tree_util.tree_leaves(self._select_params(s))
            stage_bytes.append(sum(_hbm.leaf_signature(l)[2] for l in leaves))
        return {
            "classes": {"params": self.params},
            "geometry": {"kind": "pipeline_local",
                         "num_stages": int(self.num_stages),
                         "stage_param_bytes_max": max(stage_bytes, default=0)},
        }

    # ------------------------------------------------------------- blocked base API
    def forward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    def backward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    def step(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    # ------------------------------------------------------------- train/eval
    def _next_micro_batch(self, data_iter):
        batch = next(data_iter)
        if isinstance(batch, (tuple, list)):
            return tuple(self.shard_batch(b) for b in batch)
        return (self.shard_batch(batch),)

    def _stack_window(self, data_iter):
        """Pull the accumulation window's micro-batches and stack them on a leading
        M axis, sharded over ``data`` on the batch dim (dim 1) — the layout
        pipeline_apply streams through the scan."""
        xs, ys = [], []
        for _ in range(self.micro_batches):
            batch = next(data_iter)
            if not (isinstance(batch, (tuple, list)) and len(batch) >= 2):
                raise PipelineError(
                    "SPMD pipeline mode expects (inputs, labels) batches; pass "
                    '{"pipeline": {"spmd": false}} for the instruction executor')
            xs.append(np.asarray(batch[0]))
            ys.append(np.asarray(batch[1]))

        def put(a):
            spec = P(*([None, DATA_AXIS] + [None] * (a.ndim - 2)))
            return jax.device_put(a, NamedSharding(self.mesh, spec))

        return put(np.stack(xs)), put(np.stack(ys))

    def _train_batch_spmd(self, data_iter):
        """One optimizer step: the ENTIRE micro-batch window runs inside one jitted
        forward/backward (scan + ppermute over the pipe axis of the mesh); the base
        engine's fp16/ZeRO/monitoring machinery applies unchanged."""
        x, y = self._stack_window(data_iter)
        loss = DeepSpeedEngine.forward(self, x, y)
        DeepSpeedEngine.backward(self, loss)
        DeepSpeedEngine.step(self)
        self.agg_train_loss = loss
        return loss

    def train_batch(self, data_iter=None):
        """Run one full micro-batch window to an optimizer step (reference
        pipe/engine.py:229-303): the SPMD scan executor when routed there, else the
        1F1B instruction stream."""
        if data_iter is None:
            if self.training_dataloader is None:
                raise PipelineError("train_batch() requires a data iterator or training_data")
            if not hasattr(self, "_repeating_iter"):
                from ..dataloader import RepeatingLoader
                self._repeating_iter = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._repeating_iter
        if self._spmd:
            return self._train_batch_spmd(data_iter)

        if self.telemetry is not None:
            self.telemetry.on_step_begin(self.global_steps)
        # goodput: construction -> first train step is the init interval
        self._goodput_close_init()
        tracer = self.pipe_trace
        mb = self.micro_batches
        S = self.num_stages
        scheds = [schedule.TrainSchedule(micro_batches=mb, stages=S, stage_id=s)
                  for s in range(S)]
        streams = [list(iter(sc)) for sc in scheds]
        ring_size = [sc.num_pipe_buffers() for sc in scheds]  # see _assert_ring_bound

        act_in = [dict() for _ in range(S)]    # stage -> buffer_id -> input activation
        act_out = [dict() for _ in range(S)]   # stage -> buffer_id -> output activation
        dx_buf = [dict() for _ in range(S)]    # stage -> buffer_id -> input-grad to send back
        grad_in = [dict() for _ in range(S)]   # stage -> buffer_id -> received output-grad
        # Channels are keyed by (sending stage, micro-batch id): adjacent stages size their
        # buffer rings differently (num_pipe_buffers is per-stage), so receiver-local buffer
        # ids do NOT line up across stages. Micro-batch ids are globally consistent; each
        # stage forwards/retires/receives micro-batches strictly in order.
        chan_act = {}
        chan_grad = {}
        in_mb = [dict() for _ in range(S)]     # stage -> buffer_id -> micro-batch id
        labels_by_mb = {}
        fwd_count = [0] * S
        bwd_count = [0] * S
        recv_act_count = [0] * S
        recv_grad_count = [0] * S
        micro_losses = []
        grads_total: Optional[Dict[str, Any]] = None
        # fold the fp16 loss scale into the per-micro-batch factor (weak-spot fix:
        # stage backwards must produce loss-scaled grads for the overflow machinery
        # in _jit_apply_update to mean anything under fp16)
        scale = jnp.asarray(1.0 / mb, jnp.float32)
        if self.fp16_enabled():
            scale = scale * self.scaler_state.cur_scale

        breakdown = self.wall_clock_breakdown()
        _TIMER_BY_CMD = {
            schedule.LoadMicroBatch: "batch_input",
            schedule.ForwardPass: "forward_microstep",
            schedule.BackwardPass: "backward_microstep",
            schedule.SendActivation: "pipe_send_output",
            schedule.RecvActivation: "pipe_recv_input",
            schedule.SendGrad: "pipe_send_grad",
            schedule.RecvGrad: "pipe_recv_grad",
            schedule.OptimizerStep: "step_microstep",
        }
        if breakdown:
            self.timers("train_batch").start()

        def merge_grads(total, delta):
            if total is None:
                return dict(delta)
            merged = dict(total)
            for k, v in delta.items():
                merged[k] = (jax.tree_util.tree_map(lambda a, b: a + b, merged[k], v)
                             if k in merged else v)
            return merged

        def exec_cmd(s, cmd):
            nonlocal grads_total
            if isinstance(cmd, schedule.LoadMicroBatch):
                if s == 0:
                    batch = self._next_micro_batch(data_iter)
                    act_in[0][cmd.buffer_id] = batch[0]
                    in_mb[0][cmd.buffer_id] = fwd_count[0]
                    labels_by_mb[fwd_count[0]] = batch[1] if len(batch) > 1 else None
                # last stage: labels were stashed when stage 0 loaded this micro-batch
            elif isinstance(cmd, schedule.ForwardPass):
                x = act_in[s].pop(cmd.buffer_id)
                mb_id = in_mb[s][cmd.buffer_id]
                act_in[s][("saved", cmd.buffer_id)] = x
                if s < S - 1 or self.pipe_module.loss_fn is None:
                    act_out[s][cmd.buffer_id] = (mb_id, self._stage_fwd[s](self._select_params(s), x))
                fwd_count[s] += 1
            elif isinstance(cmd, schedule.SendActivation):
                mb_id, payload = act_out[s].pop(cmd.buffer_id)
                chan_act[(s, mb_id)] = payload
                _assert_ring_bound(chan_act, s, ring_size[s + 1], "activation")
            elif isinstance(cmd, schedule.RecvActivation):
                mb_id = recv_act_count[s]
                recv_act_count[s] += 1
                act_in[s][cmd.buffer_id] = chan_act.pop((s - 1, mb_id))
                in_mb[s][cmd.buffer_id] = mb_id
            elif isinstance(cmd, schedule.BackwardPass):
                x = act_in[s].pop(("saved", cmd.buffer_id))
                mb_id = in_mb[s].pop(cmd.buffer_id)
                if s == S - 1 and self.pipe_module.loss_fn is not None:
                    labels = labels_by_mb[mb_id]
                    loss, dparams, dx = self._stage_last_bwd(self._select_params(s), x, labels, scale)
                    micro_losses.append(loss)
                else:
                    g = grad_in[s].pop(cmd.buffer_id)
                    dparams, dx = self._stage_bwd[s](self._select_params(s), x, g)
                grads_total = merge_grads(grads_total, dparams)
                if s > 0:
                    dx_buf[s][cmd.buffer_id] = (mb_id, dx)
                bwd_count[s] += 1
            elif isinstance(cmd, schedule.SendGrad):
                mb_id, payload = dx_buf[s].pop(cmd.buffer_id)
                chan_grad[(s, mb_id)] = payload
                _assert_ring_bound(chan_grad, s, ring_size[s - 1], "grad")
            elif isinstance(cmd, schedule.RecvGrad):
                mb_id = recv_grad_count[s]
                recv_grad_count[s] += 1
                grad_in[s][cmd.buffer_id] = chan_grad.pop((s + 1, mb_id))
            elif isinstance(cmd, (schedule.ReduceTiedGrads, schedule.ReduceGrads)):
                pass  # tied grads summed in merge_grads; DP reduce emitted by XLA
            elif isinstance(cmd, schedule.OptimizerStep):
                if s == 0:
                    self._pipeline_optimizer_step(grads_total)

        def timed_exec(s, cmd):
            name = _TIMER_BY_CMD.get(type(cmd)) if breakdown else None
            if name is None:
                exec_cmd(s, cmd)
                return
            self.timers(name).start()
            exec_cmd(s, cmd)
            self.timers(name).stop()

        def trace_mb(s, cmd):
            # best-effort micro-batch attribution from the live buffer state
            # (read BEFORE exec_cmd mutates it; Load/Recv use their counters)
            if isinstance(cmd, schedule.LoadMicroBatch):
                return fwd_count[s]
            if isinstance(cmd, (schedule.ForwardPass, schedule.BackwardPass)):
                return in_mb[s].get(cmd.buffer_id)
            if isinstance(cmd, schedule.SendActivation):
                return (act_out[s].get(cmd.buffer_id) or (None,))[0]
            if isinstance(cmd, schedule.SendGrad):
                return (dx_buf[s].get(cmd.buffer_id) or (None,))[0]
            if isinstance(cmd, schedule.RecvActivation):
                return recv_act_count[s]
            if isinstance(cmd, schedule.RecvGrad):
                return recv_grad_count[s]
            return None

        def traced_exec(s, cmd, step_id):
            if tracer is None:
                timed_exec(s, cmd)
                return
            mb_id = trace_mb(s, cmd)
            t0 = time.perf_counter()
            timed_exec(s, cmd)
            tracer.record(s, step_id, cmd.name, mb_id,
                          getattr(cmd, "buffer_id", None), t0, time.perf_counter())

        if tracer is not None:
            tracer.begin_step(self.global_steps, "TrainSchedule", mb)
        self._run_streams(streams, traced_exec)
        goodput = tracer.end_step() if tracer is not None else None

        self.agg_train_loss = jnp.mean(jnp.stack(micro_losses)) if micro_losses else None
        self.global_steps += 1
        self.micro_steps += mb
        pending_losses = [self.agg_train_loss] if self.agg_train_loss is not None else None
        numerics_host = None
        if self.telemetry is not None:
            numerics_host = self.telemetry.end_step(
                self.global_steps, self.train_batch_size(),
                pending=pending_losses, numerics=self._pending_sentinel,
                schedule_goodput=goodput,
                run_goodput=self._goodput_scalars())
        elif self._pending_sentinel is not None:
            numerics_host = jax.device_get(self._pending_sentinel)
        if self._numerics is not None:
            self._commit_numerics(numerics_host,
                                  getattr(self, "_pipe_overflowed", False),
                                  pending_losses or [])
        self._goodput_close_train_step()
        if breakdown:
            self.timers("train_batch").stop()
            if self.global_steps % self.steps_per_print() == 0:
                # per-instruction wall-clock buckets (reference pipe/engine.py:964-984)
                self.timers.log(["batch_input", "forward_microstep", "backward_microstep",
                                 "pipe_send_output", "pipe_recv_input", "pipe_send_grad",
                                 "pipe_recv_grad", "step_microstep", "train_batch"],
                                reset=True)
        if self.global_steps == 1 or self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
        return self.agg_train_loss

    @staticmethod
    def _run_streams(streams, exec_cmd):
        """Execute per-stage instruction streams merged by step index. Within one
        merged step all Sends/Loads run before any Recv — the scheduling invariant
        that lets the reference's blocking p2p broadcasts rendezvous (its even/odd
        orderings serialize to exactly this). ``exec_cmd`` receives the merged
        step index so the pipeline tracer can stamp spans with their schedule
        position."""
        S = len(streams)
        for step_id in range(len(streams[0])):
            for s in range(S):
                for cmd in streams[s][step_id]:
                    if isinstance(cmd, _SEND_CMDS):
                        exec_cmd(s, cmd, step_id)
            for s in range(S):
                for cmd in streams[s][step_id]:
                    if not isinstance(cmd, _SEND_CMDS):
                        exec_cmd(s, cmd, step_id)

    def _select_params(self, stage_id):
        return {k: self.params[k] for k in self._stage_param_keys(stage_id)}

    def _pipeline_optimizer_step(self, grads_total):
        full_grads = {}
        for k, p in self.master_params.items():
            if grads_total is not None and k in grads_total:
                full_grads[k] = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                                       grads_total[k])
            else:
                full_grads[k] = jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, jnp.float32), p)
        hyper = self.optimizer.current_hyper()
        step = jnp.asarray(self.global_steps + 1 - self.skipped_steps, jnp.int32)
        outs = self._jit_apply_update(
            self.master_params, self.opt_state, self.scaler_state, full_grads,
            self.params, step, hyper)
        if self._sentinel_index is not None:
            (self.master_params, self.opt_state, self.scaler_state, self.params,
             overflow, self._last_grad_norm, self._pending_sentinel) = outs
        else:
            (self.master_params, self.opt_state, self.scaler_state, self.params,
             overflow, self._last_grad_norm) = outs
        self._pipe_overflowed = False
        if self.fp16_enabled() and bool(jax.device_get(overflow)):
            # jit already skipped the master update and backed off the scale; mirror
            # the host-side accounting (reference _take_model_step overflow branch)
            self._pipe_overflowed = True
            self.skipped_steps += 1
            logger.info("[deepspeed_tpu] OVERFLOW! Skipping pipeline step.")
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()

    def eval_batch(self, data_iter):
        """Forward-only evaluation executing the InferenceSchedule instruction stream
        through the per-stage jitted forwards (reference pipe/engine.py:305-372 runs
        InferenceSchedule through _exec_schedule; the two-buffer ring and the even/odd
        send/recv ordering of schedule.InferenceSchedule are preserved). SPMD mode
        evaluates the same jitted pipeline forward loss-only."""
        if self._spmd:
            x, y = self._stack_window(data_iter)
            self._goodput_begin_eval()
            loss = self._jit_eval(self.params, x, y)
            self._goodput_end_eval()
            return loss
        tracer = self.pipe_trace
        self._goodput_begin_eval()
        mb = self.micro_batches
        S = self.num_stages
        scheds = [schedule.InferenceSchedule(micro_batches=mb, stages=S, stage_id=s)
                  for s in range(S)]
        streams = [list(iter(sc)) for sc in scheds]
        ring_size = [sc.num_pipe_buffers() for sc in scheds]  # two-buffer ring

        act_in = [dict() for _ in range(S)]    # stage -> buffer_id -> input activation
        act_out = [dict() for _ in range(S)]   # stage -> buffer_id -> output activation
        chan_act = {}                           # (sending stage, mb id) -> payload
        in_mb = [dict() for _ in range(S)]     # stage -> buffer_id -> micro-batch id
        labels_by_mb = {}
        load_count = [0] * S
        recv_act_count = [0] * S
        micro_losses = []

        def exec_cmd(s, cmd):
            if isinstance(cmd, schedule.LoadMicroBatch):
                mb_id = load_count[s]
                load_count[s] += 1
                if s == 0:
                    batch = self._next_micro_batch(data_iter)
                    act_in[0][cmd.buffer_id] = batch[0]
                    in_mb[0][cmd.buffer_id] = mb_id
                    labels_by_mb[mb_id] = batch[1] if len(batch) > 1 else None
                # last stage: its LoadMicroBatch picks up the labels stage 0 stashed
                # (the reference's first/last stages share the data loader)
            elif isinstance(cmd, schedule.ForwardPass):
                x = act_in[s].pop(cmd.buffer_id)
                mb_id = in_mb[s].pop(cmd.buffer_id)
                if s == S - 1 and self.pipe_module.loss_fn is not None:
                    micro_losses.append(
                        self._stage_last_eval(self._select_params(s), x, labels_by_mb[mb_id]))
                else:
                    out = self._stage_fwd[s](self._select_params(s), x)
                    if s == S - 1:
                        micro_losses.append(out)
                    else:
                        act_out[s][cmd.buffer_id] = (mb_id, out)
            elif isinstance(cmd, schedule.SendActivation):
                mb_id, payload = act_out[s].pop(cmd.buffer_id)
                chan_act[(s, mb_id)] = payload
                _assert_ring_bound(chan_act, s, ring_size[s + 1], "activation")
            elif isinstance(cmd, schedule.RecvActivation):
                mb_id = recv_act_count[s]
                recv_act_count[s] += 1
                act_in[s][cmd.buffer_id] = chan_act.pop((s - 1, mb_id))
                in_mb[s][cmd.buffer_id] = mb_id

        def trace_mb(s, cmd):
            if isinstance(cmd, schedule.LoadMicroBatch):
                return load_count[s]
            if isinstance(cmd, schedule.ForwardPass):
                return in_mb[s].get(cmd.buffer_id)
            if isinstance(cmd, schedule.SendActivation):
                return (act_out[s].get(cmd.buffer_id) or (None,))[0]
            if isinstance(cmd, schedule.RecvActivation):
                return recv_act_count[s]
            return None

        def traced_exec(s, cmd, step_id):
            if tracer is None:
                exec_cmd(s, cmd)
                return
            mb_id = trace_mb(s, cmd)
            t0 = time.perf_counter()
            exec_cmd(s, cmd)
            tracer.record(s, step_id, cmd.name, mb_id,
                          getattr(cmd, "buffer_id", None), t0, time.perf_counter())

        if tracer is not None:
            tracer.begin_step(self.global_steps, "InferenceSchedule", mb, kind="eval")
        self._run_streams(streams, traced_exec)
        if tracer is not None:
            tracer.end_step()
        self._goodput_end_eval()
        return jnp.mean(jnp.stack(micro_losses))
