"""Pipeline engine: executes PipeSchedule instruction streams.

TPU-native re-design of ``deepspeed/runtime/pipe/engine.py`` (PipelineEngine l.45). The
instruction vocabulary and 1F1B stream are identical (schedule.py); what changes is the
execution model:

- The reference runs one process per stage, eager autograd per micro-batch, and blocking
  p2p broadcasts (pipe/p2p.py). Here a single controller executes every stage's stream
  (merged by step index) with **jitted per-stage forward/backward functions**; the p2p
  sends/recvs become buffer hand-offs whose device placement XLA manages, and each
  micro-batch is sharded over the mesh ``data`` axis so DP gradient reduction is emitted
  by XLA (no NCCL allreduce). Within one merged step all Sends execute before any Recv —
  the scheduling invariant that lets the reference's blocking broadcasts rendezvous.
- BackwardPass recomputes the stage forward inside the jitted VJP (activation
  checkpointing per stage — the JAX analog of the reference's retained autograd graphs
  per pipe buffer; SURVEY §7 "hard parts").
- Tied layers (TiedLayerSpec) share one parameter entry; their gradient contributions sum
  during the backward merge — ``ReduceTiedGrads`` (reference pipe/module.py:405-474)
  needs no separate collective.
- ``OptimizerStep`` reuses the base engine's jitted sharded update (ZeRO over ``data``).

``forward``/``backward``/``step`` are blocked in pipeline mode exactly like the reference
(pipe/engine.py:1034-1044): use ``train_batch``/``eval_batch``.

For *multi-chip pipe-axis* execution with homogeneous transformer stages, see
``parallel/pipeline_spmd.py`` (shard_map + ppermute inside one jit).
"""

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ...parallel.pipe.module import PipelineModule, TiedLayerSpec
from ...utils import log_dist, logger
from ..engine import DeepSpeedEngine
from . import schedule



def _assert_ring_bound(chan, src_stage, receiver_ring, direction):
    """The reference's per-stage buffer-ring memory contract
    (deepspeed/runtime/pipe/engine.py:133-148) as a tested invariant: payloads
    in flight from ``src_stage`` never exceed the RECEIVER's num_pipe_buffers()."""
    in_flight = sum(1 for (src, _) in chan if src == src_stage)
    assert in_flight <= receiver_ring, (
        f"stage {src_stage} {direction} channel holds {in_flight} payloads "
        f"> receiver num_pipe_buffers()={receiver_ring}")


class PipelineError(Exception):
    """Errors related to the use of deepspeed.PipelineEngine."""


_SEND_CMDS = (schedule.SendActivation, schedule.SendGrad, schedule.LoadMicroBatch)


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, args=None, model=None, optimizer=None, model_parameters=None,
                 training_data=None, lr_scheduler=None, mpu=None, dist_init_required=None,
                 collate_fn=None, config_params=None, mesh=None):
        assert isinstance(model, PipelineModule), "model must be a PipelineModule"
        self.pipe_module = model
        self.num_stages = model.num_stages

        canonical, layer_keys = self._canonicalize_params(model, model_parameters)
        self._layer_keys = layer_keys

        super().__init__(args=args, model=self._whole_model_fn, optimizer=optimizer,
                         model_parameters=canonical, training_data=training_data,
                         lr_scheduler=lr_scheduler, mpu=None, dist_init_required=dist_init_required,
                         collate_fn=collate_fn, config_params=config_params, mesh=mesh)
        assert self._offload is None, \
            "cpu_offload is not supported with pipeline parallelism (the pipeline " \
            "optimizer step runs on device; reference pairs offload with plain ZeRO-2 only)"

        self.micro_batches = self.gradient_accumulation_steps()
        self._compile_stage_fns()
        self.agg_train_loss = None
        log_dist(f"PipelineEngine: {self.num_stages} stages, parts={model.parts}", ranks=[0])

    # ------------------------------------------------------------- params
    def _canonicalize_params(self, module: PipelineModule, model_parameters):
        """Per-layer params list → dict keyed by layer id; tied layers collapse onto one
        'tied::<key>' entry (shared storage, summed grads)."""
        if model_parameters is None:
            raise ValueError("PipelineEngine requires model_parameters: the list returned "
                             "by PipelineModule.init_params(rng, sample_input)")
        assert len(model_parameters) == module.num_layers(), \
            f"expected {module.num_layers()} per-layer param entries"
        canonical: Dict[str, Any] = {}
        layer_keys: List[Optional[str]] = []
        for idx, (spec, p) in enumerate(zip(module._layer_specs, model_parameters)):
            if p is None:
                layer_keys.append(None)
                continue
            key = f"tied::{spec.key}" if isinstance(spec, TiedLayerSpec) else f"layer_{idx}"
            if key not in canonical:
                canonical[key] = p
            layer_keys.append(key)
        return canonical, layer_keys

    def _apply_layer(self, idx: int, params, x):
        layer = self.pipe_module._built_layers[idx]
        key = self._layer_keys[idx]
        spec = self.pipe_module._layer_specs[idx]
        if key is None:
            return layer(x)
        fwd = spec.forward_fn if isinstance(spec, TiedLayerSpec) and spec.forward_fn else None
        if fwd is not None:
            return fwd(layer, params[key], x)
        return layer.apply(params[key], x)

    def _whole_model_fn(self, params, *batch):
        """Sequential full-model apply (eval path / reference semantics)."""
        x = batch[0]
        for idx in range(self.pipe_module.num_layers()):
            x = self._apply_layer(idx, params, x)
        if self.pipe_module.loss_fn is not None and len(batch) > 1:
            return self.pipe_module.loss_fn(x, batch[1])
        return x

    # ------------------------------------------------------------- stage functions
    def _stage_fn(self, stage_id: int) -> Callable:
        lo, hi = self.pipe_module.parts[stage_id], self.pipe_module.parts[stage_id + 1]
        interval = self.pipe_module.activation_checkpoint_interval

        def run_range(start, end):
            def range_fn(stage_params, x):
                for idx in range(start, end):
                    x = self._apply_layer(idx, stage_params, x)
                return x
            return range_fn

        if interval and interval > 0:
            # remat each interval-sized chunk (reference PipelineModule.forward,
            # pipe/module.py:292-346: exec_range_func wrapped per interval)
            from ..activation_checkpointing.checkpointing import checkpoint_wrapper
            chunks = [(s, min(s + interval, hi)) for s in range(lo, hi, interval)]

            def fn(stage_params, x):
                for start, end in chunks:
                    x = checkpoint_wrapper(run_range(start, end))(stage_params, x)
                return x
            return fn

        return run_range(lo, hi)

    def _stage_param_keys(self, stage_id: int) -> List[str]:
        lo, hi = self.pipe_module.parts[stage_id], self.pipe_module.parts[stage_id + 1]
        keys = []
        for idx in range(lo, hi):
            k = self._layer_keys[idx]
            if k is not None and k not in keys:
                keys.append(k)
        return keys

    def _compile_stage_fns(self):
        self._stage_fwd = []
        self._stage_bwd = []
        self._stage_last_bwd = None
        loss_fn = self.pipe_module.loss_fn
        for s in range(self.num_stages):
            fn = self._stage_fn(s)
            self._stage_fwd.append(jax.jit(fn))

            def bwd(stage_params, x, g, _fn=fn):
                _, vjp = jax.vjp(_fn, stage_params, x)
                dparams, dx = vjp(g)
                return dparams, dx

            self._stage_bwd.append(jax.jit(bwd))

            if s == self.num_stages - 1 and loss_fn is not None:
                def last_bwd(stage_params, x, labels, scale, _fn=fn):
                    # ``scale`` folds 1/micro_batches AND the fp16 loss scale: grads
                    # leave every stage loss-scaled (the dx flowing upstream carries
                    # the factor), and _jit_apply_update unscales by cur_scale with
                    # the overflow check intact (reference loss_scaler.py:51-53).
                    def f(p, xx):
                        return loss_fn(_fn(p, xx), labels) * scale
                    loss, (dparams, dx) = jax.value_and_grad(f, argnums=(0, 1))(stage_params, x)
                    return loss / scale, dparams, dx

                self._stage_last_bwd = jax.jit(last_bwd)

                def last_eval(stage_params, x, labels, _fn=fn):
                    return loss_fn(_fn(stage_params, x), labels)

                self._stage_last_eval = jax.jit(last_eval)

    # ------------------------------------------------------------- blocked base API
    def forward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    def backward(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    def step(self, *args, **kwargs):
        raise PipelineError("Only train_batch() is accessible in pipeline mode.")

    # ------------------------------------------------------------- train/eval
    def _next_micro_batch(self, data_iter):
        batch = next(data_iter)
        if isinstance(batch, (tuple, list)):
            return tuple(self.shard_batch(b) for b in batch)
        return (self.shard_batch(batch),)

    def train_batch(self, data_iter=None):
        """Run one full 1F1B schedule over gradient_accumulation_steps micro-batches
        (reference pipe/engine.py:229-303)."""
        if data_iter is None:
            if self.training_dataloader is None:
                raise PipelineError("train_batch() requires a data iterator or training_data")
            if not hasattr(self, "_repeating_iter"):
                from ..dataloader import RepeatingLoader
                self._repeating_iter = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._repeating_iter

        mb = self.micro_batches
        S = self.num_stages
        scheds = [schedule.TrainSchedule(micro_batches=mb, stages=S, stage_id=s)
                  for s in range(S)]
        streams = [list(iter(sc)) for sc in scheds]
        ring_size = [sc.num_pipe_buffers() for sc in scheds]  # see _assert_ring_bound

        act_in = [dict() for _ in range(S)]    # stage -> buffer_id -> input activation
        act_out = [dict() for _ in range(S)]   # stage -> buffer_id -> output activation
        dx_buf = [dict() for _ in range(S)]    # stage -> buffer_id -> input-grad to send back
        grad_in = [dict() for _ in range(S)]   # stage -> buffer_id -> received output-grad
        # Channels are keyed by (sending stage, micro-batch id): adjacent stages size their
        # buffer rings differently (num_pipe_buffers is per-stage), so receiver-local buffer
        # ids do NOT line up across stages. Micro-batch ids are globally consistent; each
        # stage forwards/retires/receives micro-batches strictly in order.
        chan_act = {}
        chan_grad = {}
        in_mb = [dict() for _ in range(S)]     # stage -> buffer_id -> micro-batch id
        labels_by_mb = {}
        fwd_count = [0] * S
        bwd_count = [0] * S
        recv_act_count = [0] * S
        recv_grad_count = [0] * S
        micro_losses = []
        grads_total: Optional[Dict[str, Any]] = None
        # fold the fp16 loss scale into the per-micro-batch factor (weak-spot fix:
        # stage backwards must produce loss-scaled grads for the overflow machinery
        # in _jit_apply_update to mean anything under fp16)
        scale = jnp.asarray(1.0 / mb, jnp.float32)
        if self.fp16_enabled():
            scale = scale * self.scaler_state.cur_scale

        breakdown = self.wall_clock_breakdown()
        _TIMER_BY_CMD = {
            schedule.LoadMicroBatch: "batch_input",
            schedule.ForwardPass: "forward_microstep",
            schedule.BackwardPass: "backward_microstep",
            schedule.SendActivation: "pipe_send_output",
            schedule.RecvActivation: "pipe_recv_input",
            schedule.SendGrad: "pipe_send_grad",
            schedule.RecvGrad: "pipe_recv_grad",
            schedule.OptimizerStep: "step_microstep",
        }
        if breakdown:
            self.timers("train_batch").start()

        def merge_grads(total, delta):
            if total is None:
                return dict(delta)
            merged = dict(total)
            for k, v in delta.items():
                merged[k] = (jax.tree_util.tree_map(lambda a, b: a + b, merged[k], v)
                             if k in merged else v)
            return merged

        def exec_cmd(s, cmd):
            nonlocal grads_total
            if isinstance(cmd, schedule.LoadMicroBatch):
                if s == 0:
                    batch = self._next_micro_batch(data_iter)
                    act_in[0][cmd.buffer_id] = batch[0]
                    in_mb[0][cmd.buffer_id] = fwd_count[0]
                    labels_by_mb[fwd_count[0]] = batch[1] if len(batch) > 1 else None
                # last stage: labels were stashed when stage 0 loaded this micro-batch
            elif isinstance(cmd, schedule.ForwardPass):
                x = act_in[s].pop(cmd.buffer_id)
                mb_id = in_mb[s][cmd.buffer_id]
                act_in[s][("saved", cmd.buffer_id)] = x
                if s < S - 1 or self.pipe_module.loss_fn is None:
                    act_out[s][cmd.buffer_id] = (mb_id, self._stage_fwd[s](self._select_params(s), x))
                fwd_count[s] += 1
            elif isinstance(cmd, schedule.SendActivation):
                mb_id, payload = act_out[s].pop(cmd.buffer_id)
                chan_act[(s, mb_id)] = payload
                _assert_ring_bound(chan_act, s, ring_size[s + 1], "activation")
            elif isinstance(cmd, schedule.RecvActivation):
                mb_id = recv_act_count[s]
                recv_act_count[s] += 1
                act_in[s][cmd.buffer_id] = chan_act.pop((s - 1, mb_id))
                in_mb[s][cmd.buffer_id] = mb_id
            elif isinstance(cmd, schedule.BackwardPass):
                x = act_in[s].pop(("saved", cmd.buffer_id))
                mb_id = in_mb[s].pop(cmd.buffer_id)
                if s == S - 1 and self.pipe_module.loss_fn is not None:
                    labels = labels_by_mb[mb_id]
                    loss, dparams, dx = self._stage_last_bwd(self._select_params(s), x, labels, scale)
                    micro_losses.append(loss)
                else:
                    g = grad_in[s].pop(cmd.buffer_id)
                    dparams, dx = self._stage_bwd[s](self._select_params(s), x, g)
                grads_total = merge_grads(grads_total, dparams)
                if s > 0:
                    dx_buf[s][cmd.buffer_id] = (mb_id, dx)
                bwd_count[s] += 1
            elif isinstance(cmd, schedule.SendGrad):
                mb_id, payload = dx_buf[s].pop(cmd.buffer_id)
                chan_grad[(s, mb_id)] = payload
                _assert_ring_bound(chan_grad, s, ring_size[s - 1], "grad")
            elif isinstance(cmd, schedule.RecvGrad):
                mb_id = recv_grad_count[s]
                recv_grad_count[s] += 1
                grad_in[s][cmd.buffer_id] = chan_grad.pop((s + 1, mb_id))
            elif isinstance(cmd, (schedule.ReduceTiedGrads, schedule.ReduceGrads)):
                pass  # tied grads summed in merge_grads; DP reduce emitted by XLA
            elif isinstance(cmd, schedule.OptimizerStep):
                if s == 0:
                    self._pipeline_optimizer_step(grads_total)

        def timed_exec(s, cmd):
            name = _TIMER_BY_CMD.get(type(cmd)) if breakdown else None
            if name is None:
                exec_cmd(s, cmd)
                return
            self.timers(name).start()
            exec_cmd(s, cmd)
            self.timers(name).stop()

        self._run_streams(streams, timed_exec)

        self.agg_train_loss = jnp.mean(jnp.stack(micro_losses)) if micro_losses else None
        self.global_steps += 1
        self.micro_steps += mb
        if breakdown:
            self.timers("train_batch").stop()
            if self.global_steps % self.steps_per_print() == 0:
                # per-instruction wall-clock buckets (reference pipe/engine.py:964-984)
                self.timers.log(["batch_input", "forward_microstep", "backward_microstep",
                                 "pipe_send_output", "pipe_recv_input", "pipe_send_grad",
                                 "pipe_recv_grad", "step_microstep", "train_batch"],
                                reset=True)
        if self.global_steps == 1 or self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)
        return self.agg_train_loss

    @staticmethod
    def _run_streams(streams, exec_cmd):
        """Execute per-stage instruction streams merged by step index. Within one
        merged step all Sends/Loads run before any Recv — the scheduling invariant
        that lets the reference's blocking p2p broadcasts rendezvous (its even/odd
        orderings serialize to exactly this)."""
        S = len(streams)
        for step_id in range(len(streams[0])):
            for s in range(S):
                for cmd in streams[s][step_id]:
                    if isinstance(cmd, _SEND_CMDS):
                        exec_cmd(s, cmd)
            for s in range(S):
                for cmd in streams[s][step_id]:
                    if not isinstance(cmd, _SEND_CMDS):
                        exec_cmd(s, cmd)

    def _select_params(self, stage_id):
        return {k: self.params[k] for k in self._stage_param_keys(stage_id)}

    def _pipeline_optimizer_step(self, grads_total):
        full_grads = {}
        for k, p in self.master_params.items():
            if grads_total is not None and k in grads_total:
                full_grads[k] = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                                       grads_total[k])
            else:
                full_grads[k] = jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, jnp.float32), p)
        hyper = self.optimizer.current_hyper()
        step = jnp.asarray(self.global_steps + 1 - self.skipped_steps, jnp.int32)
        (self.master_params, self.opt_state, self.scaler_state, self.params,
         overflow, self._last_grad_norm) = self._jit_apply_update(
            self.master_params, self.opt_state, self.scaler_state, full_grads,
            self.params, step, hyper)
        if self.fp16_enabled() and bool(jax.device_get(overflow)):
            # jit already skipped the master update and backed off the scale; mirror
            # the host-side accounting (reference _take_model_step overflow branch)
            self.skipped_steps += 1
            logger.info("[deepspeed_tpu] OVERFLOW! Skipping pipeline step.")
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()

    def eval_batch(self, data_iter):
        """Forward-only evaluation executing the InferenceSchedule instruction stream
        through the per-stage jitted forwards (reference pipe/engine.py:305-372 runs
        InferenceSchedule through _exec_schedule; the two-buffer ring and the even/odd
        send/recv ordering of schedule.InferenceSchedule are preserved)."""
        mb = self.micro_batches
        S = self.num_stages
        scheds = [schedule.InferenceSchedule(micro_batches=mb, stages=S, stage_id=s)
                  for s in range(S)]
        streams = [list(iter(sc)) for sc in scheds]
        ring_size = [sc.num_pipe_buffers() for sc in scheds]  # two-buffer ring

        act_in = [dict() for _ in range(S)]    # stage -> buffer_id -> input activation
        act_out = [dict() for _ in range(S)]   # stage -> buffer_id -> output activation
        chan_act = {}                           # (sending stage, mb id) -> payload
        in_mb = [dict() for _ in range(S)]     # stage -> buffer_id -> micro-batch id
        labels_by_mb = {}
        load_count = [0] * S
        recv_act_count = [0] * S
        micro_losses = []

        def exec_cmd(s, cmd):
            if isinstance(cmd, schedule.LoadMicroBatch):
                mb_id = load_count[s]
                load_count[s] += 1
                if s == 0:
                    batch = self._next_micro_batch(data_iter)
                    act_in[0][cmd.buffer_id] = batch[0]
                    in_mb[0][cmd.buffer_id] = mb_id
                    labels_by_mb[mb_id] = batch[1] if len(batch) > 1 else None
                # last stage: its LoadMicroBatch picks up the labels stage 0 stashed
                # (the reference's first/last stages share the data loader)
            elif isinstance(cmd, schedule.ForwardPass):
                x = act_in[s].pop(cmd.buffer_id)
                mb_id = in_mb[s].pop(cmd.buffer_id)
                if s == S - 1 and self.pipe_module.loss_fn is not None:
                    micro_losses.append(
                        self._stage_last_eval(self._select_params(s), x, labels_by_mb[mb_id]))
                else:
                    out = self._stage_fwd[s](self._select_params(s), x)
                    if s == S - 1:
                        micro_losses.append(out)
                    else:
                        act_out[s][cmd.buffer_id] = (mb_id, out)
            elif isinstance(cmd, schedule.SendActivation):
                mb_id, payload = act_out[s].pop(cmd.buffer_id)
                chan_act[(s, mb_id)] = payload
                _assert_ring_bound(chan_act, s, ring_size[s + 1], "activation")
            elif isinstance(cmd, schedule.RecvActivation):
                mb_id = recv_act_count[s]
                recv_act_count[s] += 1
                act_in[s][cmd.buffer_id] = chan_act.pop((s - 1, mb_id))
                in_mb[s][cmd.buffer_id] = mb_id

        self._run_streams(streams, exec_cmd)
        return jnp.mean(jnp.stack(micro_losses))
