"""Activation checkpointing (rematerialization) with partitioned / host-offloaded
saveables and deterministic RNG.

TPU-native analog of ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(746 LoC, Megatron-derived). The reference re-ran forward in backward with exact
CPU+CUDA RNG restore (CudaRNGStatesTracker, l.147-223), optionally narrowed saved
input activations to 1/mp_size per rank (l.265-311) and moved them to CPU
(``PA_TO_CPU``, l.370-413). Under JAX each concern collapses into existing machinery:

- recompute-in-backward       → ``jax.checkpoint`` (this module adds the config layer)
- exact RNG restore           → free: PRNG keys are explicit values, so the remat
                                replay is bit-identical by construction; the
                                ``RNGTracker`` here exists for Megatron-API parity
- partition_activations       → sharding constraints on the wrapped function's inputs
                                over the ``model`` mesh axis; GSPMD all-gathers them
                                back in backward exactly like l.281-311
- cpu_checkpointing (PA_TO_CPU) → ``save_and_offload_only_these_names`` policy moving
                                named residuals to ``pinned_host`` memory
- contiguous_memory/profile   → accepted for config parity; XLA owns memory layout,
                                profiling maps to named-scope annotations
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ...utils import logger

# Name tag for residuals this module saves/offloads.
_ACT_NAME = "ds_activation"

# module-level config, set by configure() (reference checkpointing.py:654-700)
_config = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize": False,
    "profile": False,
    "model_axis": "model",
    "mesh": None,
    "mesh_explicit": False,
    "configured": False,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None, mesh=None, model_axis: Optional[str] = None):
    """Configure the module (reference checkpointing.py:654-700). Accepts either a
    DeepSpeedConfig (uses its activation_checkpointing block) or explicit flags."""
    if deepspeed_config is not None:
        ac = deepspeed_config.activation_checkpointing_config
        _config["partition_activations"] = ac.partition_activations
        _config["cpu_checkpointing"] = ac.cpu_checkpointing
        _config["contiguous_memory_optimization"] = ac.contiguous_memory_optimization
        _config["number_checkpoints"] = ac.number_checkpoints
        _config["synchronize"] = ac.synchronize_checkpoint_boundary
        _config["profile"] = ac.profile
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize),
                     ("profile", profile)):
        if val is not None:
            _config[key] = val
    if mesh is not None:
        _config["mesh"] = mesh
        _config["mesh_explicit"] = True
    if model_axis is not None:
        _config["model_axis"] = model_axis
    _config["configured"] = True
    logger.info(f"[deepspeed_tpu] activation checkpointing configured: "
                f"partition={_config['partition_activations']} "
                f"cpu={_config['cpu_checkpointing']} num={_config['number_checkpoints']}")


def set_default_mesh(mesh, model_axis: Optional[str] = None):
    """Publish a mesh for the partition constraint without flipping any flags or marking
    the module configured. The engine calls this so a later Megatron-style
    ``configure(partition_activations=True)`` — which has no mesh parameter — still
    shards saveables over the model axis instead of silently no-opping. Latest engine
    wins (a discarded engine's mesh must not linger), but a mesh passed explicitly to
    ``configure(mesh=...)`` is never overridden."""
    if not _config.get("mesh_explicit"):
        _config["mesh"] = mesh
        if model_axis is not None:
            _config["model_axis"] = model_axis


def is_configured() -> bool:
    return _config["configured"]


def cpu_checkpointing_enabled() -> bool:
    return bool(_config["cpu_checkpointing"])


def reset():
    """Reference checkpointing.py reset() dropped the contiguous buffers; here it
    just restores defaults."""
    _config.update(partition_activations=False, cpu_checkpointing=False,
                   contiguous_memory_optimization=False, number_checkpoints=None,
                   synchronize=False, profile=False, mesh=None, model_axis="model",
                   configured=False, mesh_explicit=False)


def _offload_policy():
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=[_ACT_NAME],
        offload_src="device", offload_dst="pinned_host")


def _partition_constraint(x: jnp.ndarray):
    """Shard a saveable over the model axis along its largest divisible dim
    (reference narrowed saved activations to 1/mp_size per rank, l.265-311).
    Inside jit, GSPMD inserts the gather on the backward replay."""
    mesh = _config["mesh"]
    axis = _config["model_axis"]
    if mesh is None or axis not in mesh.shape or mesh.shape[axis] <= 1 or x.ndim == 0:
        return x
    mp = mesh.shape[axis]
    from jax.sharding import NamedSharding, PartitionSpec as P
    for dim in sorted(range(x.ndim), key=lambda d: -x.shape[d]):
        if x.shape[dim] % mp == 0:
            spec = [None] * x.ndim
            spec[dim] = axis
            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))
    return x


def _flash_policy(exclude="qkv", keep_qkv=False):
    """Replay-free attention remat policies: save the flash kernel's named
    residuals (out, lse) plus no-batch-dims dots, minus a width-signature-chosen
    exclusion that funds the attention saves in HBM.

    Measured at GPT-2 1.5B, batch 8, one v5e (PERF.md round-5 remat table):
    'dots' replays the flash fwd kernel in backward (the custom_vjp residuals
    are not dots) and plain 'dots+attn' overshoots HBM by ~60 MB. Exclusions by
    2-D-rhs width signature (unique among the transformer's dots):
    - "qkv" (policy 'flash'): rhs [E, 3E] — frees 3E per layer (3.7 GB) but the
      replay re-runs the widest projection;
    - "square" (policy 'dots+attn-lean'): rhs [E, E], the attention output
      projection — frees E per layer (1.25 GB) and the replay is one cheap dot
      whose input (attn_out) is itself saved.

    Dot classification is tag-first: attention call sites announce their dots by
    emitting ``checkpoint_name(x, "ds_dot:qkv")`` / ``"ds_dot:proj"`` on the
    dot's INPUT immediately before the dot (gpt2 ``_attention`` and the fused
    transformer kernel do). The jaxpr records equations in trace order, so the
    announcement reaches this policy before its dot_general; once ANY ``ds_dot``
    tag is seen in a trace the width heuristic below is OFF and only announced
    dots can be excluded — a square MoE expert or a 3E-wide vocab head in a
    tagged model can no longer be misclassified.

    UNTAGGED FALLBACK: models that never announce keep the pure shape-based
    classification, which is only sound when each width signature is UNIQUE
    among the model's dots. Each returned policy instance tracks the distinct
    (contracted, out) rhs shapes it excludes across its trace and raises instead
    of misclassifying: a second distinct shape in the same exclusion class, or a
    square width that disagrees with the qkv-implied embed width, is an error
    directing the caller to tags or an explicit policy."""
    names = jax.checkpoint_policies.save_only_these_names("attn_out", "attn_lse")
    # per-instance (== per checkpoint_wrapper call, i.e. per trace) signature log:
    # class name -> set of distinct (contracted, out_w) rhs shapes observed. qkv
    # signatures are recorded even when kept so the square check can cross-validate
    # against the qkv-implied embed width.
    seen = {"qkv": set(), "square": set()}
    # tag-gating state: 'tagged' flips on the first ds_dot announcement; each
    # announcement queues (class, input-shape) until its dot_general consumes it
    # (shape-matched so unrelated interleaved dots pass through untouched).
    tag_state = {"tagged": False, "pending": []}

    def _record(cls, shape, excluding):
        seen[cls].add(shape)
        if excluding and len(seen[cls]) > 1:
            raise ValueError(
                f"remat policy width-signature collision: {sorted(seen[cls])} both "
                f"classify as the '{cls}' exclusion — the shape heuristic cannot "
                f"tell them apart, so one would silently lose its save. Pass an "
                f"explicit jax.checkpoint_policies callable (or use 'dots+attn') "
                f"for this model.")
        if exclude == "square" and seen["qkv"] and seen["square"]:
            e_widths = {c for c, _ in seen["qkv"]}
            for e_sq, _ in seen["square"]:
                if e_sq not in e_widths:
                    raise ValueError(
                        f"remat policy width-signature collision: square dot "
                        f"[{e_sq}, {e_sq}] does not match the fused-qkv embed "
                        f"width(s) {sorted(e_widths)}, so it is not the attention "
                        f"output projection (an MoE/router square?) and would "
                        f"silently lose its save. Pass an explicit "
                        f"jax.checkpoint_policies callable (or use 'dots+attn') "
                        f"for this model.")

    def eff_policy(prim, *avals, **params):
        if names(prim, *avals, **params):
            return True
        pname = getattr(prim, "name", "")
        if pname == "name":
            tag = str(params.get("name", ""))
            if tag.startswith("ds_dot:"):
                tag_state["tagged"] = True
                cls = tag.split(":", 2)[1]
                shape = tuple(getattr(avals[0], "shape", ())) if avals else ()
                tag_state["pending"].append((cls, shape))
            return False
        if pname != "dot_general":
            return False
        (lc, rc), (lb, rb) = params["dimension_numbers"]
        if lb or rb:
            return False
        if tag_state["tagged"]:
            # tag-gated mode: only announced dots may be excluded. The pending
            # announcement is consumed by the first dot whose lhs matches the
            # tagged input's shape (trace order puts it right after the tag).
            pending = tag_state["pending"]
            lhs_shape = tuple(getattr(avals[0], "shape", ())) if avals else ()
            if pending and pending[0][1] == lhs_shape:
                cls, _ = pending.pop(0)
                if cls == "qkv" and not keep_qkv:
                    return False  # fused-qkv projection: recompute, don't save
                if cls == "proj" and exclude == "square":
                    return False  # attn output projection: recompute from attn_out
            return True
        if len(avals) >= 2 and getattr(avals[1], "ndim", 0) == 2 and len(rc) == 1:
            rhs = avals[1]
            contracted, out_w = rhs.shape[rc[0]], rhs.shape[1 - rc[0]]
            if out_w == 3 * contracted:
                _record("qkv", (contracted, out_w), excluding=not keep_qkv)
                if not keep_qkv:
                    return False  # fused-qkv projection: recompute, don't save
            if exclude == "square" and out_w == contracted:
                _record("square", (contracted, out_w), excluding=True)
                return False  # attention output projection: recompute from attn_out
        return True

    return eff_policy


def checkpoint_wrapper(fn, policy=None):
    """Wrap ``fn(*args)`` so its forward is rematerialized in backward, honoring the
    configured saveable placement. The TPU analog of CheckpointFunction
    (reference checkpointing.py:314-576).

    ``policy`` selects what escapes recompute: None saves only the block inputs (full
    remat, the reference's semantics); ``"dots"`` additionally saves matmul outputs
    (``dots_with_no_batch_dims_saveable``) so backward replays only cheap elementwise
    ops — the sweet spot on TPU where HBM is larger relative to flops than the
    reference's V100s and full recompute wastes MXU cycles. A configured
    ``checkpoint_in_cpu`` overrides ``policy`` with the host-offload policy."""

    @functools.wraps(fn)
    def inner(*args):
        # Tag+place the block inputs: they are the residuals jax.checkpoint saves.
        def placed(*inner_args):
            processed = []
            for a in inner_args:
                if isinstance(a, jnp.ndarray) and jnp.issubdtype(a.dtype, jnp.inexact):
                    if _config["cpu_checkpointing"]:
                        a = checkpoint_name(a, _ACT_NAME)
                    if _config["partition_activations"]:
                        a = _partition_constraint(a)
                processed.append(a)
            return fn(*processed)

        if _config["cpu_checkpointing"]:
            eff_policy = _offload_policy()
        elif policy == "dots":
            eff_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif policy == "attn":
            # save only attention OUTPUTS (tagged "attn_out"/"attn_lse" by the
            # models): backward skips replaying the flash kernel — the priciest
            # recompute — for one [B, T, E] + one [B, H, T] residual per layer
            eff_policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_lse")
        elif policy == "dots+attn":
            # dots AND the flash kernel's (out, lse): backward replays ONLY cheap
            # elementwise ops (layernorm/gelu/adds) — the kernel's own residuals
            # (q,k,v) are saved dots, out/lse are the named saves, so the flash
            # bwd kernels run with zero fwd-kernel replay. The extra HBM over
            # 'dots' is one [B,T,E] + one [B,H,T] per layer (~3% of the dots set).
            eff_policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("attn_out", "attn_lse"))
        elif policy == "flash":
            eff_policy = _flash_policy()
        elif policy == "dots+attn-lean":
            # dots+attn minus the SQUARE-rhs dots (the attention output
            # projection, rhs [E, E]): its replay is ONE cheap dot from the
            # saved attn_out, and dropping the save frees a [B, T, E] per layer
            # (1.25 GB at 1.5B/batch 8) — the margin that lets the replay-free
            # attention saves fit in HBM (see PERF.md round-5 remat table)
            eff_policy = _flash_policy(exclude="square", keep_qkv=True)
        elif policy is None or callable(policy):
            eff_policy = policy
        else:
            raise ValueError(f"unknown remat policy {policy!r}: expected None, 'dots', "
                             f"'attn', 'dots+attn', 'dots+attn-lean', 'flash', or a "
                             f"jax.checkpoint_policies callable")
        ckpt = jax.checkpoint(placed, policy=eff_policy)
        if _config["profile"]:
            with jax.named_scope("ds_activation_checkpoint"):
                return ckpt(*args)
        return ckpt(*args)

    return inner


def checkpoint(function, *args):
    """Reference-style call: ``checkpoint(run_function, *args)``
    (checkpointing.py:739-746)."""
    return checkpoint_wrapper(function)(*args)


# ---------------------------------------------------------------------------
# RNG parity API (reference CudaRNGStatesTracker, checkpointing.py:147-223).
# JAX PRNG keys are explicit, so remat replay is deterministic with zero effort;
# this tracker exists so Megatron-style callers keep working.
# ---------------------------------------------------------------------------

class RNGTracker:
    """Named PRNG streams. ``fork(name)`` returns a fresh subkey each call;
    inside a remat replay the same sequence is regenerated bit-identically
    because the stream state is a pure value captured in the trace."""

    def __init__(self):
        self._keys = {}

    def reset(self):
        self._keys = {}

    def get_states(self):
        return dict(self._keys)

    def set_states(self, states):
        self._keys = dict(states)

    def add(self, name: str, seed: int):
        if name in self._keys:
            raise ValueError(f"RNG state {name} already exists")
        self._keys[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        if name not in self._keys:
            raise KeyError(f"RNG state {name} not added")
        self._keys[name], sub = jax.random.split(self._keys[name])
        return sub


_RNG_TRACKER = RNGTracker()


def get_rng_tracker() -> RNGTracker:
    return _RNG_TRACKER


# reference alias (checkpointing.py:218)
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed: int, axis: Optional[str] = None):
    """Per-model-parallel-rank PRNG key (reference model_parallel_cuda_manual_seed,
    checkpointing.py:223-262): dropout must differ across TP ranks while staying
    reproducible. Call inside shard_map/jit with the mesh axis bound; outside a
    bound axis it returns the base key."""
    key = jax.random.PRNGKey(seed)
    axis = axis or _config["model_axis"]
    try:
        idx = jax.lax.axis_index(axis)
    except NameError:
        return key
    return jax.random.fold_in(key, idx)


def model_parallel_cuda_manual_seed(seed: int):
    """Parity shim: seeds the tracker's default streams (reference l.223-262)."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
    _RNG_TRACKER.add("data-parallel-rng", seed)
