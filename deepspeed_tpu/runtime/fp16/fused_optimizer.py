"""Standalone mixed-precision optimizer wrapper (non-ZeRO path).

TPU-native analog of ``deepspeed/runtime/fp16/fused_optimizer.py`` (FP16_Optimizer,
l.17-429): fp32 master weights, loss-scaled backward, overflow check → skip step,
dynamic loss scale. The reference flattened params into one fused fp32 buffer
(l.48-66) because apex kernels wanted contiguous memory; under XLA a pytree of
arrays compiles to the same fused update, so the "fused" and "unfused" variants
share this implementation and differ only in the inner update rule they host.

The engine embeds this logic directly in its jitted step (runtime/engine.py
apply_update); this class is the *user-facing* wrapper for custom training loops:

    opt = FP16_Optimizer(params, optimizer="adam", dynamic_loss_scale=True)
    loss, grads = opt.backward(loss_fn, params16, batch)   # scaled grad
    params16 = opt.step(grads)                             # new compute-dtype params

Everything (overflow select, scaler update, master update) runs in ONE jitted call
with donated state — step-skip costs no host round-trip (SURVEY §7 hard part).
"""

import types
from collections import OrderedDict
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ...ops import adam as adam_opt
from ...ops import lamb as lamb_opt
from ...utils import logger
from ..utils import clip_grads_by_global_norm, detect_overflow
from . import loss_scaler as ls


class FP16_Optimizer:
    """Mixed-precision wrapper around an inner update rule (reference l.17).

    ``optimizer``: "adam" | "adamw" | "lamb" or a custom ``(grads, state, master,
    step, hyper) -> (new_master, new_state)`` callable plus ``init_state`` fn.
    """

    def __init__(self,
                 init_params,
                 optimizer: str = "adamw",
                 compute_dtype=jnp.bfloat16,
                 static_loss_scale: float = 0.0,
                 dynamic_loss_scale: bool = True,
                 initial_scale_power: int = 16,
                 scale_window: int = 1000,
                 min_loss_scale: float = 1.0,
                 hysteresis: int = 2,
                 clip_grad: float = 0.0,
                 lr: float = 1e-3,
                 betas=(0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 inner_apply: Optional[Callable] = None,
                 inner_init: Optional[Callable] = None,
                 groups=None):
        self.compute_dtype = compute_dtype
        self.clip_grad = float(clip_grad)
        self.dynamic = bool(dynamic_loss_scale) and not static_loss_scale
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.hysteresis = hysteresis
        self.hyper = {"lr": lr, "beta1": betas[0], "beta2": betas[1], "eps": eps,
                      "weight_decay": weight_decay}

        # per-group hypers (reference fused_optimizer.py:48-66 iterates param_groups):
        # ``groups`` is a static per-leaf group-id pytree; hyper values may then be
        # [n_groups] sequences (e.g. lr=[1e-3, 5e-4])
        if inner_apply is not None:
            assert groups is None, "groups require a built-in inner optimizer"
            self._apply, self._init = inner_apply, inner_init
        elif optimizer in ("adam", "adamw"):
            self._apply = lambda g, s, p, t, h: adam_opt.apply(g, s, p, t, h,
                                                               adamw=(optimizer == "adamw"),
                                                               groups=groups)
            self._init = adam_opt.init
        elif optimizer == "lamb":
            self._apply = lambda g, s, p, t, h: lamb_opt.apply(g, s, p, t, h,
                                                               groups=groups)
            self._init = lamb_opt.init
        else:
            raise ValueError(f"unknown optimizer {optimizer!r}")

        # fp32 master copy (reference fused_optimizer.py:48-66)
        self.master = jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32), init_params)
        self.state = self._init(self.master)
        self.scaler = ls.init_state(static_loss_scale, initial_scale_power, hysteresis)
        # host-side shadow of the device scaler: structured loss-scale events
        # (ramp/backoff/skip) instead of silence — see docs/numerics.md
        init_scale = float(static_loss_scale) if static_loss_scale and static_loss_scale > 0 \
            else float(2**initial_scale_power)
        self.journal = ls.LossScaleJournal(self.dynamic, init_scale,
                                           scale_window=scale_window,
                                           min_scale=min_loss_scale,
                                           hysteresis=hysteresis)
        self.steps = jnp.asarray(0, jnp.int32)
        self._jit_step = jax.jit(self._step_impl, donate_argnums=(0, 1, 2, 3))
        # Per-loss_fn compiled backward cache, LRU-bounded: the jitted closure holds a
        # strong ref to its loss_fn, so an unbounded dict would leak executables (and
        # whatever the loss_fn closes over) for callers that pass a fresh lambda per step.
        self._jit_backwards = OrderedDict()
        self._jit_backwards_max = 4
        self.overflow = False  # python-visible last-step overflow flag (reference l.245)

    # ------------------------------------------------------------------ loss scaling
    @property
    def cur_scale(self) -> float:
        return float(jax.device_get(self.scaler.cur_scale))

    # reference property name
    loss_scale = cur_scale

    def scale_loss(self, loss):
        return loss * self.scaler.cur_scale.astype(loss.dtype)

    def backward(self, loss_fn: Callable, params16, *batch):
        """Scaled value_and_grad (reference backward l.159: loss*scale → autograd).
        Returns (unscaled loss, scaled grads in fp32). The compiled backward is
        cached per loss_fn with the scale as an explicit argument, so repeated
        steps pay zero retrace."""
        # Closure-free plain functions are keyed by their code object, so the documented
        # fresh-lambda-per-step pattern (`opt.backward(lambda p, x: ..., p, x)`) hits the
        # cache instead of recompiling every step. Anything else — closures, bound
        # methods (which share __code__ across instances!), arbitrary callables — is
        # keyed by identity (same code, different captured state → different trace).
        if (isinstance(loss_fn, types.FunctionType) and loss_fn.__closure__ is None
                and not loss_fn.__defaults__ and not loss_fn.__kwdefaults__):
            key = loss_fn.__code__
        else:
            key = loss_fn
        jitted = self._jit_backwards.get(key)
        if jitted is None:
            def scaled_loss_and_grad(p, scale, *b):
                def scaled(p, *bb):
                    loss = loss_fn(p, *bb)
                    return loss * scale.astype(loss.dtype), loss
                (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(p, *b)
                return loss, jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
            jitted = self._jit_backwards[key] = jax.jit(scaled_loss_and_grad)
            while len(self._jit_backwards) > self._jit_backwards_max:
                self._jit_backwards.popitem(last=False)
        else:
            self._jit_backwards.move_to_end(key)
        return jitted(params16, self.scaler.cur_scale, *batch)

    # ------------------------------------------------------------------ step
    def _step_impl(self, master, state, scaler, steps, grads, hyper):
        inv = jnp.where(scaler.cur_scale > 0, 1.0 / scaler.cur_scale, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        # shared engine-level overflow helper (inf/nan survives the unscale, so
        # checking post-unscale matches the raw-grad check the engine performs)
        overflow, _ = detect_overflow(grads, fp16_active=True)
        if self.clip_grad > 0:
            grads = clip_grads_by_global_norm(grads, self.clip_grad)
        new_steps = jnp.where(overflow, steps, steps + 1)
        new_master, new_state = self._apply(grads, state, master, new_steps, hyper)
        # select: skip the update entirely on overflow (reference step l.191-273)
        sel = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(overflow, o, n), new, old)
        new_master = sel(new_master, master)
        new_state = sel(new_state, state)
        new_scaler = ls.update(scaler, overflow, dynamic=self.dynamic,
                               scale_window=self.scale_window,
                               min_scale=self.min_loss_scale, hysteresis=self.hysteresis)
        params16 = jax.tree_util.tree_map(lambda p: p.astype(self.compute_dtype), new_master)
        return new_master, new_state, new_scaler, new_steps, params16, overflow

    def step(self, grads):
        """Unscale, overflow-check, clip, inner update, re-cast (reference l.191-273).
        Returns fresh compute-dtype params (the fp16 tensors the reference wrote
        back into the model in-place)."""
        (self.master, self.state, self.scaler, self.steps,
         params16, overflow) = self._jit_step(self.master, self.state, self.scaler,
                                              self.steps, grads, self.hyper)
        self.overflow = bool(jax.device_get(overflow))
        self.journal.record(self.journal.iter_count + 1, self.overflow)
        if self.overflow:
            logger.info(f"[fp16] OVERFLOW — skipping step, new loss scale {self.cur_scale}")
        return params16

    def zero_grad(self, set_grads_to_None=True):
        """No-op in a functional API (grads are values, not buffers); kept for parity."""

    # ------------------------------------------------------------------ checkpointing
    def state_dict(self):
        return {"master": self.master, "state": self.state, "scaler": self.scaler,
                "steps": self.steps, "overflow": self.overflow,
                "dynamic_loss_scale": self.dynamic, "clip_grad": self.clip_grad}

    def load_state_dict(self, sd, load_optimizer_states: bool = True):
        self.master = sd["master"]
        if load_optimizer_states and "state" in sd:
            self.state = sd["state"]
        self.scaler = sd["scaler"]
        self.steps = sd["steps"]
        self.overflow = bool(sd.get("overflow", False))


class FP16_UnfusedOptimizer(FP16_Optimizer):
    """Reference ``unfused_optimizer.py`` hosted LAMB per-tensor (l.376). Under XLA
    fused/unfused is a non-distinction; this subclass just defaults to LAMB."""

    def __init__(self, init_params, optimizer: str = "lamb", **kw):
        super().__init__(init_params, optimizer=optimizer, **kw)
