from .loss_scaler import LossScaleState, init_state, update
