from .loss_scaler import LossScaleState, init_state, update
from .fused_optimizer import FP16_Optimizer, FP16_UnfusedOptimizer
