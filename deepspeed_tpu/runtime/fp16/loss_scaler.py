"""Static + dynamic loss scaling.

Mirrors ``deepspeed/runtime/fp16/loss_scaler.py`` (LossScaler l.56, DynamicLossScaler l.79,
hysteresis l.151-166) — but redesigned to live INSIDE a jitted train step: the scaler state
is a pytree of device scalars and the skip-on-overflow decision is a ``jnp.where`` select,
so overflow handling costs no host round-trip (reference hard part §7: "dynamic loss
scaling with step-skip inside jit").
"""

from collections import deque
from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray        # fp32 scalar
    cur_hysteresis: jnp.ndarray   # int32 scalar
    last_overflow_iter: jnp.ndarray  # int32 scalar
    iter_count: jnp.ndarray       # int32 scalar


def init_state(static_loss_scale: float = 0,
               initial_scale_power: int = 32,
               hysteresis: int = 2) -> LossScaleState:
    """static_loss_scale > 0 → fixed scale; 0 → dynamic starting at 2**initial_scale_power."""
    init_scale = float(static_loss_scale) if static_loss_scale and static_loss_scale > 0 \
        else float(2**initial_scale_power)
    return LossScaleState(cur_scale=jnp.asarray(init_scale, jnp.float32),
                          cur_hysteresis=jnp.asarray(hysteresis, jnp.int32),
                          last_overflow_iter=jnp.asarray(-1, jnp.int32),
                          iter_count=jnp.asarray(0, jnp.int32))


def update(state: LossScaleState,
           overflow: jnp.ndarray,
           dynamic: bool,
           scale_window: int = 1000,
           scale_factor: float = 2.0,
           min_scale: float = 1.0,
           hysteresis: int = 2) -> LossScaleState:
    """Advance scaler state after a step whose grads overflowed (or not).

    Semantics (reference loss_scaler.py:140-170): on overflow, consume hysteresis; only
    when exhausted divide the scale by scale_factor (floored at min_scale). After
    ``scale_window`` consecutive clean iters, multiply by scale_factor and reset hysteresis.
    """
    it = state.iter_count + 1
    if not dynamic:
        return state._replace(iter_count=it)

    # overflow path
    hys_after = jnp.maximum(state.cur_hysteresis - 1, 0)
    drop_scale = jnp.maximum(state.cur_scale / scale_factor, min_scale)
    of_scale = jnp.where(state.cur_hysteresis <= 1, drop_scale, state.cur_scale)
    of_hys = jnp.where(state.cur_hysteresis <= 1, state.cur_hysteresis, hys_after)

    # clean path
    window_ok = (it - state.last_overflow_iter) % scale_window == 0
    clean_scale = jnp.where(window_ok, state.cur_scale * scale_factor, state.cur_scale)
    clean_hys = jnp.where(window_ok, jnp.asarray(hysteresis, jnp.int32), state.cur_hysteresis)

    return LossScaleState(
        cur_scale=jnp.where(overflow, of_scale, clean_scale),
        cur_hysteresis=jnp.where(overflow, of_hys, clean_hys),
        last_overflow_iter=jnp.where(overflow, it, state.last_overflow_iter),
        iter_count=it,
    )


class LossScaleJournal:
    """Host-side shadow of :func:`update` that turns the silent device-state
    transitions into structured events (ramp, backoff, skip, min-scale floor,
    consecutive-skip streaks — the numerics-observatory journal).

    The device scaler state never leaves the accelerator on the hot path, so
    the journal REPLAYS the exact update semantics on Python floats from the
    one host fact the engine already fetches per step: the overflow bool. At
    every step ``journal.cur_scale == float(engine.loss_scale())`` — tested in
    tests/unit/test_numerics.py.
    """

    def __init__(self, dynamic, init_scale, scale_window=1000, scale_factor=2.0,
                 min_scale=1.0, hysteresis=2, emit=None, max_events=1024):
        self.dynamic = bool(dynamic)
        self.cur_scale = float(init_scale)
        self.scale_window = int(scale_window)
        self.scale_factor = float(scale_factor)
        self.min_scale = float(min_scale)
        self.hysteresis = int(hysteresis)
        self.cur_hysteresis = int(hysteresis)
        self.last_overflow_iter = -1
        self.iter_count = 0
        self.skip_streak = 0
        self.emit = emit  # callable(event_dict, step) — set by NumericsMonitor
        self.events = deque(maxlen=int(max_events))

    def _event(self, step, kind, **fields):
        ev = dict(fields, kind=kind, step=step, scale=self.cur_scale)
        self.events.append(ev)
        if self.emit is not None:
            self.emit(ev, step)
        return ev

    def record(self, step, overflowed):
        """Advance the shadow state one step; returns the events it emitted."""
        emitted = []
        it = self.iter_count + 1
        if overflowed:
            self.skip_streak += 1
            if self.dynamic:
                if self.cur_hysteresis <= 1:
                    prev = self.cur_scale
                    self.cur_scale = max(self.cur_scale / self.scale_factor,
                                         self.min_scale)
                    emitted.append(self._event(step, "backoff", previous=prev))
                    if self.cur_scale <= self.min_scale:
                        emitted.append(self._event(step, "min_scale_floor"))
                else:
                    self.cur_hysteresis -= 1
                    emitted.append(self._event(
                        step, "hysteresis", remaining=self.cur_hysteresis))
            self.last_overflow_iter = it
            emitted.append(self._event(step, "skip", streak=self.skip_streak))
        else:
            if self.skip_streak:
                emitted.append(self._event(step, "recovered",
                                           streak=self.skip_streak))
            self.skip_streak = 0
            if self.dynamic and (it - self.last_overflow_iter) % self.scale_window == 0:
                prev = self.cur_scale
                self.cur_scale *= self.scale_factor
                self.cur_hysteresis = self.hysteresis
                emitted.append(self._event(step, "ramp", previous=prev))
        self.iter_count = it
        return emitted
