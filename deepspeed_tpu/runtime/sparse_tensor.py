"""Row-sparse gradient support for embedding tables.

TPU-native analog of ``deepspeed/runtime/csr_tensor.py`` (CSRTensor) and the engine's
CSR allreduce (``deepspeed/runtime/engine.py:1091-1147``): embedding gradients are
row-sparse (a token's backward touches exactly one table row), so data-parallel
reduction ships (indices, values) instead of the dense [vocab, width] array.

The reference used dynamic-size nonzero + padded all_gathers. Under XLA everything
must be static-shaped, so ``SparseTensor`` carries a **fixed capacity** k of rows:
``from_dense`` selects up to k nonzero rows (k = local token count bounds the true
nonzero count for gather-transpose gradients, making this exact, not approximate);
``all_gather`` over the mesh axis then needs no padding dance at all — every shard
contributes exactly k rows. Empty slots point at row 0 with all-zero values, so the
scatter-add in ``to_dense`` is a harmless no-op for them.
"""

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class SparseTensor:
    """Fixed-capacity row-sparse tensor (reference csr_tensor.py:11-59).

    ``indices``: int32 [k] row ids (unused slots = 0), ``values``: [k, cols]
    (unused slots = 0), ``dense_shape``: (rows, cols) static.
    """

    def __init__(self, indices: jnp.ndarray, values: jnp.ndarray,
                 dense_shape: Tuple[int, int]):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(dense_shape)

    @staticmethod
    def type() -> str:
        return "deepspeed_tpu.SparseTensor"

    @classmethod
    def from_dense(cls, dense: jnp.ndarray, capacity: Optional[int] = None) -> "SparseTensor":
        """Extract up to ``capacity`` nonzero rows (by any-nonzero test, reference
        csr_tensor.py:16-18 used sum!=0 which misses cancelling rows; we use abs-sum).
        Rows beyond capacity are dropped — pass a capacity that upper-bounds the true
        nonzero count (token count for embedding grads) for exactness."""
        rows, _ = dense.shape
        k = rows if capacity is None else min(capacity, rows)
        row_mass = jnp.sum(jnp.abs(dense), axis=1)
        (idx,) = jnp.nonzero(row_mass, size=k, fill_value=0)
        # nonzero() pads the tail with fill_value=0; a positional mask (slot < true
        # nnz) distinguishes padding from a genuinely-nonzero row 0.
        nnz = jnp.sum(row_mass > 0)
        valid = jnp.arange(k) < nnz
        values = dense[idx] * valid[:, None].astype(dense.dtype)
        return cls(idx.astype(jnp.int32), values, dense.shape)

    def to_dense(self) -> jnp.ndarray:
        """Scatter-add rows back (reference csr_tensor.py:29-35). Duplicate indices
        accumulate, so gathered multi-worker tensors densify correctly."""
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> Tuple[int, int]:
        index_size = self.indices.shape[0]
        value_size = self.values.shape[0] * self.values.shape[1]
        dense_size = self.dense_shape[0] * self.dense_shape[1]
        return index_size + value_size, dense_size

    def add(self, other: "SparseTensor") -> "SparseTensor":
        """Concatenate entries (reference csr_tensor.py:45-48); duplicates resolve
        at to_dense time."""
        assert self.dense_shape == other.dense_shape
        return SparseTensor(jnp.concatenate([self.indices, other.indices]),
                            jnp.concatenate([self.values, other.values]),
                            self.dense_shape)

    def __repr__(self):
        sparse_size, dense_size = self.sparse_size()
        return (f"SparseTensor(k={self.indices.shape[0]}, dense_shape={self.dense_shape}, "
                f"reduction_factor={dense_size / max(sparse_size, 1):.1f})")


def row_sparse_allreduce(dense_local: jnp.ndarray, axis_name: str, capacity: int,
                         mean: bool = True) -> jnp.ndarray:
    """Average a row-sparse gradient over a mesh axis by gathering (indices, values)
    instead of psum-ing the dense table (reference engine.py:1105-1127).

    Must be called inside shard_map/pmap with ``axis_name`` bound. Comm volume is
    world*k*(cols+1) vs rows*cols for a dense psum — a win when k << rows/world.
    """
    st = SparseTensor.from_dense(dense_local, capacity)
    # Static capacity per shard → plain all_gathers, no size exchange or padding
    # (the reference needed an extra scalar all_gather + fill, engine.py:1116-1140).
    all_idx = jax.lax.all_gather(st.indices, axis_name)      # [world, k]
    all_val = jax.lax.all_gather(st.values, axis_name)       # [world, k, cols]
    gathered = SparseTensor(all_idx.reshape(-1), all_val.reshape(-1, all_val.shape[-1]),
                            st.dense_shape)
    dense = gathered.to_dense()
    if mean:
        from ..parallel.mesh import axis_size
        dense = dense / axis_size(axis_name)
    return dense.astype(dense_local.dtype)


def match_sparse_paths(path_str: str, patterns: Sequence[str]) -> bool:
    """Leaf-path matcher for the engine's sparse-grad selection (the reference keyed
    on ``isinstance(module, nn.Embedding)``, engine.py:180-187; a functional pytree
    keys on leaf path substrings instead)."""
    return any(p in path_str for p in patterns)


# Reference-name alias (deepspeed/runtime/csr_tensor.py exports CSRTensor; the TPU
# rebuild is row-sparse rather than true CSR, but the role and API surface match).
CSRTensor = SparseTensor
