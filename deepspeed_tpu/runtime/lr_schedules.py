"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Semantics mirror ``deepspeed/runtime/lr_schedules.py`` (LRRangeTest l.301, OneCycle l.401,
WarmupLR l.645, WarmupDecayLR l.722). Schedulers mutate host-side ``param_groups`` dicts on
the optimizer handle; the engine reads ``param_groups[0]['lr']`` each step and feeds it to
the jitted train step as a device scalar — LR changes never trigger recompilation.
"""

import math
from typing import Union, List

from ..utils import logger

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_MOMENTUM = "cycle_momentum"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


def _get_optimizer_handle(optimizer):
    """Any object with a ``param_groups`` list of dicts works as the handle."""
    if hasattr(optimizer, "param_groups"):
        return optimizer
    raise TypeError(f"{type(optimizer).__name__} does not expose param_groups; "
                    "wrap it in an engine optimizer handle")


def _format_param(optimizer, param_value, param_name) -> List[float]:
    if isinstance(param_value, (list, tuple)):
        if len(param_value) != len(optimizer.param_groups):
            raise ValueError("expected {} value for {}, got {}".format(
                len(optimizer.param_groups), param_name, param_value))
        return list(param_value)
    return [param_value] * len(optimizer.param_groups)


class LRRangeTest:
    """LR range test: lr = min_lr * (1 + step_rate * interval(step))."""

    def __init__(self,
                 optimizer,
                 lr_range_test_min_lr: Union[float, List[float]] = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        self.optimizer = _get_optimizer_handle(optimizer)
        self.min_lr = _format_param(self.optimizer, lr_range_test_min_lr, "lr_range_test_min_lr")
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration
        if last_batch_iteration == -1:
            self._update_optimizer(self.min_lr)

    def _interval(self) -> float:
        frac = float(self.last_batch_iteration) / self.step_size
        return float(math.floor(frac)) if self.staircase else frac

    def get_lr(self):
        increase = 1 + self.step_rate * self._interval()
        return [lr * increase for lr in self.min_lr]

    def get_last_lr(self):
        return self._last_lr

    def _update_optimizer(self, group_lrs):
        self._last_lr = list(group_lrs)
        for param_group, lr in zip(self.optimizer.param_groups, group_lrs):
            param_group["lr"] = lr

    def step(self, batch_iteration=None):
        if batch_iteration is None:
            batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = batch_iteration
        self._update_optimizer(self.get_lr())

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class OneCycle:
    """1-cycle policy: lr rises over the first leg, falls over the second, then decays;
    momentum cycles inversely when cycle_momentum is set."""

    def __init__(self,
                 optimizer,
                 cycle_min_lr,
                 cycle_max_lr,
                 decay_lr_rate=0.0,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 cycle_momentum=True,
                 cycle_min_mom=0.8,
                 cycle_max_mom=0.9,
                 decay_mom_rate=0.0,
                 last_batch_iteration=-1):
        self.optimizer = _get_optimizer_handle(optimizer)

        cycle_first_step_size = float(cycle_first_step_size)
        cycle_second_step_size = float(
            cycle_second_step_size) if cycle_second_step_size is not None else cycle_first_step_size
        self.total_size = cycle_first_step_size + cycle_second_step_size
        self.step_ratio = cycle_first_step_size / self.total_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = (cycle_first_stair_count
                                   if cycle_second_stair_count is None else cycle_second_stair_count)
        self.decay_step_size = max(decay_step_size, 1)

        self.min_lrs = [cycle_min_lr] * len(self.optimizer.param_groups)
        self.max_lrs = [cycle_max_lr] * len(self.optimizer.param_groups)
        self.decay_lr_rate = decay_lr_rate

        self.cycle_momentum = cycle_momentum
        if cycle_momentum:
            self.min_moms = [(cycle_min_mom, 0.99)] * len(self.optimizer.param_groups)
            self.max_moms = [(cycle_max_mom, 0.99)] * len(self.optimizer.param_groups)
            self.decay_mom_rate = decay_mom_rate

        self.last_batch_iteration = last_batch_iteration
        if last_batch_iteration == -1:
            self._update_optimizer(self.get_lr())

    def _cycle_progress(self):
        cycle = math.floor(1 + self.last_batch_iteration / self.total_size)
        x = 1.0 + self.last_batch_iteration / self.total_size - cycle
        if x <= self.step_ratio:
            scale_factor = x / self.step_ratio
            stair_count = self.first_stair_count
        else:
            scale_factor = (x - 1) / (self.step_ratio - 1)
            stair_count = self.second_stair_count
        if stair_count:
            scale_factor = math.floor(scale_factor * stair_count) / stair_count
        return scale_factor

    def _get_cycle_lr(self):
        scale_factor = self._cycle_progress()
        lrs = [min_lr + (max_lr - min_lr) * scale_factor
               for min_lr, max_lr in zip(self.min_lrs, self.max_lrs)]
        if self.cycle_momentum:
            moms = [(max_mom[0] - (max_mom[0] - min_mom[0]) * scale_factor, max_mom[1])
                    for min_mom, max_mom in zip(self.min_moms, self.max_moms)]
            for param_group, momentum in zip(self.optimizer.param_groups, moms):
                param_group["betas"] = momentum
        return lrs

    def _get_decay_lr(self, decay_batch_iteration):
        decay_interval = decay_batch_iteration / self.decay_step_size
        lr_decay_factor = 1 + self.decay_lr_rate * decay_interval
        lrs = [lr / lr_decay_factor for lr in self.min_lrs]
        if self.cycle_momentum:
            mom_decay_factor = 1 + self.decay_mom_rate * decay_interval
            moms = [(beta0 * mom_decay_factor, beta1) for beta0, beta1 in self.max_moms]
            for param_group, momentum in zip(self.optimizer.param_groups, moms):
                param_group["betas"] = momentum
        return lrs

    def get_lr(self):
        if self.last_batch_iteration <= self.total_size:
            return self._get_cycle_lr()
        return self._get_decay_lr(self.last_batch_iteration - self.total_size)

    def get_last_lr(self):
        return self._last_lr

    def _update_optimizer(self, group_lrs):
        self._last_lr = list(group_lrs)
        for param_group, lr in zip(self.optimizer.param_groups, group_lrs):
            param_group["lr"] = lr

    def step(self, batch_iteration=None):
        if batch_iteration is None:
            batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = batch_iteration
        self._update_optimizer(self.get_lr())

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR:
    """Log-warmup from min_lr to max_lr over warmup_num_steps, then constant."""

    def __init__(self,
                 optimizer,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        self.optimizer = _get_optimizer_handle(optimizer)
        self.min_lrs = _format_param(self.optimizer, warmup_min_lr, "min_lr")
        self.max_lrs = _format_param(self.optimizer, warmup_max_lr, "max_lr")
        self.delta_lrs = [big - small for big, small in zip(self.max_lrs, self.min_lrs)]
        self.warmup_num_steps = warmup_num_steps
        self.inverse_log_warm_up = 1.0 / math.log(max(warmup_num_steps, 2))
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = list(self.min_lrs)

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(self.last_batch_iteration + 1)
        return 1.0

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler before it has started")
            return [0.0]
        gamma = self._get_gamma()
        return [min_lr + (delta_lr * gamma) for min_lr, delta_lr in zip(self.min_lrs, self.delta_lrs)]

    def get_last_lr(self):
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        lrs = self.get_lr()
        self._last_lr = list(lrs)
        for param_group, lr in zip(self.optimizer.param_groups, lrs):
            param_group["lr"] = lr

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 over total_num_steps."""

    def __init__(self,
                 optimizer,
                 total_num_steps: int,
                 warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning("total_num_steps {} is less than warmup_num_steps {}".format(
                total_num_steps, warmup_num_steps))

    def _get_gamma(self):
        if self.last_batch_iteration < self.warmup_num_steps:
            return self.inverse_log_warm_up * math.log(self.last_batch_iteration + 1)
        return max(
            0.0,
            float(self.total_num_steps - self.last_batch_iteration) /
            float(max(1.0, self.total_num_steps - self.warmup_num_steps)))


_SCHEDULES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_scheduler(name, optimizer, params: dict):
    """Instantiate a scheduler by config name (engine: reference engine.py:402-417)."""
    if name not in _SCHEDULES:
        raise ValueError(f"unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULES[name](optimizer, **params)


# ---------------------------------------------------------------------------
# CLI convergence-tuning arguments (reference lr_schedules.py:54-239): schedules can be
# configured/overridden from the command line in addition to the JSON config.
# ---------------------------------------------------------------------------

def add_tuning_arguments(parser):
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    # Learning rate range test
    group.add_argument("--lr_range_test_min_lr", type=float, default=None,
                       help="Starting lr value.")
    group.add_argument("--lr_range_test_step_rate", type=float, default=None,
                       help="scaling rate for LR range test.")
    group.add_argument("--lr_range_test_step_size", type=int, default=None,
                       help="training steps per LR change.")
    group.add_argument("--lr_range_test_staircase", default=None, action="store_true",
                       help="use staircase scaling for LR range test.")
    # OneCycle schedule
    group.add_argument("--cycle_first_step_size", type=int, default=None,
                       help="size of first step of 1Cycle schedule (training steps).")
    group.add_argument("--cycle_first_stair_count", type=int, default=None,
                       help="first stair count for 1Cycle schedule.")
    group.add_argument("--cycle_second_step_size", type=int, default=None,
                       help="size of second step of 1Cycle schedule (default first_step_size).")
    group.add_argument("--cycle_second_stair_count", type=int, default=None,
                       help="second stair count for 1Cycle schedule.")
    group.add_argument("--decay_step_size", type=int, default=None,
                       help="size of intervals for applying post cycle decay (training steps).")
    group.add_argument("--cycle_min_lr", type=float, default=None,
                       help="1Cycle LR lower bound.")
    group.add_argument("--cycle_max_lr", type=float, default=None,
                       help="1Cycle LR upper bound.")
    group.add_argument("--decay_lr_rate", type=float, default=None,
                       help="post cycle LR decay rate.")
    group.add_argument("--cycle_momentum", default=None, action="store_true",
                       help="Enable 1Cycle momentum schedule.")
    group.add_argument("--cycle_min_mom", type=float, default=None,
                       help="1Cycle momentum lower bound.")
    group.add_argument("--cycle_max_mom", type=float, default=None,
                       help="1Cycle momentum upper bound.")
    group.add_argument("--decay_mom_rate", type=float, default=None,
                       help="post cycle momentum decay rate.")
    # Warmup LR
    group.add_argument("--warmup_min_lr", type=float, default=None,
                       help="WarmupLR minimum/initial LR value")
    group.add_argument("--warmup_max_lr", type=float, default=None,
                       help="WarmupLR maximum LR value.")
    group.add_argument("--warmup_num_steps", type=int, default=None,
                       help="WarmupLR step count for LR warmup.")
    return parser


def parse_arguments():
    import argparse
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args


def _override_from(args, params, keys):
    for key in keys:
        if getattr(args, key, None) is not None:
            params[key] = getattr(args, key)


def override_lr_range_test_params(args, params):
    _override_from(args, params, (LR_RANGE_TEST_MIN_LR, LR_RANGE_TEST_STEP_RATE,
                                  LR_RANGE_TEST_STEP_SIZE, LR_RANGE_TEST_STAIRCASE))


def override_1cycle_params(args, params):
    _override_from(args, params, (CYCLE_FIRST_STEP_SIZE, CYCLE_FIRST_STAIR_COUNT,
                                  CYCLE_SECOND_STEP_SIZE, CYCLE_SECOND_STAIR_COUNT,
                                  DECAY_STEP_SIZE, CYCLE_MIN_LR, CYCLE_MAX_LR,
                                  DECAY_LR_RATE, CYCLE_MOMENTUM, CYCLE_MIN_MOM, CYCLE_MAX_MOM,
                                  DECAY_MOM_RATE))


def override_warmupLR_params(args, params):
    _override_from(args, params, (WARMUP_MIN_LR, WARMUP_MAX_LR, WARMUP_NUM_STEPS))


def override_params(args, params):
    override_lr_range_test_params(args, params)
    override_1cycle_params(args, params)
    override_warmupLR_params(args, params)
