"""Config key constants and defaults.

Mirrors the key surface of the reference's ``deepspeed/runtime/constants.py`` (293 LoC) so a
DeepSpeed JSON config is accepted unchanged. TPU-specific additions are marked; CUDA-only
knobs are accepted and either honored semantically or ignored with a logged warning.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
# TPU-friendly alias accepted in the JSON.
TRAIN_MICRO_BATCH_SIZE_PER_DEVICE = "train_micro_batch_size_per_device"

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

#############################################
# Optimizer names recognized by the engine
#############################################
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, SGD_OPTIMIZER]

#############################################
# FP16 / mixed precision support
# On TPU "fp16" enables loss-scaled low-precision training; the compute dtype
# defaults to bfloat16 (no scaling needed) unless fp16.actual_dtype=float16.
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False

FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0

FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32

FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000

FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2

FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

# TPU-native bf16 block (default on): {"bf16": {"enabled": true}}
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = True

#############################################
# Apex AMP parity block — accepted, mapped to bf16 policy.
#############################################
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient clipping
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

#############################################
# Communication / reduction
#############################################
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

# Fused single-jit train step (forward+backward+optimizer in one program;
# requires gradient_accumulation_steps == 1). TPU-native extension: buys
# ~1 param-tree of HBM headroom by never materializing the grad tree.
FUSED_STEP = "fused_step"
FUSED_STEP_DEFAULT = False

# Persistent XLA compilation cache directory (TPU-native extension). Cuts large-
# model recompiles across processes/restarts to seconds; measured 13.0s -> 1.4s
# for a warm cross-process compile through the remote-compile relay.
COMPILATION_CACHE_DIR = "compilation_cache_dir"
COMPILATION_CACHE_DIR_DEFAULT = None

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = "allreduce_always_fp32"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

#############################################
# Steps
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Training options
#############################################
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None

#############################################
# Wall block breakdown
#############################################
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Tensorboard
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# Telemetry (TPU-native observability; no reference key — replaces the
# reference's barrier-heavy wall_clock_breakdown path with non-perturbing
# step metrics, profiler trace windows, a compile watchdog, and an HBM +
# wire-bytes ledger. See docs/telemetry.md.)
#############################################
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_TRACE_DIR = "trace_dir"
TELEMETRY_TRACE_DIR_DEFAULT = ""
TELEMETRY_TRACE_STEPS = "trace_steps"
TELEMETRY_TRACE_STEPS_DEFAULT = None
TELEMETRY_PERTURBING_BREAKDOWN = "perturbing_breakdown"
TELEMETRY_PERTURBING_BREAKDOWN_DEFAULT = False
TELEMETRY_PEAK_TFLOPS = "peak_tflops"
TELEMETRY_PEAK_TFLOPS_DEFAULT = 0.0
TELEMETRY_MFU_WINDOW = "mfu_window"
TELEMETRY_MFU_WINDOW_DEFAULT = 20
TELEMETRY_RECOMPILE_WARN = "recompile_warn"
TELEMETRY_RECOMPILE_WARN_DEFAULT = 3
TELEMETRY_OUTPUT_PATH = "output_path"
TELEMETRY_OUTPUT_PATH_DEFAULT = ""
TELEMETRY_JOB_NAME = "job_name"
TELEMETRY_JOB_NAME_DEFAULT = "DeepSpeedTelemetry"

# telemetry.anatomy sub-block: the step-time anatomy — per-program roofline
# ledger + async-overlap analysis over the watchdog's AOT artifacts, emitted
# as Anatomy/* scalars (docs/anatomy.md). chip "" auto-detects; the rate
# overrides (0 = keep the chip table value) let one machine be priced as
# another.
TELEMETRY_ANATOMY = "anatomy"
ANATOMY_ENABLED = "enabled"
ANATOMY_ENABLED_DEFAULT = False
ANATOMY_CHIP = "chip"
ANATOMY_CHIP_DEFAULT = ""
ANATOMY_PEAK_TFLOPS = "peak_tflops"
ANATOMY_PEAK_TFLOPS_DEFAULT = 0.0
ANATOMY_HBM_GBPS = "hbm_gbps"
ANATOMY_HBM_GBPS_DEFAULT = 0.0
ANATOMY_ICI_GBPS = "ici_gbps"
ANATOMY_ICI_GBPS_DEFAULT = 0.0
ANATOMY_DCN_GBPS = "dcn_gbps"
ANATOMY_DCN_GBPS_DEFAULT = 0.0

# telemetry.pipeline_trace sub-block: per-instruction span timeline for the
# pipeline instruction executor (docs/pipeline-trace.md)
TELEMETRY_PIPELINE_TRACE = "pipeline_trace"
PIPELINE_TRACE_ENABLED = "enabled"
PIPELINE_TRACE_ENABLED_DEFAULT = False
PIPELINE_TRACE_CAPACITY = "capacity"
PIPELINE_TRACE_CAPACITY_DEFAULT = 64
PIPELINE_TRACE_DUMP_DIR = "dump_dir"
PIPELINE_TRACE_DUMP_DIR_DEFAULT = ""

# telemetry.cluster sub-block: cross-host observability plane — heartbeat
# aggregation over the host CPU world, straggler naming, hang watchdog,
# merged post-mortems (docs/cluster.md)
TELEMETRY_CLUSTER = "cluster"
CLUSTER_ENABLED = "enabled"
CLUSTER_ENABLED_DEFAULT = False
CLUSTER_HEARTBEAT_INTERVAL = "heartbeat_interval"
CLUSTER_HEARTBEAT_INTERVAL_DEFAULT = 1
CLUSTER_HANG_DEADLINE_S = "hang_deadline_s"
CLUSTER_HANG_DEADLINE_S_DEFAULT = 0.0  # 0 = watchdog off
CLUSTER_DUMP_DIR = "dump_dir"
CLUSTER_DUMP_DIR_DEFAULT = ""
CLUSTER_STRAGGLER_THRESHOLD = "straggler_threshold"
CLUSTER_STRAGGLER_THRESHOLD_DEFAULT = 3.0
CLUSTER_SIGNAL_PEERS = "signal_peers"
CLUSTER_SIGNAL_PEERS_DEFAULT = True
# steps before the watchdog arms / stragglers are named: the first step(s)
# pay multi-second compiles, which would false-fire any sane deadline
CLUSTER_WARMUP_STEPS = "warmup_steps"
CLUSTER_WARMUP_STEPS_DEFAULT = 1

# telemetry.goodput sub-block: run-lifecycle goodput/badput ledger — classifies
# every wall-clock interval of the run into a closed badput taxonomy (init,
# compile, productive_step, checkpoint_stall, restart_replay, hang,
# straggler_skew, eval, host_gap) with an exact-partition invariant
# (docs/goodput.md). Host-side only; the lowered step program is
# HLO-instruction-identical with the block on or off.
TELEMETRY_GOODPUT = "goodput"
GOODPUT_ENABLED = "enabled"
GOODPUT_ENABLED_DEFAULT = False
# where the per-run ledger JSON lands; "" falls back to the flight-recorder
# dump_dir (numerics.dump_dir) so the ledger sits beside the dumps it prices
GOODPUT_LEDGER_DIR = "ledger_dir"
GOODPUT_LEDGER_DIR_DEFAULT = ""
GOODPUT_EMIT_SCALARS = "emit_scalars"
GOODPUT_EMIT_SCALARS_DEFAULT = True
# tag used for eval intervals in the ledger (and the Run/Goodput scalar name)
GOODPUT_EVAL_TAG = "eval_tag"
GOODPUT_EVAL_TAG_DEFAULT = "eval"

# telemetry.hbm sub-block: HBM memory observatory — installs the engine's
# per-class resident-byte manifest (params / grads / master / optimizer /
# comm-EF) into the telemetry session so end_step emits Memory/* scalars and
# the flight recorder's dump carries OOM forensics (docs/hbm.md). Host-side
# constants only; the lowered step program is HLO-instruction-identical with
# the block on or off.
TELEMETRY_HBM = "hbm"
HBM_ENABLED = "enabled"
HBM_ENABLED_DEFAULT = False

# telemetry.profile sub-block: measured-time profile observatory — reads the
# trace window's profiler JSON back after it closes, classifies the device
# timeline per named scope, and reconciles measured vs predicted (anatomy) vs
# derived (step counters) step time (docs/profile.md). Host-side file parsing
# only; the lowered step program is HLO-instruction-identical with the block
# on or off. Requires telemetry.enabled (and a trace window to have anything
# to ingest).
TELEMETRY_PROFILE = "profile"
PROFILE_ENABLED = "enabled"
PROFILE_ENABLED_DEFAULT = False
# relative tolerance of the ds-tpu profile --reconcile verdicts (the
# machine-independent pairs: flops, collective counts, wire bytes)
PROFILE_RECONCILE_TOLERANCE = "reconcile_tolerance"
PROFILE_RECONCILE_TOLERANCE_DEFAULT = 0.05
PROFILE_EMIT_SCALARS = "emit_scalars"
PROFILE_EMIT_SCALARS_DEFAULT = True

# telemetry.metrics sub-block: unified metric catalog + per-host time-series
# ring — every scalar any observatory emits is resolved against the declared
# catalog (utils/metrics.py: unit, direction, class, description; unknown
# names warn-once, strict mode raises) and recorded into a bounded ring with
# fixed geometry, exactly mergeable across hosts via the dump plane
# (docs/metrics.md). Host-side only; the lowered step program is
# HLO-instruction-identical with the block on or off.
TELEMETRY_METRICS = "metrics"
METRICS_ENABLED = "enabled"
METRICS_ENABLED_DEFAULT = False
# observations kept per metric (the ring's fixed geometry)
METRICS_RING_LEN = "ring_len"
METRICS_RING_LEN_DEFAULT = 512
# strict catalog mode: a scalar emitted under an undeclared name raises
# instead of warning once — the test drift guard
METRICS_STRICT_CATALOG = "strict_catalog"
METRICS_STRICT_CATALOG_DEFAULT = False
# "" = no export; a path writes an OpenMetrics text exposition of the ring's
# latest values when the telemetry session closes
METRICS_EXPORT_PATH = "export_path"
METRICS_EXPORT_PATH_DEFAULT = ""

# telemetry.alerts sub-block: the alert plane — deterministic host-side rules
# (threshold / delta / stuck / slo_burn) evaluated on the end_step boundary
# against the metric ring; a firing rule emits an Alerts/* scalar, a
# structured monitor event, and (severity "page") a flight-recorder dump
# (docs/alerts.md). Zero new device syncs; the lowered step program is
# HLO-instruction-identical with the block on or off.
TELEMETRY_ALERTS = "alerts"
ALERTS_ENABLED = "enabled"
ALERTS_ENABLED_DEFAULT = False
# None arms the shipped default ruleset (utils/alerts.default_rules: MFU
# regression, fleet shed-rate SLO burn, loss-scale death spiral, dispatch
# skew); a list of rule dicts replaces it (validated at config parse)
ALERTS_RULES = "rules"
ALERTS_RULES_DEFAULT = None

#############################################
# Numerics observatory (TPU-native health layer on top of telemetry; no
# reference key — in-graph per-subtree anomaly sentinel, loss-scale event
# journal, cross-rank desync audit, and black-box flight recorder. See
# docs/numerics.md.)
#############################################
NUMERICS = "numerics"
NUMERICS_ENABLED = "enabled"
NUMERICS_ENABLED_DEFAULT = False
NUMERICS_SUBTREE_DEPTH = "subtree_depth"
NUMERICS_SUBTREE_DEPTH_DEFAULT = 1
NUMERICS_AUDIT_INTERVAL = "audit_interval"
NUMERICS_AUDIT_INTERVAL_DEFAULT = 0  # 0 = desync audit off
NUMERICS_DUMP_DIR = "dump_dir"
NUMERICS_DUMP_DIR_DEFAULT = ""
NUMERICS_RING_SIZE = "ring_size"
NUMERICS_RING_SIZE_DEFAULT = 256
NUMERICS_CONSECUTIVE_SKIP_TRIGGER = "consecutive_skip_trigger"
NUMERICS_CONSECUTIVE_SKIP_TRIGGER_DEFAULT = 8
NUMERICS_TRIGGER_ON_NONFINITE_LOSS = "trigger_on_nonfinite_loss"
NUMERICS_TRIGGER_ON_NONFINITE_LOSS_DEFAULT = True
NUMERICS_INSTALL_SIGNAL_HANDLERS = "install_signal_handlers"
NUMERICS_INSTALL_SIGNAL_HANDLERS_DEFAULT = False

#############################################
# Resilience (TPU-native fault tolerance, no reference key — async sharded
# checkpointing with a torn-write-proof commit protocol, topology-changing
# restore, flight-recorder-driven auto-resume. See docs/resilience.md. All
# hooks are host-side: with the block disabled (the default) the lowered
# step program is HLO-instruction-identical to a build without it.)
#############################################
RESILIENCE = "resilience"
RESILIENCE_ENABLED = "enabled"
RESILIENCE_ENABLED_DEFAULT = False
RESILIENCE_SAVE_DIR = "save_dir"
RESILIENCE_SAVE_DIR_DEFAULT = ""
RESILIENCE_SAVE_INTERVAL = "save_interval"
RESILIENCE_SAVE_INTERVAL_DEFAULT = 0  # 0 = no periodic saves
RESILIENCE_ASYNC_SAVE = "async_save"
RESILIENCE_ASYNC_SAVE_DEFAULT = True
RESILIENCE_AUTO_RESUME = "auto_resume"
RESILIENCE_AUTO_RESUME_DEFAULT = False

#############################################
# Serving (TPU-native inference engine, no reference key — the reference
# 0.3.0 ships no inference path. Block-paged KV cache + continuous batching;
# see docs/serving.md. Sizes are in tokens; the pool holds num_blocks pages of
# block_size tokens per layer, and block 0 is the reserved null page padded
# writes are routed to.)
#############################################
SERVING = "serving"
SERVING_ENABLED = "enabled"
SERVING_ENABLED_DEFAULT = False
SERVING_BLOCK_SIZE = "block_size"
SERVING_BLOCK_SIZE_DEFAULT = 16
SERVING_NUM_BLOCKS = "num_blocks"
SERVING_NUM_BLOCKS_DEFAULT = 257  # 256 usable + the reserved null block
SERVING_MAX_SEQS = "max_seqs"
SERVING_MAX_SEQS_DEFAULT = 8
SERVING_MAX_MODEL_LEN = "max_model_len"
SERVING_MAX_MODEL_LEN_DEFAULT = 256
SERVING_PREFILL_CHUNK = "prefill_chunk"
SERVING_PREFILL_CHUNK_DEFAULT = 32
SERVING_USE_PALLAS_DECODE = "use_pallas_decode"
SERVING_USE_PALLAS_DECODE_DEFAULT = False
# serving.request_trace — the per-request lifecycle ledger
# (serve/request_trace.py): latency percentiles, preemption-waste accounting,
# pool timeline, SLO classification, `ds-tpu serve-timeline` Perfetto export.
# Disabled -> the engine's tracer gate is None (nothing constructed).
SERVING_REQUEST_TRACE = "request_trace"
SERVING_REQUEST_TRACE_ENABLED = "enabled"
SERVING_REQUEST_TRACE_ENABLED_DEFAULT = False
SERVING_REQUEST_TRACE_CAPACITY = "capacity"          # finished-request ring
SERVING_REQUEST_TRACE_CAPACITY_DEFAULT = 256
SERVING_REQUEST_TRACE_ITERATION_CAPACITY = "iteration_capacity"
SERVING_REQUEST_TRACE_ITERATION_CAPACITY_DEFAULT = 4096
SERVING_REQUEST_TRACE_DUMP_DIR = "dump_dir"          # "" = no atexit dump
SERVING_REQUEST_TRACE_DUMP_DIR_DEFAULT = ""
SERVING_REQUEST_TRACE_SLO = "slo"
SERVING_SLO_TTFT_MS = "ttft_ms"                      # 0.0 = metric not gated
SERVING_SLO_TTFT_MS_DEFAULT = 0.0
SERVING_SLO_TPOT_MS = "tpot_ms"
SERVING_SLO_TPOT_MS_DEFAULT = 0.0
# serving.sharding — model-axis tensor parallelism for the serving engine:
# the per-layer KV pools and attention compute are sharded over "model"
# devices by attention head (n_head must divide evenly); activations stay
# replicated and each layer's output projection does one f32 all-reduce.
# model=1 (the default) is the exact single-chip path, byte-identical HLO.
SERVING_SHARDING = "sharding"
SERVING_SHARDING_MODEL = "model"
SERVING_SHARDING_MODEL_DEFAULT = 1
# serving.prefix_cache — cross-request prompt-prefix reuse: full prompt
# blocks are content-keyed at decode start (and at preemption, enabling warm
# restarts), parked in the allocator's LRU cached tier on last free, and
# remapped into new block tables on admission instead of re-prefilled.
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_CACHE_ENABLED = "enabled"
SERVING_PREFIX_CACHE_ENABLED_DEFAULT = False
# serving.speculation — greedy speculative decoding (Leviathan et al.): a
# draft model proposes up to max_draft_tokens per scheduler iteration against
# its own paged pool; the target verifies all K+1 positions in one batched
# step and a rejection rolls the block table back for free (CoW refcount
# release). Token-identical to the target's own greedy decode. draft_model is
# a human-readable label recorded in reports — the live draft model/params
# arrive via init_inference(draft_model=, draft_parameters=) because a config
# file cannot hold a parameter tree. draft_pool_blocks=0 inherits num_blocks.
SERVING_SPECULATION = "speculation"
SERVING_SPECULATION_ENABLED = "enabled"
SERVING_SPECULATION_ENABLED_DEFAULT = False
SERVING_SPECULATION_DRAFT_MODEL = "draft_model"
SERVING_SPECULATION_DRAFT_MODEL_DEFAULT = ""
SERVING_SPECULATION_MAX_DRAFT_TOKENS = "max_draft_tokens"
SERVING_SPECULATION_MAX_DRAFT_TOKENS_DEFAULT = 4
SERVING_SPECULATION_DRAFT_POOL_BLOCKS = "draft_pool_blocks"
SERVING_SPECULATION_DRAFT_POOL_BLOCKS_DEFAULT = 0
# serving.fleet — the N-replica serving front end (serve/router.py): one
# deterministic router owns "replicas" engine replicas and schedules every
# arrival. "policy" picks the routing rule — prefix-affinity (longest
# cached-prefix match, SGLang's cache-aware-routing insight, weighted against
# load by "affinity_weight"), pure least-loaded, or round-robin (the
# comparison baseline). "max_queue_depth" bounds each replica's waiting queue
# (0 = unbounded) and "occupancy_cap" caps its KV-pool used fraction; an
# arrival no replica can admit under those caps is SHED — a RequestOutput
# with status "shed", recorded in the request trace, never a crash.
# "goodput_floor" gates the merged fleet goodput fraction in `ds-tpu
# serve-sim --fleet` (0 = not gated).
SERVING_FLEET = "fleet"
SERVING_FLEET_REPLICAS = "replicas"
SERVING_FLEET_REPLICAS_DEFAULT = 1
SERVING_FLEET_POLICY = "policy"
SERVING_FLEET_POLICY_AFFINITY = "affinity"
SERVING_FLEET_POLICY_LEAST_LOADED = "least_loaded"
SERVING_FLEET_POLICY_ROUND_ROBIN = "round_robin"
SERVING_FLEET_POLICIES = (SERVING_FLEET_POLICY_AFFINITY,
                          SERVING_FLEET_POLICY_LEAST_LOADED,
                          SERVING_FLEET_POLICY_ROUND_ROBIN)
SERVING_FLEET_POLICY_DEFAULT = SERVING_FLEET_POLICY_AFFINITY
SERVING_FLEET_AFFINITY_WEIGHT = "affinity_weight"
SERVING_FLEET_AFFINITY_WEIGHT_DEFAULT = 1.0
SERVING_FLEET_MAX_QUEUE_DEPTH = "max_queue_depth"
SERVING_FLEET_MAX_QUEUE_DEPTH_DEFAULT = 0
SERVING_FLEET_OCCUPANCY_CAP = "occupancy_cap"
SERVING_FLEET_OCCUPANCY_CAP_DEFAULT = 1.0
SERVING_FLEET_GOODPUT_FLOOR = "goodput_floor"
SERVING_FLEET_GOODPUT_FLOOR_DEFAULT = 0.0

#############################################
# Comm (hierarchical ICI+DCN collectives)
#
# Routes data-parallel gradient exchange through the two-level schedule in
# deepspeed_tpu/comm: reduce-scatter within a slice over ICI, (optionally
# 1-bit sign-compressed) allreduce across slices over DCN, all-gather within
# the slice. "mode" selects flat (single-axis, the historical behaviour),
# hierarchical (two-level, full precision), or hierarchical_compressed
# (two-level with error-feedback sign compression of the cross-slice hop
# after "compress_start_step" warmup steps). "dcn_slices" fixes the slice
# count; 0 derives it from the jax.distributed process topology (one slice
# per process), falling back to a virtual 2x4 factorization of the 8-device
# CPU test mesh.
#############################################
COMM = "comm"
COMM_MODE = "mode"
COMM_MODE_DEFAULT = "flat"
COMM_MODE_FLAT = "flat"
COMM_MODE_HIERARCHICAL = "hierarchical"
COMM_MODE_COMPRESSED = "hierarchical_compressed"
COMM_MODES = (COMM_MODE_FLAT, COMM_MODE_HIERARCHICAL, COMM_MODE_COMPRESSED)
COMM_DCN_SLICES = "dcn_slices"
COMM_DCN_SLICES_DEFAULT = 0
COMM_COMPRESS_START_STEP = "compress_start_step"
COMM_COMPRESS_START_STEP_DEFAULT = 0

# comm.overlap: bucketed overlapped gradient exchange (docs/overlap.md).
# "mode" selects off (monolithic post-backward exchange, the historical
# behaviour — programs stay HLO-instruction-identical) or "bucketed"
# (partition the parameter tree into size-bounded per-subtree buckets and
# issue each bucket's exchange as soon as its backward subtree completes, so
# the collective of bucket k overlaps the remaining backward — and, under a
# hierarchical comm.mode, the DCN hop of bucket k overlaps the ICI phase of
# bucket k+1). "bucket_mb" bounds each bucket's fp32 wire footprint; the
# partition is deterministic for a given parameter tree and bucket_mb
# (DeepSpeed's allreduce_bucket_size, restated for eager issue).
COMM_OVERLAP = "overlap"
COMM_OVERLAP_MODE = "mode"
COMM_OVERLAP_MODE_DEFAULT = "off"
COMM_OVERLAP_OFF = "off"
COMM_OVERLAP_BUCKETED = "bucketed"
COMM_OVERLAP_MODES = (COMM_OVERLAP_OFF, COMM_OVERLAP_BUCKETED)
COMM_OVERLAP_BUCKET_MB = "bucket_mb"
COMM_OVERLAP_BUCKET_MB_DEFAULT = 25.0

#############################################
# Gradient accumulation fp32 buffer
#############################################
FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Sequence parallelism (ring attention; TPU-native extension, no reference key)
#############################################
SEQUENCE_PARALLEL = "sequence_parallel"
SEQUENCE_PARALLEL_ENABLED = "enabled"
SEQUENCE_PARALLEL_ENABLED_DEFAULT = False
SEQUENCE_PARALLEL_AXIS = "axis"
SEQUENCE_PARALLEL_AXIS_DEFAULT = "data"
SEQUENCE_PARALLEL_SCHEDULE = "schedule"
SEQUENCE_PARALLEL_SCHEDULE_DEFAULT = "zigzag"

#############################################
# Pipeline (engine-level block; PipelineModule takes most knobs in-code)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = "auto"
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0

#############################################
# ZeRO client-optimizer opt-in (reference constants: zero_allow_untested_optimizer)
#############################################
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

#############################################
# Key registry
#############################################
from .zero.constants import (ZERO_OPTIMIZATION,
                             ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED)
from .activation_checkpointing.config import ACTIVATION_CHKPT

# Every recognized TOP-LEVEL JSON config key. DeepSpeedConfig warns about any
# top-level key not in this set (reference parity: config.py:633-670 error/
# warning checks), and tests/unit/test_config_keys.py sweeps the registry
# asserting each key either changes engine-visible config state or emits a
# diagnostic — no key may silently no-op.
TOP_LEVEL_CONFIG_KEYS = frozenset({
    TRAIN_BATCH_SIZE,
    TRAIN_MICRO_BATCH_SIZE_PER_GPU,
    TRAIN_MICRO_BATCH_SIZE_PER_DEVICE,
    GRADIENT_ACCUMULATION_STEPS,
    SPARSE_GRADIENTS,
    OPTIMIZER,
    SCHEDULER,
    FP16,
    BF16,
    AMP,
    GRADIENT_CLIPPING,
    COMMUNICATION_DATA_TYPE,
    PRESCALE_GRADIENTS,
    FUSED_STEP,
    COMPILATION_CACHE_DIR,
    GRADIENT_PREDIVIDE_FACTOR,
    DISABLE_ALLGATHER,
    ALLREDUCE_ALWAYS_FP32,
    FP32_ALLREDUCE,
    STEPS_PER_PRINT,
    DUMP_STATE,
    VOCABULARY_SIZE,
    WALL_CLOCK_BREAKDOWN,
    MEMORY_BREAKDOWN,
    TENSORBOARD,
    TELEMETRY,
    NUMERICS,
    RESILIENCE,
    SERVING,
    COMM,
    SPARSE_ATTENTION,
    SEQUENCE_PARALLEL,
    PIPELINE,
    ZERO_OPTIMIZATION,
    ZERO_ALLOW_UNTESTED_OPTIMIZER,
    ACTIVATION_CHKPT,
    # deprecated boolean-zero companion (zero/config.py read_zero_config_deprecated)
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
})

# Recognized keys of the nested observability blocks. DeepSpeedConfig warns on
# any unknown key inside these dicts just like the top-level sweep — a typo'd
# "enable" must not silently leave a subsystem off.
TELEMETRY_CONFIG_KEYS = frozenset({
    TELEMETRY_ENABLED,
    TELEMETRY_TRACE_DIR,
    TELEMETRY_TRACE_STEPS,
    TELEMETRY_PERTURBING_BREAKDOWN,
    TELEMETRY_PEAK_TFLOPS,
    TELEMETRY_MFU_WINDOW,
    TELEMETRY_RECOMPILE_WARN,
    TELEMETRY_OUTPUT_PATH,
    TELEMETRY_JOB_NAME,
    TELEMETRY_PIPELINE_TRACE,
    TELEMETRY_ANATOMY,
    TELEMETRY_CLUSTER,
    TELEMETRY_GOODPUT,
    TELEMETRY_HBM,
    TELEMETRY_PROFILE,
    TELEMETRY_METRICS,
    TELEMETRY_ALERTS,
})

ANATOMY_CONFIG_KEYS = frozenset({
    ANATOMY_ENABLED,
    ANATOMY_CHIP,
    ANATOMY_PEAK_TFLOPS,
    ANATOMY_HBM_GBPS,
    ANATOMY_ICI_GBPS,
    ANATOMY_DCN_GBPS,
})

PIPELINE_TRACE_CONFIG_KEYS = frozenset({
    PIPELINE_TRACE_ENABLED,
    PIPELINE_TRACE_CAPACITY,
    PIPELINE_TRACE_DUMP_DIR,
})

CLUSTER_CONFIG_KEYS = frozenset({
    CLUSTER_ENABLED,
    CLUSTER_HEARTBEAT_INTERVAL,
    CLUSTER_HANG_DEADLINE_S,
    CLUSTER_DUMP_DIR,
    CLUSTER_STRAGGLER_THRESHOLD,
    CLUSTER_SIGNAL_PEERS,
    CLUSTER_WARMUP_STEPS,
})

GOODPUT_CONFIG_KEYS = frozenset({
    GOODPUT_ENABLED,
    GOODPUT_LEDGER_DIR,
    GOODPUT_EMIT_SCALARS,
    GOODPUT_EVAL_TAG,
})

HBM_CONFIG_KEYS = frozenset({
    HBM_ENABLED,
})

PROFILE_CONFIG_KEYS = frozenset({
    PROFILE_ENABLED,
    PROFILE_RECONCILE_TOLERANCE,
    PROFILE_EMIT_SCALARS,
})

METRICS_CONFIG_KEYS = frozenset({
    METRICS_ENABLED,
    METRICS_RING_LEN,
    METRICS_STRICT_CATALOG,
    METRICS_EXPORT_PATH,
})

ALERTS_CONFIG_KEYS = frozenset({
    ALERTS_ENABLED,
    ALERTS_RULES,
})

NUMERICS_CONFIG_KEYS = frozenset({
    NUMERICS_ENABLED,
    NUMERICS_SUBTREE_DEPTH,
    NUMERICS_AUDIT_INTERVAL,
    NUMERICS_DUMP_DIR,
    NUMERICS_RING_SIZE,
    NUMERICS_CONSECUTIVE_SKIP_TRIGGER,
    NUMERICS_TRIGGER_ON_NONFINITE_LOSS,
    NUMERICS_INSTALL_SIGNAL_HANDLERS,
})

SERVING_CONFIG_KEYS = frozenset({
    SERVING_ENABLED,
    SERVING_BLOCK_SIZE,
    SERVING_NUM_BLOCKS,
    SERVING_MAX_SEQS,
    SERVING_MAX_MODEL_LEN,
    SERVING_PREFILL_CHUNK,
    SERVING_USE_PALLAS_DECODE,
    SERVING_REQUEST_TRACE,
    SERVING_SHARDING,
    SERVING_PREFIX_CACHE,
    SERVING_SPECULATION,
    SERVING_FLEET,
})

SERVING_FLEET_CONFIG_KEYS = frozenset({
    SERVING_FLEET_REPLICAS,
    SERVING_FLEET_POLICY,
    SERVING_FLEET_AFFINITY_WEIGHT,
    SERVING_FLEET_MAX_QUEUE_DEPTH,
    SERVING_FLEET_OCCUPANCY_CAP,
    SERVING_FLEET_GOODPUT_FLOOR,
})

SERVING_SHARDING_CONFIG_KEYS = frozenset({
    SERVING_SHARDING_MODEL,
})

SERVING_PREFIX_CACHE_CONFIG_KEYS = frozenset({
    SERVING_PREFIX_CACHE_ENABLED,
})

SERVING_SPECULATION_CONFIG_KEYS = frozenset({
    SERVING_SPECULATION_ENABLED,
    SERVING_SPECULATION_DRAFT_MODEL,
    SERVING_SPECULATION_MAX_DRAFT_TOKENS,
    SERVING_SPECULATION_DRAFT_POOL_BLOCKS,
})

SERVING_REQUEST_TRACE_CONFIG_KEYS = frozenset({
    SERVING_REQUEST_TRACE_ENABLED,
    SERVING_REQUEST_TRACE_CAPACITY,
    SERVING_REQUEST_TRACE_ITERATION_CAPACITY,
    SERVING_REQUEST_TRACE_DUMP_DIR,
    SERVING_REQUEST_TRACE_SLO,
})

SERVING_SLO_CONFIG_KEYS = frozenset({
    SERVING_SLO_TTFT_MS,
    SERVING_SLO_TPOT_MS,
})

COMM_CONFIG_KEYS = frozenset({
    COMM_MODE,
    COMM_DCN_SLICES,
    COMM_COMPRESS_START_STEP,
    COMM_OVERLAP,
})

COMM_OVERLAP_CONFIG_KEYS = frozenset({
    COMM_OVERLAP_MODE,
    COMM_OVERLAP_BUCKET_MB,
})

RESILIENCE_CONFIG_KEYS = frozenset({
    RESILIENCE_ENABLED,
    RESILIENCE_SAVE_DIR,
    RESILIENCE_SAVE_INTERVAL,
    RESILIENCE_ASYNC_SAVE,
    RESILIENCE_AUTO_RESUME,
})
