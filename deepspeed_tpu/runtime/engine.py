"""DeepSpeedEngine: the core training wrapper.

TPU-native re-design of ``deepspeed/runtime/engine.py`` (DeepSpeedEngine l.96). The API
shape is preserved — ``forward``/``backward``/``step`` with gradient-accumulation boundary
semantics (engine.py:843-852), ``save_checkpoint``/``load_checkpoint``, progress reporting —
but the mechanics are functional JAX:

- the model is a pure function ``model_fn(params, *inputs) -> loss`` (or ``(loss, aux)``);
  in a functional framework the objective must live inside the traced function, so the
  torch pattern "outputs = engine(x); loss = criterion(outputs); engine.backward(loss)"
  becomes "loss = engine(x, y); engine.backward(loss); engine.step()".
- ``forward`` computes loss AND gradients in one fused jitted call (value_and_grad);
  ``backward`` accumulates them into a (ZeRO-sharded) buffer; ``step`` applies the update
  at the accumulation boundary inside a single jitted function with the overflow-skip,
  clipping, optimizer and loss-scale logic all on device.
- DP/ZeRO communication is not hand-written: batches are sharded over the mesh ``data``
  axis and master/optimizer state carries ZeRO layouts (zero/sharding.py), so XLA emits
  reduce-scatter/all-gather over ICI where the reference called NCCL
  (engine.py:1016-1089, stage2.py:682-745, 1441-1472).
"""

import functools
import os
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import adam as adam_opt
from ..ops import lamb as lamb_opt
from ..ops import sgd as sgd_opt
from ..parallel.mesh import DATA_AXIS, build_mesh, mesh_from_mpu
from ..utils import ThroughputTimer, SynchronizedWallClockTimer, log_dist, logger
from ..utils.cluster import named_scope as ds_named_scope
from .config import DeepSpeedConfig
from .constants import (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                        SGD_OPTIMIZER, ROUTE_TRAIN,
                        COMM_MODE_FLAT, COMM_MODE_COMPRESSED,
                        COMM_OVERLAP_BUCKETED)
from .dataloader import DeepSpeedDataLoader
from .fp16 import loss_scaler as ls
from .lr_schedules import get_scheduler
from .utils import (clip_grads_by_global_norm, detect_overflow, global_norm)
from .zero.sharding import replicated_sharding, zero_sharding

MEMORY_OPT_ALLREDUCE_SIZE = 500000000


class OptimizerHandle:
    """Host-side view of optimizer hyperparameters (the reference's param_groups,
    engine.py:503-650 / fp16/fused_optimizer.py:48-66).

    Group 0 holds the optimizer block's top-level hypers; each ``group_specs`` entry
    adds a group that inherits the base values and applies its overrides (lr,
    weight_decay, betas, eps). Leaf membership is decided elsewhere (the engine's
    group-index tree); the handle only owns the per-group scalars that schedulers
    mutate and ``current_hyper`` ships to the device each step."""

    def __init__(self, name: str, params: dict, group_specs=()):
        self.name = name
        params = params or {}

        def group_dict(overrides: dict) -> dict:
            hyper = adam_opt.hyper_from_params({**params, **overrides})
            return {"lr": hyper["lr"], "betas": (hyper["beta1"], hyper["beta2"]),
                    "eps": hyper["eps"], "weight_decay": hyper["weight_decay"]}

        self.param_groups = [group_dict({})]
        for spec in group_specs or ():
            overrides = {k: v for k, v in dict(spec).items()
                         if k in ("lr", "weight_decay", "betas", "eps")}
            self.param_groups.append(group_dict(overrides))

    def current_hyper(self) -> dict:
        gs = self.param_groups
        if len(gs) == 1:  # single group: 0-d scalars, the historical jit signature
            g = gs[0]
            return dict(lr=jnp.asarray(g["lr"], jnp.float32),
                        beta1=jnp.asarray(g["betas"][0], jnp.float32),
                        beta2=jnp.asarray(g["betas"][1], jnp.float32),
                        eps=jnp.asarray(g["eps"], jnp.float32),
                        weight_decay=jnp.asarray(g["weight_decay"], jnp.float32))
        return dict(
            lr=jnp.asarray([g["lr"] for g in gs], jnp.float32),
            beta1=jnp.asarray([g["betas"][0] for g in gs], jnp.float32),
            beta2=jnp.asarray([g["betas"][1] for g in gs], jnp.float32),
            eps=jnp.asarray([g["eps"] for g in gs], jnp.float32),
            weight_decay=jnp.asarray([g["weight_decay"] for g in gs], jnp.float32))

    def hyper_for_leaf_groups(self) -> list:
        """Host-side per-group hyper dicts (the offload path's view)."""
        return [dict(lr=g["lr"], beta1=g["betas"][0], beta2=g["betas"][1],
                     eps=g["eps"], weight_decay=g["weight_decay"])
                for g in self.param_groups]

    # schedulers poke param_groups[i]['lr'] directly

    def state_dict(self):
        return {"param_groups": [dict(g) for g in self.param_groups]}

    def load_state_dict(self, sd):
        for g, src in zip(self.param_groups, sd["param_groups"]):
            g.update(src)


_OPTIMIZER_APPLY = {
    # "Adam" is classic L2 Adam: the reference's v0.3.0 kernels fold wd*p into the
    # gradient before the moments (csrc/adam/cpu_adam.cpp:81-82,122 `grad = param *
    # _weight_decay + grad`; no adam_w_mode knob existed yet). "AdamW" is decoupled.
    ADAM_OPTIMIZER: (adam_opt.init,
                     functools.partial(adam_opt.apply, adamw=False)),
    ADAMW_OPTIMIZER: (adam_opt.init, adam_opt.apply),
    LAMB_OPTIMIZER: (lamb_opt.init, lamb_opt.apply),
    SGD_OPTIMIZER: (sgd_opt.init, sgd_opt.apply),
}


def make_engine(args=None, model=None, optimizer=None, model_parameters=None, training_data=None,
                lr_scheduler=None, mpu=None, dist_init_required=None, collate_fn=None,
                config_params=None):
    """Engine factory: dispatches to PipelineEngine for PipelineModule models
    (reference deepspeed/__init__.py:111-133)."""
    if dist_init_required is not False:
        # Join the multi-host world when the launcher configured one (reference
        # engine.py:129-149 did dist.init_process_group here). No-op single-process.
        from .dist import init_distributed
        init_distributed()
    from ..parallel.pipe.module import PipelineModule
    if isinstance(model, PipelineModule):
        from .pipe.engine import PipelineEngine
        assert mpu is None, "mpu is mutually exclusive with a PipelineModule model"
        return PipelineEngine(args=args, model=model, optimizer=optimizer,
                              model_parameters=model_parameters, training_data=training_data,
                              lr_scheduler=lr_scheduler, mpu=model.mpu(),
                              dist_init_required=dist_init_required, collate_fn=collate_fn,
                              config_params=config_params)
    return DeepSpeedEngine(args=args, model=model, optimizer=optimizer,
                           model_parameters=model_parameters, training_data=training_data,
                           lr_scheduler=lr_scheduler, mpu=mpu,
                           dist_init_required=dist_init_required, collate_fn=collate_fn,
                           config_params=config_params)


# sentinel marking a fused-step window in the pending-grads / grad-acc slots
# (the gradient tree never exists outside the fused jit)
_FUSED = object()


class DeepSpeedEngine:

    def __init__(self, args=None, model=None, optimizer=None, model_parameters=None,
                 training_data=None, lr_scheduler=None, mpu=None, dist_init_required=None,
                 collate_fn=None, config_params=None, mesh=None, param_shardings=None):
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.warn_unscaled_loss = True
        self._in_training = True

        # ---- mesh (first: its data-axis size is the config's DP world size) ----
        if mesh is not None:
            self.mesh = mesh
        elif mpu is not None:
            self.mesh = mesh_from_mpu(mpu)
        else:
            self.mesh = build_mesh(model=1, pipe=1)
        self.dp_size = self.mesh.shape[DATA_AXIS]

        # ---- config ----
        config_file = getattr(args, "deepspeed_config", None) if args is not None else None
        if config_params is not None:
            self.config = DeepSpeedConfig(config_params, world_size=self.dp_size)
        else:
            assert config_file is not None, "DeepSpeed requires --deepspeed_config or config_params"
            self.config = DeepSpeedConfig(config_file, world_size=self.dp_size)

        # ---- comm topology (hierarchical ICI+DCN collectives; docs/multislice.md) ----
        # Derived for every engine (the per-level desync audit and wire ledger
        # read the factorization); the MODE decides whether the grad exchange
        # actually routes through the two-level schedule.
        from ..comm import derive_topology
        self._comm_mode = self.config.comm_mode
        self._comm_topo = derive_topology(self.dp_size, self.config.comm_dcn_slices)
        if self._comm_mode != COMM_MODE_FLAT:
            if self.zero_optimization() and self.zero_cpu_offload():
                raise ValueError(
                    f"comm.mode={self._comm_mode!r} does not compose with "
                    "ZeRO-Offload (the host-tier step owns the grad layout)")
            if self.zero_optimization_stage() >= 3:
                raise ValueError(
                    f"comm.mode={self._comm_mode!r} requires ZeRO stage <= 2: the "
                    "two-level exchange runs in a shard_map with replicated "
                    "parameter in_specs, which would re-gather stage-3 sharded "
                    "parameters every step")
            if self.config.sparse_gradients_enabled:
                raise ValueError(
                    f"comm.mode={self._comm_mode!r} does not compose with "
                    "sparse_gradients (the row-sparse reduction owns the grad "
                    "exchange); pick one")
            if (self._comm_mode == COMM_MODE_COMPRESSED
                    and self.gradient_accumulation_steps() > 1
                    and self.config.optimizer_name != ONEBIT_ADAM_OPTIMIZER):
                raise ValueError(
                    "comm.mode='hierarchical_compressed' requires "
                    "gradient_accumulation_steps == 1: error-feedback compression "
                    "of per-micro-batch partial gradients would accumulate "
                    "compression error across the window")
        if self.config.comm_overlap_mode == COMM_OVERLAP_BUCKETED:
            # bucketed overlapped grad exchange (docs/overlap.md) runs the same
            # shard_map scaffold as hierarchical comm, so it inherits the same
            # composition limits even under comm.mode=flat
            if self.zero_optimization() and self.zero_cpu_offload():
                raise ValueError(
                    "comm.overlap.mode='bucketed' does not compose with "
                    "ZeRO-Offload (the host-tier step owns the grad layout)")
            if self.zero_optimization_stage() >= 3:
                raise ValueError(
                    "comm.overlap.mode='bucketed' requires ZeRO stage <= 2: the "
                    "bucketed exchange runs in a shard_map with replicated "
                    "parameter in_specs, which would re-gather stage-3 sharded "
                    "parameters every step")
            if self.config.sparse_gradients_enabled:
                raise ValueError(
                    "comm.overlap.mode='bucketed' does not compose with "
                    "sparse_gradients (the row-sparse reduction owns the grad "
                    "exchange); pick one")

        # ---- persistent compilation cache (opt-in; see constants.py) ----
        if self.config.compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir",
                              str(self.config.compilation_cache_dir))
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

        # ---- model function + params ----
        assert model is not None, "deepspeed.initialize requires a model"
        if hasattr(model, "apply"):
            # flax-style module: apply(params, *inputs)
            self.model_fn = model.apply
        elif callable(model):
            self.model_fn = model
        else:
            raise TypeError("model must be a flax-style module (.apply) or a callable "
                            "model_fn(params, *inputs) -> loss")
        self.module = model
        assert model_parameters is not None, ("model_parameters (the initialized parameter pytree) "
                                              "is required in the functional API")

        # ---- sequence parallelism (ring attention over the mesh axis) ----
        # The ``sequence_parallel`` config block swaps the loss fn for the model's
        # sequence-parallel build: tokens/labels stay in natural order at the API
        # boundary, the model shards them over the axis (zigzag layout by default)
        # and runs ring attention internally.
        if self.config.sequence_parallel_enabled:
            sp_build = getattr(model, "sequence_parallel_loss_fn", None)
            if sp_build is None:
                raise TypeError("sequence_parallel requires a model exposing "
                                "sequence_parallel_loss_fn(mesh, axis, schedule=...)")
            self.model_fn = sp_build(self.mesh, self.config.sequence_parallel_axis,
                                     schedule=self.config.sequence_parallel_schedule)

        # ---- precision policy ----
        if self.fp16_enabled():
            self.compute_dtype = jnp.float16
        elif self.bfloat16_enabled():
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32

        # ---- external-master client optimizers ----
        # A client (init, apply) pair whose apply carries ``external_master = True``
        # declares that it OWNS the parameter state it updates (e.g. the bench's
        # emulated ZeRO-2 rank, whose fp32 shard lives in opt_state and whose param
        # refresh would come from the missing ranks' all-gather): the engine then
        # holds NO master storage — master_params becomes a derived fp32 view of
        # the compute params (checkpoint save only) — and does not re-derive
        # compute params after the update. At dp=1 this removes the 4-bytes/param
        # master burden a real 1/dp rank never carries.
        client_apply = (optimizer[1] if isinstance(optimizer, tuple)
                        and len(optimizer) == 2 else None)
        self._external_master = bool(getattr(client_apply, "external_master", False))

        # ---- shardings ----
        zero_stage = self.zero_optimization_stage()
        self._repl = lambda tree: replicated_sharding(self.mesh, tree)
        master_fp32 = jax.tree_util.tree_map(lambda p: jnp.asarray(p, jnp.float32), model_parameters)
        # 1-bit Adam needs per-worker (unreduced) gradients: grads are kept stacked with a
        # leading dp axis sharded over 'data' (reference onebit_adam.py:335-336 relies on
        # engine.enable_backward_allreduce=False for the same effect).
        self._use_stacked_grads = (self.config.optimizer_name == ONEBIT_ADAM_OPTIMIZER
                                   and (optimizer is None or isinstance(optimizer, str)))
        if self._use_stacked_grads:
            assert zero_stage == 0, "1-bit Adam does not compose with ZeRO (reference parity)"
            assert param_shardings is None, "1-bit Adam requires replicated parameters"

        # ---- sparse (row-sparse embedding) gradients (reference engine.py:176-187) ----
        # The model declares which leaves are untied embedding tables via
        # sparse_grad_paths() (the reference auto-detected nn.Embedding modules; a
        # functional pytree has no module types to sniff).
        self._sparse_grad_flags = None
        # Optional model hint: sparse_grad_tokens(*batch) -> token positions in the
        # GLOBAL batch. Without it the engine assumes batch arg 0 is the token-id
        # tensor, which silently mis-sizes the row capacity for models whose first
        # positional input is something else.
        self._sparse_tokens_fn = getattr(model, "sparse_grad_tokens", None)
        if self.config.sparse_gradients_enabled and not self._use_stacked_grads:
            if param_shardings is not None or zero_stage >= 3:
                # the sparse-reduction shard_map pins replicated param in_specs,
                # so it is unavailable whenever params are sharded: under stage 3
                # (it would all-gather the sharded params every step — dense
                # reduction keeps the gather at use points only) and under
                # caller-provided layouts
                reason = ("with caller-provided param_shardings"
                          if param_shardings is not None
                          else "under ZeRO stage 3 (sharded parameters)")
                logger.warning(f"[deepspeed_tpu] sparse_gradients is inactive "
                               f"{reason}; using dense gradient reduction")
            elif (patterns := tuple(getattr(model, "sparse_grad_paths",
                                            lambda: ())())):
                from .sparse_tensor import match_sparse_paths
                paths = jax.tree_util.tree_flatten_with_path(master_fp32)[0]
                flags = []
                for path, leaf in paths:
                    pstr = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                                    for p in path)
                    flags.append(bool(leaf.ndim == 2 and match_sparse_paths(pstr, patterns)))
                self._sparse_grad_flags = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(master_fp32), flags)
                matched = sum(jax.tree_util.tree_leaves(self._sparse_grad_flags))
                logger.info(f"[deepspeed_tpu] sparse gradients enabled for {matched} "
                            f"embedding leaves (patterns={patterns})")
                if matched == 0:
                    self._sparse_grad_flags = None
            else:
                logger.warning("sparse_gradients requested but the model defines no "
                               "sparse_grad_paths(); falling back to dense reduction")
        if param_shardings is not None:
            # caller-provided layout (pipe-stacked stages, TP-sharded weights, ...);
            # ZeRO composes on top by claiming a free data-divisible axis per leaf
            from .zero.sharding import merge_zero_into
            self._master_shardings = merge_zero_into(self.mesh, param_shardings, master_fp32,
                                                     zero_stage)
            # stage 3: compute params adopt the merged (caller + data-axis) layout —
            # full parameter sharding on top of pipe/TP
            self._param_shardings = (self._master_shardings if zero_stage >= 3
                                     else param_shardings)
            self._grad_shardings = (self._master_shardings if zero_stage >= 2
                                    else param_shardings)
        else:
            self._master_shardings = zero_sharding(self.mesh, master_fp32, zero_stage)
            # stage 3 (parameter sharding — beyond the v0.3.0 reference, which stops
            # at stage 2): the bf16 compute params themselves carry the data-axis
            # layout; XLA all-gathers each leaf at its use point in forward/backward
            # (the later ZeRO-3's gather-on-use, as a GSPMD annotation) and the
            # updated master casts back to the SAME sharded layout — per-device
            # param HBM scales as 1/dp.
            self._param_shardings = (self._master_shardings if zero_stage >= 3
                                     else replicated_sharding(self.mesh, master_fp32))
            if self._use_stacked_grads:
                self._grad_shardings = jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, P(DATA_AXIS)), master_fp32)
            else:
                # stage 2: accumulated grads live reduce-scattered; stage<=1: replicated
                self._grad_shardings = (zero_sharding(self.mesh, master_fp32, zero_stage)
                                        if zero_stage >= 2 else replicated_sharding(self.mesh, master_fp32))
        self._zero_sharded_fraction = None
        if zero_stage >= 1 and self.dp_size > 1:
            # observability: zero_spec leaves awkward leaves replicated by policy —
            # surface what fraction of master/optimizer bytes actually sharded
            # (Adam moments mirror the master layout, so one count covers both)
            from .zero.sharding import sharding_coverage
            sharded_b, total_b = sharding_coverage(self._master_shardings, master_fp32)
            self._zero_sharded_fraction = sharded_b / max(total_b, 1)
            log_dist(
                f"ZeRO-{zero_stage}: {sharded_b / 2**20:.1f}/{total_b / 2**20:.1f} MiB "
                f"({self._zero_sharded_fraction:.1%}) of master+optimizer"
                + ("+parameter" if zero_stage >= 3 else "")
                + f" state sharded over data={self.dp_size}"
                + ("" if self._zero_sharded_fraction > 0.9 else
                   " — mostly REPLICATED (no dp-divisible axes / leaves under min_size);"
                   " per-rank memory will not scale as 1/dp"),
                ranks=[0])

        # ---- ZeRO-Offload: master weights + optimizer state live in host DRAM ----
        # (reference stage2.py:333-349 keeps fp32 master/grads pinned on host and steps
        # DeepSpeedCPUAdam there; on a TPU-VM "host" is the VM's DRAM tier). The host
        # buffers are PARTITIONED by the ZeRO master layout: each process stores and
        # steps only the regions its addressable devices own (the reference's
        # per-DP-rank single_partition_of_fp32_groups, stage2.py:750-907), so offload
        # composes with multi-host runs and per-host DRAM/compute scale as 1/dp.
        self._offload = None
        if self.zero_optimization() and self.zero_cpu_offload():
            from ..ops.cpu_adam import DeepSpeedCPUAdam
            # non-Adam optimizers are rejected later by _configure_optimizer's
            # Adam/AdamW assert; absent optimizer block defaults to "adam" (L2),
            # matching the _OPTIMIZER_APPLY default for the non-offload path
            _offload_name = self.config.optimizer_name or ADAM_OPTIMIZER
            zc = self.config.zero_config
            self._offload = DeepSpeedCPUAdam(
                master_fp32,
                adamw=(_offload_name == ADAMW_OPTIMIZER),
                shardings=self._master_shardings,
                pipeline=zc.offload_pipeline,
                pipeline_depth=zc.offload_pipeline_depth,
                max_region_elements=zc.offload_max_region_elements)
        elif self._external_master:
            # no engine-held master at all: the optimizer owns parameter state, and
            # the master_params property derives an fp32 VIEW of the compute params
            # on access (checkpoint save). Keeping a real copy would either occupy
            # 4 bytes/param of HBM (the exact dp=1 burden this mode removes) or
            # require a full-model D2H at construction (minutes over the relay).
            pass
        else:
            self.master_params = jax.device_put(master_fp32, self._master_shardings)
        self.params = jax.device_put(
            jax.tree_util.tree_map(lambda p: p.astype(self.compute_dtype), master_fp32),
            self._param_shardings)

        # ---- optimizer ----
        self._configure_optimizer(optimizer)

        # ---- loss scaler state ----
        self._dynamic_scale = self.fp16_enabled() and self.config.loss_scale == 0
        if self.fp16_enabled():
            self.scaler_state = ls.init_state(self.config.loss_scale, self.config.initial_scale_power,
                                              self.config.hysteresis)
        else:
            self.scaler_state = ls.init_state(1.0)  # scale fixed at 1

        # ---- grad accumulation buffer ----
        self._grad_acc = None  # lazily zero-initialized with grad shardings
        self._pending_grads = None
        self._pending_loss = None
        self._window_losses = []  # per-accumulation-window losses for monitor emission
        self._last_grad_norm = None

        # ---- lr scheduler ----
        self._configure_lr_scheduler(lr_scheduler)

        # ---- dataloader ----
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)
        self.data_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

        # ---- timers ----
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu() * self.dp_size,
            num_workers=1,
            steps_per_output=self.steps_per_print(),
            monitor_memory=False)

        # module-level activation-checkpointing config (reference engine.py:385-400).
        # Only push settings into the process-global module when THIS config carries
        # the block — a second engine without one must not clobber the first's setup.
        from .activation_checkpointing import checkpointing as act_ckpt
        if self.config.activation_checkpointing_config.configured_in_json:
            act_ckpt.configure(deepspeed_config=self.config, mesh=self.mesh)
        else:
            act_ckpt.set_default_mesh(self.mesh)

        # ---- scalar monitor (reference tensorboard wiring, engine.py:151-152, 246-261) ----
        self.monitor = None
        if self.config.tensorboard_enabled:
            from ..utils.monitor import SummaryMonitor
            self.monitor = SummaryMonitor(self.config.tensorboard_output_path or None,
                                          self.config.tensorboard_job_name)

        # ---- telemetry (docs/telemetry.md): compile watchdog, trace windows,
        # non-perturbing step metrics + resource ledger. Created BEFORE
        # _compile_steps so the step programs compile through the watchdog.
        self.telemetry = None
        if self.config.telemetry_enabled:
            from ..utils.telemetry import TelemetrySession
            anatomy_spec = None
            if self.config.telemetry_anatomy_enabled:
                # step-anatomy roofline spec (docs/anatomy.md): resolved once
                # here so every program the watchdog captures is priced
                # against the same chip model
                from ..utils.roofline import resolve_spec
                anatomy_spec = resolve_spec(
                    self.config.telemetry_anatomy_chip,
                    self.config.telemetry_anatomy_peak_tflops,
                    self.config.telemetry_anatomy_hbm_gbps,
                    self.config.telemetry_anatomy_ici_gbps,
                    self.config.telemetry_anatomy_dcn_gbps)
            # with anatomy on and no explicit MFU peak, price measured MFU off
            # the same chip spec as the ceiling — the two are only comparable
            # against one denominator
            peak_tflops = (self.config.telemetry_peak_tflops
                           or (anatomy_spec.peak_tflops if anatomy_spec
                               else 0.0))
            self.telemetry = TelemetrySession(
                monitor=self.monitor,
                peak_tflops=peak_tflops or None,
                trace_dir=self.config.telemetry_trace_dir or None,
                trace_steps=self.config.telemetry_trace_steps,
                mfu_window=self.config.telemetry_mfu_window,
                recompile_warn=self.config.telemetry_recompile_warn,
                output_path=self.config.telemetry_output_path or None,
                job_name=self.config.telemetry_job_name,
                anatomy_spec=anatomy_spec)
            # measured-time profile observatory (docs/profile.md): configured
            # BEFORE _compile_steps so every step program's compile also
            # records the scope/collective identity catalog the trace
            # ingester joins on — host-side text analysis only, the compiled
            # step is HLO-instruction-identical on or off (pinned in tests)
            if self.config.telemetry_profile_enabled:
                self.telemetry.configure_profile(
                    True,
                    reconcile_tolerance=(
                        self.config.telemetry_profile_reconcile_tolerance),
                    emit_scalars=(
                        self.config.telemetry_profile_emit_scalars))
            if self._comm_topo.is_hierarchical:
                # per-axis wire ledger: split every program's collective bytes
                # into ICI (intra-slice) vs DCN (cross-slice) — installed before
                # _compile_steps so the step programs analyze against it
                self.telemetry.set_comm_topology(
                    self._comm_topo.slice_device_sets(self.mesh))
            # metric catalog router + alert plane (docs/metrics.md,
            # docs/alerts.md): hooks the SummaryMonitor so EVERY observatory's
            # scalars resolve against the declared catalog and land in the
            # per-host ring; alert rules evaluate on the end_step boundary.
            # Host bookkeeping only — the step programs stay
            # HLO-instruction-identical with these blocks on (tested).
            if self.config.telemetry_metrics_enabled \
                    or self.config.telemetry_alerts_enabled:
                self.telemetry.configure_metrics(
                    ring_len=self.config.telemetry_metrics_ring_len,
                    strict=self.config.telemetry_metrics_strict_catalog,
                    export_path=(self.config.telemetry_metrics_export_path
                                 or None))
            if self.config.telemetry_alerts_enabled:
                self.telemetry.configure_alerts(
                    rules=self.config.telemetry_alerts_rules)

        # ---- numerics observatory (docs/numerics.md): in-graph sentinel,
        # loss-scale journal, cross-rank desync audit, flight recorder. Built
        # BEFORE _compile_steps so the step programs fold the per-subtree
        # bucketing into the already-jitted update (no extra host syncs).
        self._numerics = None
        self._sentinel_index = None
        self._pending_sentinel = None
        self._audit_fn_cached = None
        if self.config.numerics_enabled:
            from ..utils.numerics import (FlightRecorder, NumericsMonitor,
                                          build_subtree_index)
            self._sentinel_index = build_subtree_index(
                master_fp32, self.config.numerics_subtree_depth)
            journal = None
            if self.fp16_enabled():
                # host shadow of the device scaler — seeded from config, never
                # from a device fetch (ls.init_state uses the same derivation)
                init_scale = (float(self.config.loss_scale)
                              if self.config.loss_scale and self.config.loss_scale > 0
                              else float(2 ** self.config.initial_scale_power))
                journal = ls.LossScaleJournal(
                    self._dynamic_scale, init_scale,
                    scale_window=self.config.loss_scale_window,
                    min_scale=self.config.min_loss_scale,
                    hysteresis=self.config.hysteresis)
            recorder = FlightRecorder(
                capacity=self.config.numerics_ring_size,
                dump_dir=self.config.numerics_dump_dir or "numerics_dumps",
                telemetry=self.telemetry,
                host_id=jax.process_index())
            recorder.install(self.config.numerics_install_signal_handlers)
            self._numerics = NumericsMonitor(
                self._sentinel_index, monitor=self.monitor,
                telemetry=self.telemetry, journal=journal, recorder=recorder,
                audit_interval=self.config.numerics_audit_interval,
                consecutive_skip_trigger=self.config.numerics_consecutive_skip_trigger,
                trigger_on_nonfinite_loss=self.config.numerics_trigger_on_nonfinite_loss)
            # page-severity alerts dump through the same flight recorder, so
            # the post-mortem bundle carries the metric ring + alert state
            if self.telemetry is not None \
                    and self.telemetry.alert_engine is not None:
                self.telemetry.alert_engine.recorder = recorder

        # ---- cluster observatory (docs/cluster.md): cross-host heartbeat
        # aggregation, straggler naming, hang watchdog. Entirely host-side —
        # the step programs stay HLO-instruction-identical with this block
        # enabled (tested), same as every other observatory.
        self._cluster = None
        if self.telemetry is not None and self.config.telemetry_cluster_enabled:
            from ..utils.cluster import ClusterMonitor
            cluster_recorder = (self._numerics.recorder
                                if self._numerics is not None else None)
            cluster_dump_dir = None
            if cluster_recorder is None:
                # no numerics recorder to ride: give the watchdog its own
                from ..utils.numerics import FlightRecorder
                cluster_dump_dir = (self.config.telemetry_cluster_dump_dir
                                    or "cluster_dumps")
                cluster_recorder = FlightRecorder(
                    capacity=64, dump_dir=cluster_dump_dir,
                    telemetry=self.telemetry, host_id=jax.process_index())
            self._cluster = ClusterMonitor(
                telemetry=self.telemetry,
                recorder=cluster_recorder,
                heartbeat_interval=self.config.telemetry_cluster_heartbeat_interval,
                hang_deadline_s=self.config.telemetry_cluster_hang_deadline_s,
                straggler_threshold=self.config.telemetry_cluster_straggler_threshold,
                signal_peers=self.config.telemetry_cluster_signal_peers,
                warmup_steps=self.config.telemetry_cluster_warmup_steps,
                dump_dir=cluster_dump_dir)
            # heartbeat history + clock offsets ride along in every dump so
            # cluster-dump / timeline --cluster can merge hosts coherently
            cluster_recorder.cluster = self._cluster
            if self.telemetry.alert_engine is not None \
                    and self.telemetry.alert_engine.recorder is None:
                # no numerics recorder took the alert plane: page alerts dump
                # through the cluster watchdog's recorder instead
                self.telemetry.alert_engine.recorder = cluster_recorder

        # ---- run-lifecycle goodput ledger (docs/goodput.md): classifies the
        # run's entire wall-clock into a closed badput taxonomy (init, compile,
        # productive_step, checkpoint_stall, restart_replay, hang,
        # straggler_skew, eval, host_gap) with an exact-partition invariant.
        # Opened HERE, before _compile_steps, so construction-time compiles
        # land in the ledger. Pure host arithmetic over timestamps the other
        # observatories already took — the step programs stay
        # HLO-instruction-identical with this block enabled (tested).
        self._goodput = None
        if self.telemetry is not None and self.config.telemetry_goodput_enabled:
            from ..utils.goodput import RunLedger
            gp_recorder = (self._numerics.recorder
                           if self._numerics is not None else None)
            if gp_recorder is None and self._cluster is not None:
                gp_recorder = self._cluster.recorder
            ledger_dir = (self.config.telemetry_goodput_ledger_dir
                          or (gp_recorder.dump_dir
                              if gp_recorder is not None else None)
                          or "goodput_ledgers")
            if gp_recorder is not None:
                run_id = gp_recorder.run_id
            else:
                from ..utils.numerics import default_run_id
                run_id = default_run_id()
            self._goodput = RunLedger(
                run_id=run_id, host=jax.process_index(),
                ledger_dir=ledger_dir,
                eval_tag=self.config.telemetry_goodput_eval_tag)
            # carve-out baselines: compile seconds, watchdog fires, and
            # checkpoint saves are cumulative counters; the ledger bills
            # per-step deltas
            self._goodput_compile_base = 0.0
            self._goodput_hang_base = 0
            self._goodput_saves_base = 0
            self._goodput_init_open = True
            if self._cluster is not None:
                self._cluster.goodput = self._goodput

        self._compile_steps()

        # ---- HBM observatory (docs/hbm.md): install the per-class resident-
        # byte manifest into the telemetry session. Pure host arithmetic over
        # abstract shapes/shardings — no device work, and the compiled step is
        # HLO-instruction-identical with the block on or off (pinned in tests).
        if self.telemetry is not None and self.config.telemetry_hbm_enabled:
            from ..utils import hbm as _hbm
            manifest = self.memory_manifest()
            _, class_bytes = _hbm.manifest_signatures(manifest)
            self.telemetry.set_memory_manifest(
                class_bytes, geometry=manifest.get("geometry"))

        # ---- resilience (docs/resilience.md): periodic async checkpointing +
        # flight-recorder-driven auto-resume. Everything here is host-side —
        # the save hook snapshots committed step state and commits in a
        # background thread — so with the block disabled the lowered step
        # programs are HLO-instruction-identical to a build without it.
        self._resilience = None
        if self.config.resilience_enabled and self.config.resilience_save_dir:
            from ..resilience.async_ckpt import AsyncCheckpointer
            self._resilience = AsyncCheckpointer(
                self, self.config.resilience_save_dir)
            if self.config.resilience_auto_resume:
                from ..resilience.auto_resume import auto_resume
                _, _, resume_info = auto_resume(
                    self, self.config.resilience_save_dir)
                if self._goodput is not None and resume_info is not None:
                    # restart-replay billing: steps between the restore point
                    # and the pre-crash step are work the run already paid for
                    # once. The pre-crash step is the flight recorder's first
                    # bad step (exclusive — re-running IT is new work) or,
                    # after a clean preemption, the dump's last recorded step.
                    stop = resume_info.get("first_bad_step")
                    if stop is not None:
                        stop = int(stop) - 1
                    elif self._numerics is not None:
                        from ..utils.numerics import scan_dump_dir
                        bundle = scan_dump_dir(
                            self._numerics.recorder.dump_dir) or {}
                        span = bundle.get("span") or {}
                        stop = span.get("last_step")
                    if stop is not None:
                        self._goodput.set_replay_until(int(stop))

        if self.config.dump_state:
            self.config.print("DeepSpeedEngine configuration")

    # ------------------------------------------------------------------ state views
    # Under ZeRO-Offload the fp32 master and Adam moments live in the host-tier flat
    # buffers; these properties materialize fresh tree views on access so checkpointing
    # always sees the current state (leaf views alias the flat buffers where the region
    # layout is contiguous, and are assembled copies otherwise).
    @property
    def master_params(self):
        if getattr(self, "_offload", None) is not None:
            return self._offload.params_tree()
        if getattr(self, "_external_master", False):
            # The optimizer owns parameter state (its fp32 shard lives in
            # opt_state, checkpointed with it); the engine-level master is a
            # DERIVED fp32 view of the compute params, materialized on access for
            # checkpoint save. There is no separate storage to restore into —
            # the setter is a no-op (a loaded master equals this view upcast).
            return jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), self.params)
        return self._master_params_store

    @master_params.setter
    def master_params(self, value):
        if getattr(self, "_external_master", False):
            return
        self._master_params_store = value

    @property
    def opt_state(self):
        if getattr(self, "_offload", None) is not None:
            from ..ops.adam import AdamState
            return AdamState(exp_avg=self._offload.exp_avg_tree(),
                             exp_avg_sq=self._offload.exp_avg_sq_tree())
        return self._opt_state_store

    @opt_state.setter
    def opt_state(self, value):
        self._opt_state_store = value

    # ------------------------------------------------------------------ config accessors
    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def steps_per_print(self):
        return self.config.steps_per_print

    def zero_optimization(self):
        return self.config.zero_enabled

    def zero_optimization_stage(self):
        return self.config.zero_optimization_stage

    def zero_cpu_offload(self):
        return self.config.zero_config.cpu_offload

    @property
    def offload_step_timing(self):
        """Last offload step's timing: aggregate lanes (fetch_wait/host_adam/push/total),
        lane busy sums (fetch_busy/push_busy), pipeline shape (pipeline_depth/
        region_cap/n_work_items) and per-region records — None before the first step
        or when offload is disabled. See DeepSpeedCPUAdam.step_regions."""
        return self._offload.last_step_timing if self._offload is not None else None

    def fp16_enabled(self):
        return self.config.fp16_enabled

    def bfloat16_enabled(self):
        return self.config.bf16_enabled

    def gradient_clipping(self):
        return self.config.gradient_clipping

    def allreduce_always_fp32(self):
        return self.config.allreduce_always_fp32

    def wall_clock_breakdown(self):
        # With telemetry active, the barrier-per-section breakdown timers are
        # perturbing instrumentation (each section boundary drains the device
        # queue, serializing the async dispatch telemetry exists to preserve):
        # they run only behind the explicit telemetry.perturbing_breakdown flag.
        if self.telemetry is not None:
            if self.config.telemetry_perturbing_breakdown:
                self.telemetry.warn_perturbing_once()
                return True
            if self.config.wall_clock_breakdown:
                self.telemetry.note_breakdown_suppressed_once()
            return False
        return self.config.wall_clock_breakdown

    def _watch(self, name, jitted):
        """Route a jitted step program through the telemetry compile watchdog
        (identity when telemetry is off)."""
        if self.telemetry is None or jitted is None:
            return jitted
        return self.telemetry.watch(name, jitted)

    def dynamic_loss_scale(self):
        return self._dynamic_scale

    def loss_scale(self):
        return float(jax.device_get(self.scaler_state.cur_scale))

    def get_lr(self):
        return [g["lr"] for g in self.optimizer.param_groups]

    def get_mom(self):
        return [g["betas"] for g in self.optimizer.param_groups]

    # ------------------------------------------------------------------ setup
    def _build_group_index(self, specs):
        """Per-leaf STATIC group ids from pattern specs: leaf paths matching
        ``specs[i]['pattern']`` (first match wins) belong to group i+1; unmatched
        leaves to the base group 0. The analog of the reference's torch param_groups
        lists (engine.py:503-650) for a functional pytree, where leaves are named by
        path, not identity — the BERT no-decay recipe is
        ``[{"pattern": "bias|LayerNorm|ln_", "weight_decay": 0.0}]``."""
        import re
        treedef = jax.tree_util.tree_structure(self.params)
        paths = jax.tree_util.tree_flatten_with_path(self.params)[0]
        compiled = [re.compile(s["pattern"]) for s in specs]
        ids, counts = [], [0] * (len(specs) + 1)
        for path, _ in paths:
            pstr = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                            for p in path)
            gi = 0
            for i, rx in enumerate(compiled):
                if rx.search(pstr):
                    gi = i + 1
                    break
            ids.append(gi)
            counts[gi] += 1
        log_dist(f"optimizer param groups: {counts[0]} base leaves + "
                 f"{counts[1:]} per pattern group", ranks=[0])
        return jax.tree_util.tree_unflatten(treedef, ids)

    def _configure_optimizer(self, client_optimizer):
        # per-group hyperparameters: JSON config wins, else an optional model hook
        # (patterns over leaf paths; see _build_group_index)
        specs = (self.config.optimizer_params or {}).get("param_groups")
        if not specs:
            hook = getattr(self.module, "param_group_patterns", None)
            specs = tuple(hook()) if callable(hook) else ()
        specs = tuple(specs or ())
        self._group_index = self._build_group_index(specs) if specs else None
        if self._offload is not None:
            # Host-tier optimizer: the engine steps DeepSpeedCPUAdam directly
            # (reference engine.py:560-566 requires the cpu_adam op under ZeRO-Offload).
            name = self.config.optimizer_name or ADAM_OPTIMIZER
            assert name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER), \
                f"ZeRO-Offload supports Adam/AdamW (got {name!r})"
            assert client_optimizer is None or isinstance(client_optimizer, str), \
                "ZeRO-Offload steps the host-side DeepSpeedCPUAdam; client optimizers unsupported"
            self.optimizer = OptimizerHandle(name, self.config.optimizer_params or {},
                                             group_specs=specs)
            log_dist("Using ZeRO-Offload: host-tier DeepSpeedCPUAdam "
                     f"({'native' if self._offload._lib is not None else 'numpy'} kernel, "
                     f"{self._offload.numel} local master elements)", ranks=[0])
            return
        if client_optimizer is not None and not isinstance(client_optimizer, str):
            # client-provided (init, apply) pair or OptimizerHandle-compatible object
            if isinstance(client_optimizer, tuple) and len(client_optimizer) == 2:
                assert not specs, ("param_groups require a built-in optimizer; a client "
                                   "(init, apply) pair has no groups kwarg contract")
                if self.config.zero_enabled:
                    # reference engine.py:521-528: unknown optimizers under ZeRO need an
                    # explicit opt-in (sharded state layouts are derived from the state
                    # tree the client's init returns; untested shapes may shard poorly)
                    assert self.config.zero_allow_untested_optimizer, (
                        'You are using an untested ZeRO Optimizer. Please add '
                        '<"zero_allow_untested_optimizer": true> in the configuration '
                        'file to use it.')
                    log_dist("**** You are using ZeRO with an untested optimizer, "
                             "proceed with caution *****", ranks=[0])
                self._opt_init, self._opt_apply = client_optimizer
                self.optimizer = OptimizerHandle("client", self.config.optimizer_params or {})
            else:
                raise TypeError("client optimizer must be an (init_fn, apply_fn) pair; "
                                "torch optimizers are not supported on TPU")
        else:
            name = self.config.optimizer_name or ADAM_OPTIMIZER
            if name == ONEBIT_ADAM_OPTIMIZER:
                assert not specs, "1-bit Adam runs a single param group (compressed " \
                                  "error feedback is not per-group)"
                from ..ops import onebit_adam as onebit
                freeze_step = (self.config.optimizer_params or {}).get("freeze_step", 100000)
                # under a non-flat comm mode the frozen-phase momentum exchange
                # runs the two-level ICI+DCN schedule instead of the flat
                # compressed allreduce (docs/multislice.md)
                onebit_topo = (self._comm_topo
                               if self._comm_mode != COMM_MODE_FLAT else None)
                self._onebit = onebit.OneBitAdam(freeze_step=freeze_step, dp_size=self.dp_size,
                                                 mesh=self.mesh, topology=onebit_topo)
                self._opt_init, self._opt_apply = self._onebit.init, self._onebit.apply
            elif name in _OPTIMIZER_APPLY:
                self._opt_init, self._opt_apply = _OPTIMIZER_APPLY[name]
                if self._group_index is not None:
                    self._opt_apply = functools.partial(self._opt_apply,
                                                        groups=self._group_index)
            else:
                raise ValueError(f"Unrecognized optimizer {name!r}")
            self.optimizer = OptimizerHandle(name, self.config.optimizer_params or {},
                                             group_specs=specs)
        init = self._opt_init
        if self._external_master:
            # the master is a derived view (see the master_params property) — never
            # materialize it here. init sees an ABSTRACT fp32 master for shapes and
            # a zero master for values: an external-master optimizer owns its own
            # state, so by contract its init reads master SIZES, not values.
            abstract_master = jax.tree_util.tree_map(
                lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32), self.params)
            opt_state_zero = jax.eval_shape(init, abstract_master)
            params_treedef = jax.tree_util.tree_structure(abstract_master)
        else:
            abstract_master = None
            opt_state_zero = jax.eval_shape(init, self.master_params)
            params_treedef = jax.tree_util.tree_structure(self.master_params)
        # optimizer states mirror the master-param tree (Adam moments, momentum buffers):
        # give each params-shaped field the master sharding so ZeRO/pipe layouts carry over

        def field_shardings(field):
            if jax.tree_util.tree_structure(field) == params_treedef:
                return self._master_shardings
            return replicated_sharding(self.mesh, field)

        if hasattr(self, "_onebit"):
            self._opt_shardings = self._onebit.state_shardings(self.mesh)
        elif hasattr(opt_state_zero, "_fields"):
            self._opt_shardings = type(opt_state_zero)(*[field_shardings(f) for f in opt_state_zero])
        elif jax.tree_util.tree_structure(opt_state_zero) == params_treedef:
            self._opt_shardings = self._master_shardings
        else:
            # Unknown client state shape: replicate rather than guess a wrong ZeRO axis
            # (a caller layout like pipe-stacked stages would otherwise be violated).
            logger.warning("client optimizer state does not mirror the param tree; "
                           "optimizer state will be replicated")
            self._opt_shardings = replicated_sharding(self.mesh, opt_state_zero)
        if self._external_master:
            # init sees the REAL master values (master == params at construction):
            # the fp32 upcast happens inside the jit, so leaves are freed as init
            # consumes them (and fold away entirely for size-only inits) — no
            # resident fp32 master tree is ever created.
            self.opt_state = jax.jit(
                lambda p: init(jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), p)),
                out_shardings=self._opt_shardings)(self.params)
        else:
            self.opt_state = jax.jit(init, out_shardings=self._opt_shardings)(self.master_params)
        log_dist(f"Using DeepSpeed Optimizer param name {self.optimizer.name}", ranks=[0])

    def _configure_lr_scheduler(self, client_lr_scheduler):
        if client_lr_scheduler is not None:
            self.lr_scheduler = client_lr_scheduler
        elif self.config.scheduler_name is not None:
            self.lr_scheduler = get_scheduler(self.config.scheduler_name, self.optimizer,
                                              self.config.scheduler_params or {})
            log_dist(f"DeepSpeed using configured LR scheduler = {self.config.scheduler_name}", ranks=[0])
        else:
            self.lr_scheduler = None

    def deepspeed_io(self, dataset, batch_size=None, route=ROUTE_TRAIN, data_sampler=None,
                     collate_fn=None, num_local_io_workers=None):
        if batch_size is None:
            batch_size = self.train_micro_batch_size_per_gpu() * self.dp_size
        return DeepSpeedDataLoader(dataset, batch_size=batch_size,
                                   collate_fn=collate_fn or self.collate_fn,
                                   data_parallel_world_size=self.dp_size)

    # ------------------------------------------------------------------ jitted step functions
    def _compile_steps(self):
        self._run_fused_step = None   # set on the fused gas==1 paths below
        self._fused_pending = None
        self._jit_fused = None        # the fused jit object, for flops_profile
        self._overlap_plan = None     # set when comm.overlap=bucketed is live
        grad_acc_steps = self.gradient_accumulation_steps()
        fp16 = self.fp16_enabled()
        clip = float(self.gradient_clipping() or 0.0)
        compute_dtype = self.compute_dtype
        model_fn = self.model_fn
        opt_apply = getattr(self, "_opt_apply", None)  # None under ZeRO-Offload (host step)
        dynamic = self._dynamic_scale
        scale_window = self.config.loss_scale_window
        min_scale = self.config.min_loss_scale
        hysteresis = self.config.hysteresis
        predivide = float(self.config.gradient_predivide_factor or 1.0)
        prescale = self.config.prescale_gradients
        use_stacked = self._use_stacked_grads
        # numerics sentinel: a STATIC trace-time switch. When None the step
        # functions return their historical tuples with the historical ops —
        # HLO-instruction-identical to pre-sentinel programs by construction.
        sentinel_index = self._sentinel_index
        if sentinel_index is not None:
            from ..utils.numerics import bucket_sumsq
        # ZeRO stage >= 2 and ZeRO-Offload keep device grads in the compute dtype —
        # the reference's fp16 grad partitions (stage2.py:333-349, upcast only at the
        # fp32 master update) — halving the grad HBM footprint that bounds max model
        # size per chip. Stage <= 1 keeps fp32 grads (the reference's fp32 allreduce
        # option); the optimizer update always upcasts per-leaf inside its fused loop.
        # `allreduce_always_fp32` (reference engine.py:1016-1089 upcasts the allreduce
        # tensor) and `communication_data_type` override the default: grads are
        # produced in grad_dtype, so the psum XLA inserts over the data axis rides
        # the wire in exactly this dtype.
        zero_stage_ = self.zero_optimization_stage()
        grad_dtype = (compute_dtype if (self._offload is not None or zero_stage_ >= 2)
                      else jnp.float32)
        if self.config.allreduce_always_fp32:
            grad_dtype = jnp.float32
        if self.config.communication_data_type is not None:
            grad_dtype = {"fp32": jnp.float32, "fp16": jnp.float16,
                          "bf16": jnp.bfloat16}[self.config.communication_data_type]
        self._grad_dtype = grad_dtype

        def local_loss_and_grad(params, scale, *batch):
            # named_scope is HLO metadata only (zero instructions — asserted by
            # tests/unit/test_telemetry.py), so the trace annotation is unconditional
            with ds_named_scope("ds_fwd_bwd"):
                def scaled_loss_fn(p):
                    out = model_fn(p, *batch)
                    loss = out[0] if isinstance(out, (tuple, list)) else out
                    factor = scale / grad_acc_steps
                    if prescale:
                        factor = factor / predivide
                    return loss * factor, loss
                (_, loss), grads = jax.value_and_grad(scaled_loss_fn, has_aux=True)(params)
                grads = jax.tree_util.tree_map(lambda g: g.astype(grad_dtype), grads)
            return loss, grads

        def shard_mapped_loss_and_grad(reduce_grads, grad_out_specs):
            """shard_map scaffold shared by the stacked (1-bit Adam) and sparse
            reduction modes: replicated params, data-sharded batch, pmean'd loss;
            only the per-leaf grad handling differs."""
            from ..parallel.mesh import shard_map
            param_specs = jax.tree_util.tree_map(lambda _: P(), self.params)

            def loss_and_grad(params, scale, *batch):
                def local(params, scale, *local_batch):
                    loss, grads = local_loss_and_grad(params, scale, *local_batch)
                    return jax.lax.pmean(loss, DATA_AXIS), reduce_grads(grads, batch)

                batch_specs = tuple(P(DATA_AXIS) for _ in batch)
                fn = shard_map(local, mesh=self.mesh,
                               in_specs=(param_specs, P()) + batch_specs,
                               out_specs=(P(), grad_out_specs), check_vma=False)
                return fn(params, scale, *batch)

            return loss_and_grad

        # comm.overlap=bucketed (docs/overlap.md): issue the grad exchange per
        # size-bounded bucket instead of as one monolithic post-backward vector,
        # so each bucket's collectives depend only on its own backward subtree
        # and can overlap the remaining backward compute (and, hierarchically,
        # each other's DCN phase). Inert when another subsystem owns the
        # exchange or there is nothing to exchange (dp == 1).
        overlap_requested = self.config.comm_overlap_mode == COMM_OVERLAP_BUCKETED
        overlap_active = (overlap_requested and not use_stacked
                          and self._sparse_grad_flags is None
                          and self.dp_size > 1 and self._offload is None)
        if overlap_requested and not overlap_active and self.dp_size > 1:
            logger.warning(
                "[deepspeed_tpu] comm.overlap.mode='bucketed' requested but the "
                "gradient exchange is owned elsewhere (1-bit Adam stacked grads "
                "or sparse-gradient reduction); overlap is inert")

        if self._use_stacked_grads:
            # 1-bit Adam path: keep per-worker grads stacked over a leading dp axis
            # instead of letting XLA psum them — the compressed allreduce in the optimizer
            # replaces the gradient averaging (reference disables engine allreduce when
            # frozen, onebit_adam.py:372).
            loss_and_grad = shard_mapped_loss_and_grad(
                lambda grads, batch: jax.tree_util.tree_map(lambda g: g[None], grads),
                jax.tree_util.tree_map(lambda _: P(DATA_AXIS), self.params))
        elif self._sparse_grad_flags is not None and self.dp_size > 1:
            # sparse_gradients mode (reference engine.py:1091-1147): embedding-table
            # grads are reduced by gathering (indices, values) over the data axis
            # instead of a dense psum; all other grads pmean as usual. shard_map
            # replaces XLA's automatic reduction so we control the per-leaf strategy.
            from .sparse_tensor import row_sparse_allreduce
            sparse_flags = self._sparse_grad_flags
            sparse_tokens_fn = self._sparse_tokens_fn
            if sparse_tokens_fn is None:
                logger.warning(
                    "[deepspeed_tpu] sparse_gradients: no sparse_grad_tokens() hint on "
                    "the model; sizing the sparse row capacity from batch arg 0 when it "
                    "is an integer token-id tensor, else falling back to dense reduction")
            dp = self.dp_size

            def reduce_sparse(grads, batch):
                # A token position contributes at most one nonzero row per table,
                # so local token count exactly bounds the sparse row capacity.
                if sparse_tokens_fn is not None:
                    global_tokens = int(sparse_tokens_fn(*batch))
                elif batch and hasattr(batch[0], "dtype") and \
                        jnp.issubdtype(batch[0].dtype, jnp.integer):
                    global_tokens = int(np.prod(batch[0].shape))
                else:
                    # no hint and arg 0 is not a token-id tensor: a guessed capacity
                    # could silently DROP gradient rows — use the dense reduction
                    return jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, DATA_AXIS), grads)
                local_tokens = global_tokens // dp
                flat, treedef = jax.tree_util.tree_flatten(grads)
                flat_flags = jax.tree_util.tree_leaves(sparse_flags)
                reduced = []
                for g, is_sparse in zip(flat, flat_flags):
                    cap = min(local_tokens, g.shape[0]) if is_sparse else 0
                    # sparse gather ships dp*cap rows; dense psum ships rows/...: only
                    # gather when the table is genuinely sparse this step
                    if is_sparse and cap * dp < g.shape[0]:
                        reduced.append(row_sparse_allreduce(g, DATA_AXIS, capacity=cap))
                    else:
                        reduced.append(jax.lax.pmean(g, DATA_AXIS))
                return jax.tree_util.tree_unflatten(treedef, reduced)

            loss_and_grad = shard_mapped_loss_and_grad(
                reduce_sparse, jax.tree_util.tree_map(lambda _: P(), self.params))
        elif overlap_active:
            # bucketed overlapped exchange (docs/overlap.md): the same two-level
            # schedule as the hierarchical branch below, issued once per bucket
            # under a ds_grad_bucket{k} named_scope. Per element the reduction
            # tree is unchanged, so the result is bit-equal to the monolithic
            # exchange given the same topology (and, under comm.mode=flat, each
            # bucket degenerates to a plain psum — the flat exchange up to an
            # exact power-of-two rescale). Under hierarchical_compressed this
            # is also the full-precision warmup phase.
            from ..comm.hierarchical import bucket_plan, bucketed_two_level_mean
            from ..comm.topology import CommTopology
            topo = (self._comm_topo if self._comm_mode != COMM_MODE_FLAT
                    else CommTopology(self.dp_size, 1))
            bucket_bytes = int(self.config.comm_overlap_bucket_mb * (1 << 20))
            plan = bucket_plan(self.params, bucket_bytes, self.dp_size)
            self._overlap_plan = plan
            self._overlap_topo = topo

            def reduce_overlap(grads, batch):
                del batch
                leaves, treedef = jax.tree_util.tree_flatten(grads)
                out = bucketed_two_level_mean(leaves, plan, topo)
                return jax.tree_util.tree_unflatten(treedef, out)

            loss_and_grad = shard_mapped_loss_and_grad(
                reduce_overlap, jax.tree_util.tree_map(lambda _: P(), self.params))
        elif self._comm_mode != COMM_MODE_FLAT and self.dp_size > 1:
            # hierarchical comm (docs/multislice.md): the gradient exchange runs
            # the explicit two-level schedule — reduce-scatter within each slice
            # over ICI, allreduce across slices over DCN, all-gather within the
            # slice — instead of GSPMD's flat single-axis psum. One division at
            # the end, same placement as the flat pmean. Under
            # hierarchical_compressed this full-precision path is also the
            # warmup phase (forward() switches to the compressed program at
            # comm.compress_start_step).
            from ..comm.hierarchical import (flatten_tree, unflatten_tree,
                                             tree_size, two_level_sum,
                                             padded_size)
            topo = self._comm_topo
            dp = self.dp_size
            n_total = tree_size(self.params)
            n_pad = padded_size(n_total, dp)

            def reduce_hier(grads, batch):
                del batch
                vec, recipe = flatten_tree(grads)
                vec = jnp.pad(vec, (0, n_pad - n_total))
                mean = two_level_sum(vec, topo) / dp
                return unflatten_tree(mean[:n_total].astype(grad_dtype), recipe)

            loss_and_grad = shard_mapped_loss_and_grad(
                reduce_hier, jax.tree_util.tree_map(lambda _: P(), self.params))
        else:
            loss_and_grad = local_loss_and_grad

        # The fused single-jit paths inline `loss_and_grad` directly. That
        # historically required the plain local grad path; the bucketed overlap
        # exchange is the one shard_mapped reduction that composes (its
        # value_and_grad runs INSIDE the shard_map body, so nothing
        # differentiates through the shard_map) — except under
        # hierarchical_compressed, whose warmup->compressed program switch in
        # forward() needs the two-jit step.
        fused_grad_ok = (loss_and_grad is local_loss_and_grad
                         or (overlap_active
                             and self._comm_mode != COMM_MODE_COMPRESSED))
        if self.config.fused_step and not (
                grad_acc_steps == 1 and fused_grad_ok
                and self._offload is None and not self._cpu_checkpointing_active()):
            # warn HERE (the offload path returns early below and would otherwise
            # swallow the flag silently): the user must not believe the fused
            # step's HBM saving is active when it is not
            logger.warning(
                "[deepspeed_tpu] fused_step requested but ineligible (it needs "
                "gradient_accumulation_steps == 1 and the plain local grad path "
                "or the bucketed overlap exchange — no 1-bit Adam stacked "
                "grads, sparse-gradient reduction, non-overlapped hierarchical "
                "comm, compressed comm, ZeRO-Offload, or cpu activation "
                "checkpointing); using the two-jit step")

        # Inputs carry their shardings (params/batch were device_put with the right
        # layouts); out_shardings on the grads is what makes stage-2 store them
        # reduce-scattered instead of materializing full replicas.
        # Exception: host-offloaded remat residuals introduce side-effecting
        # placement custom-calls that XLA's SPMD partitioner refuses to combine
        # with explicit (esp. replicated) out_shardings — there we let XLA pick
        # output layouts and the downstream jits re-shard via their in_shardings.
        # The choice is deferred to first forward (see _jit_loss_and_grad) so a
        # Megatron-style act_ckpt.configure(checkpoint_in_cpu=True) AFTER engine
        # construction still lands on the compatible jit.
        self._loss_and_grad_fn = loss_and_grad
        self._jit_loss_and_grad_cached = None
        self._jit_eval_cached = None

        # ---- compressed comm scaffold (comm.mode=hierarchical_compressed) ----
        # A second grad program carrying the persistent error-feedback buffers:
        # forward() runs it once global_steps reaches comm.compress_start_step
        # (the 1-bit two-phase rule: full-precision warmup, compressed after).
        # EF state is engine-held (it belongs to the EXCHANGE, not the
        # optimizer) and starts zeroed at the phase switch.
        self._loss_and_grad_comm_fn = None
        self._jit_loss_and_grad_comm_cached = None
        self._comm_we = self._comm_se = None
        if (self._comm_mode == COMM_MODE_COMPRESSED and not use_stacked
                and self._sparse_grad_flags is None and self.dp_size > 1):
            from ..comm.hierarchical import (flatten_tree, unflatten_tree,
                                             tree_size, grad_segment_ids,
                                             two_level_compressed,
                                             bucketed_error_state_shapes,
                                             bucketed_two_level_compressed,
                                             error_state_shapes, padded_size)
            from ..parallel.mesh import shard_map
            topo = self._comm_topo
            if overlap_active:
                # bucketed EF layout (docs/overlap.md): the persistent error
                # buffers hold the per-bucket chunks back to back, and each
                # bucket compresses with its OWN per-tensor scale segments —
                # same telescoping contract per bucket, different (chunked)
                # scale boundaries than the monolithic exchange.
                plan = self._overlap_plan
                param_leaves = jax.tree_util.tree_leaves(self.params)
                seg_consts, n_segs_list = [], []
                for b in plan:
                    sn = grad_segment_ids(
                        [param_leaves[i] for i in b["leaf_indices"]], b["n_pad"])
                    seg_consts.append(jnp.asarray(sn))
                    n_segs_list.append(int(sn.max()) + 1)
                we_shape, se_shape = bucketed_error_state_shapes(plan, topo)
            else:
                n_total = tree_size(self.params)
                n_pad = padded_size(n_total, self.dp_size)
                seg_np = grad_segment_ids(self.params, n_pad)
                n_segs = int(seg_np.max()) + 1
                seg_const = jnp.asarray(seg_np)
                we_shape, se_shape = error_state_shapes(n_pad, topo)
            ef_sharding = NamedSharding(self.mesh, P(DATA_AXIS, None))
            self._comm_we = jax.device_put(jnp.zeros(we_shape, jnp.float32),
                                           ef_sharding)
            self._comm_se = jax.device_put(jnp.zeros(se_shape, jnp.float32),
                                           ef_sharding)
            param_specs = jax.tree_util.tree_map(lambda _: P(), self.params)
            grad_specs = jax.tree_util.tree_map(lambda _: P(), self.params)

            def loss_and_grad_comm(params, scale, we, se, *batch):
                def local(params, scale, we_row, se_row, *local_batch):
                    loss, grads = local_loss_and_grad(params, scale, *local_batch)
                    if overlap_active:
                        leaves, treedef = jax.tree_util.tree_flatten(grads)
                        out, new_we, new_se = bucketed_two_level_compressed(
                            leaves, we_row[0], se_row[0], plan, topo,
                            seg_consts, n_segs_list)
                        grads_out = jax.tree_util.tree_unflatten(treedef, out)
                    else:
                        vec, recipe = flatten_tree(grads)
                        # compression runs in fp32: the sign + per-segment scale
                        # IS the wire format, whatever grad_dtype is
                        vec = jnp.pad(vec.astype(jnp.float32),
                                      (0, n_pad - n_total))
                        out, new_we, new_se = two_level_compressed(
                            vec, we_row[0], se_row[0], topo, seg_const, n_segs)
                        grads_out = unflatten_tree(
                            out[:n_total].astype(grad_dtype), recipe)
                    return (jax.lax.pmean(loss, DATA_AXIS), grads_out,
                            new_we[None], new_se[None])

                batch_specs = tuple(P(DATA_AXIS) for _ in batch)
                fn = shard_map(local, mesh=self.mesh,
                               in_specs=(param_specs, P(), P(DATA_AXIS, None),
                                         P(DATA_AXIS, None)) + batch_specs,
                               out_specs=(P(), grad_specs, P(DATA_AXIS, None),
                                          P(DATA_AXIS, None)),
                               check_vma=False)
                return fn(params, scale, we, se, *batch)

            self._loss_and_grad_comm_fn = loss_and_grad_comm

        # Per-microbatch grads stay in the compute dtype (halves the backward HBM
        # footprint) but the ACCUMULATOR is fp32 when the window spans multiple
        # micro-batches: bf16 a+g loses mantissa bits as the window grows and
        # loss-scaled fp16 sums can overflow mid-window. The reference accumulates into
        # fp32 host buffers (stage2.py async CPU grad accumulation) — matching numerics
        # costs one fp32 accumulator.
        acc_dtype = (jnp.float32 if (grad_dtype != jnp.float32 and grad_acc_steps > 1)
                     else grad_dtype)
        self._acc_dtype = acc_dtype

        def accumulate(acc, grads):
            with ds_named_scope("ds_accumulate"):
                return jax.tree_util.tree_map(lambda a, g: a + g.astype(acc_dtype), acc, grads)

        self._jit_accumulate = self._watch("accumulate", jax.jit(
            accumulate,
            in_shardings=(self._grad_shardings, self._grad_shardings),
            out_shardings=self._grad_shardings,
            donate_argnums=(0,)))
        # (no donation: a compute-dtype buffer can't back the wider fp32 output)
        self._jit_adopt_acc = (None if acc_dtype == grad_dtype else self._watch("adopt_acc", jax.jit(
            lambda g: jax.tree_util.tree_map(lambda x: x.astype(acc_dtype), g),
            in_shardings=(self._grad_shardings,),
            out_shardings=self._grad_shardings)))

        def prep_grads(acc_grads, scaler_state):
            """Shared update prologue (standard + external-master paths): fp16
            overflow check and unscale, optional predivide, global norm, clip.
            With the numerics sentinel enabled, additionally returns per-subtree
            grad sumsq + nonfinite counts (the global norm and overflow bool are
            then DERIVED from those vectors — one pass over the tree either way,
            and no extra collectives)."""
            scale = scaler_state.cur_scale
            overflow, nonfinite = detect_overflow(acc_grads, fp16, sentinel_index)
            if fp16:
                inv = jnp.where(scale > 0, 1.0 / scale, 1.0)

                def unscale(g):
                    # bf16 spans fp32's exponent range, so a power-of-two unscale is
                    # an exact exponent shift in-dtype (no fp32-tree materialization).
                    # fp16's narrow exponent would flush small unscaled grads to zero
                    # — exactly what loss scaling protects — so fp16 unscales through
                    # fp32 (costing the fp32 grad copy the reference also pays at its
                    # fp32 master update, fused there into the optimizer).
                    if g.dtype == jnp.float16:
                        return g.astype(jnp.float32) * inv
                    return g * inv.astype(g.dtype)

                grads = jax.tree_util.tree_map(unscale, acc_grads)
            else:
                grads = acc_grads  # scale fixed at 1
            if prescale and predivide != 1.0:
                grads = jax.tree_util.tree_map(
                    lambda g: g * jnp.asarray(predivide, g.dtype), grads)
            if use_stacked:
                # stacked per-worker grads: the logical gradient is the worker mean —
                # clip/report on that, not on the sqrt(dp)-inflated stacked norm
                norm_tree = jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), grads)
            else:
                norm_tree = grads
            if sentinel_index is not None:
                gss = bucket_sumsq(norm_tree, sentinel_index)
                norm = jnp.sqrt(jnp.sum(gss))
                sent = {"grad_sumsq": gss, "grad_nonfinite": nonfinite}
            else:
                norm = global_norm(norm_tree)
                sent = None
            if clip > 0:
                grads = clip_grads_by_global_norm(grads, clip, norm=norm)
            return grads, overflow, norm, sent

        def apply_update(master, opt_state, scaler_state, acc_grads, params, step, hyper):
            grads, overflow, norm, sent = prep_grads(acc_grads, scaler_state)

            def do_update(_):
                return opt_apply(grads, opt_state, master, step, hyper)

            def skip_update(_):
                return master, opt_state

            with ds_named_scope("ds_apply_update"):
                new_master, new_opt = jax.lax.cond(overflow, skip_update, do_update, operand=None)
            new_scaler = ls.update(scaler_state, overflow, dynamic=dynamic, scale_window=scale_window,
                                   min_scale=min_scale, hysteresis=hysteresis)
            # params enter only to donate their buffer to the re-cast output
            del params
            new_params = jax.tree_util.tree_map(lambda p: p.astype(compute_dtype), new_master)
            if sent is not None:
                # weight norm + update magnitude per subtree (update is exactly
                # zero on a skipped step — the cond selected the old master)
                sent = dict(sent,
                            weight_sumsq=bucket_sumsq(new_master, sentinel_index),
                            update_sumsq=bucket_sumsq(
                                jax.tree_util.tree_map(lambda a, b: a - b,
                                                       new_master, master),
                                sentinel_index))
                return new_master, new_opt, new_scaler, new_params, overflow, norm, sent
            return new_master, new_opt, new_scaler, new_params, overflow, norm

        if self._offload is not None:
            # Host-tier step: the only device work is (a) one cheap stats pass for the
            # global grad norm + fp16 overflow flag (replicated scalars — XLA inserts
            # the cross-host psum the reference did with allreduce, stage2.py:1399-1415)
            # and (b) the all-gather that turns the pushed master-sharded compute-dtype
            # partitions back into the replicated/caller param layout (the reference's
            # all_gather of updated fp16 partitions, stage2.py:1441-1472).
            scalar = NamedSharding(self.mesh, P())

            def grad_stats(grads):
                overflow, nonfinite = detect_overflow(grads, fp16, sentinel_index)
                if sentinel_index is not None:
                    gss = bucket_sumsq(grads, sentinel_index)
                    return (jnp.sqrt(jnp.sum(gss)), overflow,
                            {"grad_sumsq": gss, "grad_nonfinite": nonfinite})
                return global_norm(grads), overflow

            stats_out = ((scalar, scalar) if sentinel_index is None else
                         (scalar, scalar, {"grad_sumsq": scalar,
                                           "grad_nonfinite": scalar}))
            self._jit_grad_stats = self._watch(
                "grad_stats", jax.jit(grad_stats, out_shardings=stats_out))
            same_layout = all(
                m.is_equivalent_to(p, l.ndim)
                for m, p, l in zip(jax.tree_util.tree_leaves(self._master_shardings),
                                   jax.tree_util.tree_leaves(self._param_shardings),
                                   jax.tree_util.tree_leaves(self.params)))
            self._jit_offload_push = (None if same_layout else self._watch(
                "offload_push", jax.jit(lambda t: t, out_shardings=self._param_shardings)))
            return  # no jitted optimizer update; Adam runs on the host tier

        scalar_shard = NamedSharding(self.mesh, P())
        scaler_shards = jax.tree_util.tree_map(lambda _: scalar_shard, self.scaler_state)
        # per-subtree sentinel vectors are tiny replicated arrays
        grad_sent_shards = {"grad_sumsq": scalar_shard, "grad_nonfinite": scalar_shard}
        full_sent_shards = dict(grad_sent_shards, weight_sumsq=scalar_shard,
                                update_sumsq=scalar_shard)
        if self._external_master:
            # The optimizer owns its parameter state: the update touches only
            # opt_state (there is no engine master, and compute params are not
            # re-derived — a real ZeRO rank refreshes them from the all-gather of
            # every rank's updated shard).
            def apply_update_ext(opt_state, scaler_state, acc_grads, step, hyper):
                grads, overflow, norm, sent = prep_grads(acc_grads, scaler_state)

                def do_update(_):
                    _, new_state = opt_apply(grads, opt_state, None, step, hyper)
                    return new_state

                with ds_named_scope("ds_apply_update"):
                    new_opt = jax.lax.cond(overflow, lambda _: opt_state, do_update,
                                           operand=None)
                new_scaler = ls.update(scaler_state, overflow, dynamic=dynamic,
                                       scale_window=scale_window, min_scale=min_scale,
                                       hysteresis=hysteresis)
                if sent is not None:
                    # no engine-held master here: the sentinel carries grad stats
                    # only (weight/update norms need master storage)
                    return new_opt, new_scaler, overflow, norm, sent
                return new_opt, new_scaler, overflow, norm

            ext_out = (self._opt_shardings, scaler_shards, scalar_shard, scalar_shard)
            if sentinel_index is not None:
                ext_out = ext_out + (grad_sent_shards,)
            self._jit_apply_update = self._watch("apply_update", jax.jit(
                apply_update_ext,
                out_shardings=ext_out,
                # donate the grad buffer too (the standard path donates arg 3): at
                # 1.5B the undonated fp32 grad tree would raise peak HBM through
                # the update by a full param-tree
                donate_argnums=(0, 2)))

            # Fused single-jit train step (gas == 1): forward+backward+update in ONE
            # program, so the full gradient tree never materializes as jit outputs —
            # XLA frees each grad leaf as soon as the optimizer consumed it. The
            # two-jit split must hold params + activations + the ENTIRE grad tree
            # simultaneously, which is exactly the ~1 param-tree of HBM that keeps a
            # 1.5B dp=1 run off the remat=dots policy (measured: dots@8 OOMs split,
            # fits fused — the same structure as a hand-rolled one-jit rank step).
            # Semantics: the update runs at forward() and is COMMITTED at step();
            # forward/backward/step must rotate strictly (enforced in forward()).
            if grad_acc_steps == 1 and fused_grad_ok:
                def fused_step(opt_state, scaler_state, params, step, hyper, *batch):
                    loss, grads = loss_and_grad(params, scaler_state.cur_scale,
                                                *batch)
                    grads, overflow, norm, sent = prep_grads(grads, scaler_state)

                    def do_update(_):
                        _, new_state = opt_apply(grads, opt_state, None, step, hyper)
                        return new_state

                    with ds_named_scope("ds_apply_update"):
                        new_opt = jax.lax.cond(overflow, lambda _: opt_state, do_update,
                                               operand=None)
                    new_scaler = ls.update(scaler_state, overflow, dynamic=dynamic,
                                           scale_window=scale_window,
                                           min_scale=min_scale, hysteresis=hysteresis)
                    if sent is not None:
                        return loss, new_opt, new_scaler, overflow, norm, sent
                    return loss, new_opt, new_scaler, overflow, norm

                fused_out = (scalar_shard, self._opt_shardings, scaler_shards,
                             scalar_shard, scalar_shard)
                if sentinel_index is not None:
                    fused_out = fused_out + (grad_sent_shards,)
                jit_fused = self._watch("fused_step", jax.jit(
                    fused_step,
                    out_shardings=fused_out,
                    donate_argnums=(0,)))
                self._jit_fused = jit_fused  # exposed for flops_profile

                def run_fused(batch):
                    step_no = jnp.asarray(self.global_steps + 1 - self.skipped_steps,
                                          jnp.int32)
                    outs = jit_fused(
                        self.opt_state, self.scaler_state, self.params, step_no,
                        self.optimizer.current_hyper(), *batch)
                    if sentinel_index is not None:
                        loss, new_opt, new_scaler, overflow, norm, sent = outs
                    else:
                        (loss, new_opt, new_scaler, overflow, norm), sent = outs, None
                    self.opt_state = new_opt
                    self.scaler_state = new_scaler
                    return loss, (overflow, norm, sent)

                self._run_fused_step = run_fused
            return

        std_out = (self._master_shardings, self._opt_shardings, scaler_shards,
                   self._param_shardings, scalar_shard, scalar_shard)
        if sentinel_index is not None:
            std_out = std_out + (full_sent_shards,)
        self._jit_apply_update = self._watch("apply_update", jax.jit(
            apply_update,
            out_shardings=std_out,
            donate_argnums=(0, 1, 3, 4)))

        # Opt-in fused step for STANDARD engines ({"fused_step": true}, gas == 1):
        # same single-program structure as the external-master fused step — the
        # grad tree never materializes as jit outputs, buying ~1 param-tree of HBM
        # headroom (the margin that decides the remat policy for large dp=1 runs).
        # The update executes at forward() with master/opt/params adopted
        # immediately (their buffers are donated); step() commits bookkeeping, and
        # strict forward/backward/step rotation is enforced in forward().
        if (self.config.fused_step and grad_acc_steps == 1
                and fused_grad_ok
                and not self._cpu_checkpointing_active()):
            def fused_step_std(master, opt_state, scaler_state, params, step, hyper,
                               *batch):
                # the whole two-jit pipeline inlined: value_and_grad feeds the
                # SAME apply_update body (overflow skip, scaler, param re-cast)
                loss, grads = loss_and_grad(params, scaler_state.cur_scale,
                                            *batch)
                return (loss,) + apply_update(master, opt_state, scaler_state,
                                              grads, params, step, hyper)

            fused_std_out = (scalar_shard,) + std_out
            jit_fused_std = self._watch("fused_step", jax.jit(
                fused_step_std,
                out_shardings=fused_std_out,
                donate_argnums=(0, 1, 3)))
            self._jit_fused = jit_fused_std  # exposed for flops_profile

            def run_fused_std(batch):
                step_no = jnp.asarray(self.global_steps + 1 - self.skipped_steps,
                                      jnp.int32)
                outs = jit_fused_std(
                    self.master_params, self.opt_state, self.scaler_state,
                    self.params, step_no, self.optimizer.current_hyper(), *batch)
                if sentinel_index is not None:
                    (loss, new_master, new_opt, new_scaler, new_params, overflow,
                     norm, sent) = outs
                else:
                    (loss, new_master, new_opt, new_scaler, new_params, overflow,
                     norm), sent = outs, None
                self.master_params = new_master
                self.opt_state = new_opt
                self.scaler_state = new_scaler
                self.params = new_params
                return loss, (overflow, norm, sent)

            self._run_fused_step = run_fused_std

    # ------------------------------------------------------------------ lint hooks
    @staticmethod
    def _lint_dtype_name(dt):
        name = jnp.dtype(dt).name
        return {"float16": "f16", "bfloat16": "bf16", "float32": "f32"}.get(name, name)

    def lint_programs(self, sample_batch):
        """[(name, jitted, args, manifest)] for every jitted program on this
        engine's ACTIVE step path, with the expected-collective manifest the
        program lint passes diff against the optimized HLO (docs/lint.md).

        The manifests encode the claims the bespoke HLO tests pin one path at
        a time: ZeRO>=2 backward crosses the data axis with a reduction (and
        with NOTHING param-scale besides it — a full-parameter all-gather here
        is the regression the suite exists to catch), the update re-gathers
        params only when the engine master is actually scattered, and the
        collective dtype is exactly the resolved grad/comm dtype. Budgets
        count only results above the small-element threshold, so scalar loss
        pmeans and norm reductions ride free.
        """
        batch = tuple(x if isinstance(x, jax.Array) else self.shard_batch(x)
                      for x in sample_batch)
        scale = self.scaler_state.cur_scale
        step = jnp.asarray(1, jnp.int32)
        hyper = self.optimizer.current_hyper()
        compute = self._lint_dtype_name(self.compute_dtype)
        grad_dt = self._lint_dtype_name(self._grad_dtype)
        dp = self.dp_size
        zstage = self.zero_optimization_stage()
        gas = self.gradient_accumulation_steps()

        def grads_like(dt, shardings):
            return jax.tree_util.tree_map(
                lambda p, s: jax.ShapeDtypeStruct(p.shape, dt, sharding=s),
                self.params, shardings)

        # the backward's cross-data reduction rides in exactly grad_dtype; with
        # the bucketed overlap exchange live there is one reduction PER BUCKET
        # (the per-bucket count is the structural claim — a re-fused monolithic
        # exchange would fail this floor)
        n_buckets = len(self._overlap_plan) if self._overlap_plan else 0
        red = ({"min": max(1, n_buckets), "dtypes": [grad_dt]} if dp > 1
               else {"max": 0})
        gather_gate = {"all-gather": {"min": 1, "dtypes": [compute, "f32"]}}
        comm_hier = (self._comm_mode != COMM_MODE_FLAT
                     and not self._use_stacked_grads
                     and self._sparse_grad_flags is None and dp > 1)
        lg_man = {
            "compute_dtype": compute,
            "any_reduction": red,
            # ZeRO-3 re-gathers params in forward; below stage 3 any large
            # all-gather in the backward is an undeclared-collective violation.
            # Hierarchical comm's intra-slice all-gather (level 3 of the
            # two-level schedule, one per bucket when overlapped) is a
            # declared exception.
            "collectives": (dict(gather_gate) if zstage >= 3 else
                            ({"all-gather": {"min": max(1, n_buckets),
                                             "dtypes": sorted({grad_dt, "f32"})}}
                             if comm_hier else {})),
            "donation": {"check_unusable": True},
            "strict": True,
        }
        if n_buckets:
            # bucketing scatters each bucket's chunk over the mesh; the
            # smallest per-bucket shard must still cross the large-collective
            # floor or the per-bucket reduction count could not be enforced
            lg_man["small_element_threshold"] = max(
                8, min(b["n_pad"] for b in self._overlap_plan) // dp - 1)
        local_man = {"compute_dtype": compute, "strict": True,
                     "donation": {"check_unusable": True}}
        progs = []

        if self._offload is not None:
            g_in = grads_like(self._grad_dtype, self._grad_shardings)
            progs.append(("loss_and_grad", self._jit_loss_and_grad,
                          (self.params, scale) + batch, lg_man))
            progs.append(("grad_stats", self._jit_grad_stats, (g_in,),
                          dict(local_man)))
            if self._jit_offload_push is not None:
                push_in = grads_like(self.compute_dtype, self._master_shardings)
                progs.append(("offload_push", self._jit_offload_push, (push_in,),
                              dict(local_man,
                                   collectives={"all-gather": {"min": 1,
                                                               "dtypes": [compute]}})))
            return progs

        scattered_master = (not self._external_master) and any(
            not s.is_fully_replicated
            for s in jax.tree_util.tree_leaves(self._master_shardings))

        if self._run_fused_step is not None:
            f_man = {"compute_dtype": compute, "any_reduction": red,
                     "collectives": dict(gather_gate) if scattered_master else {},
                     "donation": {"check_unusable": True}, "strict": True}
            if n_buckets:
                f_man["small_element_threshold"] = \
                    lg_man["small_element_threshold"]
                if comm_hier:
                    # the bucketed two-level exchange's intra-slice gathers
                    # appear inside the fused step too
                    f_man["collectives"] = dict(
                        f_man["collectives"],
                        **{"all-gather": {"min": max(1, n_buckets),
                                          "dtypes": sorted({grad_dt, "f32",
                                                            compute})}})
            if self._external_master:
                args = (self.opt_state, self.scaler_state, self.params, step,
                        hyper) + batch
            else:
                args = (self.master_params, self.opt_state, self.scaler_state,
                        self.params, step, hyper) + batch
            progs.append(("fused_step", self._jit_fused, args, f_man))
            return progs

        progs.append(("loss_and_grad", self._jit_loss_and_grad,
                      (self.params, scale) + batch, lg_man))
        if self._loss_and_grad_comm_fn is not None:
            # frozen-phase compressed exchange: sign payloads ride as packed u8
            # (or raw s8 when the sub-chunk defeats packing) over the DCN
            # all-to-all / all-gather; the per-segment scales and the ICI
            # reduce-scatter stay f32
            comm_man = {
                "compute_dtype": compute,
                "any_reduction": {"min": 1, "dtypes": ["f32"]},
                "collectives": {
                    "all-gather": {"min": max(1, n_buckets),
                                   "dtypes": sorted({"f32", "u8", "s8", grad_dt})},
                    "all-to-all": {"min": max(1, n_buckets),
                                   "dtypes": ["s8", "u8"]},
                },
                # the 1-bit phases ship PACKED signs: n/8 u8 elements, far below
                # the default large-collective floor at test scale — lower it so
                # the sign exchange is linted, while per-segment scale gathers
                # (~n_segs elements) still ride free. Bucketing splits the sign
                # payload per bucket, so the overlapped program needs the floor
                # one notch lower for the smallest bucket's 16-element piece.
                "small_element_threshold": 8 if n_buckets else 16,
                "donation": {"check_unusable": True},
                "strict": True,
            }
            progs.append(("loss_and_grad_comm", self._jit_loss_and_grad_comm,
                          (self.params, scale, self._comm_we, self._comm_se)
                          + batch, comm_man))
        acc_in = grads_like(self._acc_dtype, self._grad_shardings)
        if gas > 1:
            g_in = grads_like(self._grad_dtype, self._grad_shardings)
            progs.append(("accumulate", self._jit_accumulate, (acc_in, g_in),
                          dict(local_man)))
        au_man = {
            "compute_dtype": compute,
            "collectives": dict(gather_gate) if scattered_master else {},
            "donation": {"check_unusable": True},
            "strict": True,
        }
        if self._external_master:
            # the client update is opaque: it receives ZeRO-sharded grads and
            # may legitimately gather them onto its own master layout (the
            # SPMD partitioner emits that as all-gathers and/or scatter+
            # all-reduce). Constrain the wire dtype, not the op counts.
            client_dts = sorted({grad_dt, compute, "f32"})
            au_man["collectives"] = {"all-gather": {"dtypes": client_dts}}
            au_man["any_reduction"] = {"dtypes": client_dts}
            args = (self.opt_state, self.scaler_state, acc_in, step, hyper)
        else:
            args = (self.master_params, self.opt_state, self.scaler_state,
                    acc_in, self.params, step, hyper)
        progs.append(("apply_update", self._jit_apply_update, args, au_man))
        return progs

    def memory_manifest(self):
        """The memory analogue of ``lint_programs``: every persistent
        device-resident pytree this engine owns, grouped into the HBM
        observatory's attribution classes, plus the geometry the closed-form
        ZeRO predictor needs (utils/hbm.modeled_classes, docs/hbm.md).

        Class leaves may be live arrays or ShapeDtypeStructs — only
        shape/dtype/sharding are read (no device work, no syncs). Classes:

        - ``params``: compute-dtype parameters (sharded at stage >= 3)
        - ``grads``: the persistent grad/accumulation buffer handed between
          programs on the two-jit, accumulation and offload paths; absent on
          the fused path, where the grad tree stays internal and XLA frees
          each leaf as the optimizer consumes it (PERF.md round 5)
        - ``master``/``optimizer``: engine-held fp32 master and moment state
          (absent under ZeRO-Offload — host tier — and external-master, whose
          client state rides in ``optimizer`` alone)
        - ``comm_ef``: the compressed exchange's persistent error-feedback
          buffers, when configured
        """
        import jax
        classes = {"params": self.params}
        fused = getattr(self, "_run_fused_step", None) is not None
        offload = self._offload is not None

        def grads_like(dt):
            return jax.tree_util.tree_map(
                lambda p, s: jax.ShapeDtypeStruct(p.shape, dt, sharding=s),
                self.params, self._grad_shardings)

        if offload:
            classes["grads"] = grads_like(self._grad_dtype)
            grad_itemsize = jnp.dtype(self._grad_dtype).itemsize
        elif not fused:
            classes["grads"] = grads_like(self._acc_dtype)
            grad_itemsize = jnp.dtype(self._acc_dtype).itemsize
        else:
            grad_itemsize = jnp.dtype(self._grad_dtype).itemsize
        master_numel = 0
        if offload:
            pass                    # master + moments live in host DRAM
        elif self._external_master:
            classes["optimizer"] = self.opt_state
            # the one client-declared quantity: an external master is an
            # Adam-style fp32 triple (master, m1, m2) over the client's shard
            master_numel = sum(
                int(np.prod(l.shape)) if l.shape else 1
                for l in jax.tree_util.tree_leaves(self.opt_state)) // 3
        else:
            classes["master"] = self.master_params
            classes["optimizer"] = self.opt_state
        comm_ef_bytes = 0
        if self._comm_we is not None:
            from ..utils.hbm import leaf_signature
            classes["comm_ef"] = [self._comm_we, self._comm_se]
            comm_ef_bytes = sum(leaf_signature(b)[2]
                                for b in (self._comm_we, self._comm_se))
        psi = sum(int(np.prod(l.shape)) if l.shape else 1
                  for l in jax.tree_util.tree_leaves(self.params))
        geometry = {
            "kind": "training",
            "psi": psi,
            "param_itemsize": int(jnp.dtype(self.compute_dtype).itemsize),
            "grad_itemsize": int(grad_itemsize),
            "dp": int(self.dp_size),
            "zero_stage": int(self.zero_optimization_stage()),
            "zero_sharded_fraction": self._zero_sharded_fraction,
            "external_master": bool(self._external_master),
            "master_numel": int(master_numel),
            "offload": offload,
            "fused": fused,
            "gas": int(self.gradient_accumulation_steps()),
            "comm_ef_bytes": int(comm_ef_bytes),
            "n_buckets": (len(self._overlap_plan) if self._overlap_plan
                          else 0),
        }
        return {"classes": classes, "geometry": geometry}

    # ------------------------------------------------------------------ train API
    def shard_batch(self, batch):
        """Place a host batch on the mesh, sharded over the data axis (leading dim)."""
        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, NamedSharding(self.mesh, P(*( [DATA_AXIS] + [None] * (x.ndim - 1) ))))
        return jax.tree_util.tree_map(put, batch)

    def train(self, mode=True):
        self._in_training = mode

    def eval(self):
        self.warn_unscaled_loss = True
        self._in_training = False

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    def _cpu_checkpointing_active(self) -> bool:
        """Whether host-offloaded remat residuals are in play for this engine's traces.
        An engine WITH a JSON activation_checkpointing block decides from its own
        config (another engine's configure() must not strip its grad shardings);
        an engine WITHOUT one consults the process-global module, since its model's
        checkpoint_wrapper traces against that same global state."""
        from .activation_checkpointing import checkpointing as act_ckpt
        ac = self.config.activation_checkpointing_config
        if ac.configured_in_json:
            return bool(ac.cpu_checkpointing)
        return bool(act_ckpt.cpu_checkpointing_enabled())

    @property
    def _jit_loss_and_grad(self):
        """Built lazily at first training forward so the cpu-checkpointing decision sees
        both this engine's JSON config and any later module-level act_ckpt.configure()
        call (a post-first-step reconfigure cannot retroactively change the jit)."""
        if self._jit_loss_and_grad_cached is None:
            if self._cpu_checkpointing_active():
                jitted = jax.jit(self._loss_and_grad_fn)
            else:
                jitted = jax.jit(
                    self._loss_and_grad_fn,
                    out_shardings=(NamedSharding(self.mesh, P()), self._grad_shardings))
            self._jit_loss_and_grad_cached = self._watch("loss_and_grad", jitted)
        return self._jit_loss_and_grad_cached

    @property
    def _jit_loss_and_grad_comm(self):
        """Compressed-exchange grad program (comm.mode=hierarchical_compressed,
        frozen phase): carries the error-feedback buffers through, donated —
        they are persistent state rewritten every step."""
        if self._jit_loss_and_grad_comm_cached is None:
            ef = NamedSharding(self.mesh, P(DATA_AXIS, None))
            jitted = jax.jit(
                self._loss_and_grad_comm_fn,
                out_shardings=(NamedSharding(self.mesh, P()),
                               self._grad_shardings, ef, ef),
                donate_argnums=(2, 3))
            self._jit_loss_and_grad_comm_cached = self._watch(
                "loss_and_grad_comm", jitted)
        return self._jit_loss_and_grad_comm_cached

    @property
    def _jit_eval(self):
        """Jitted loss-only forward for eval() mode — the train path jits, and an
        op-by-op eval dispatch on a billion-parameter model is pathologically slow.
        Mirrors _jit_loss_and_grad's sharding handling (same cpu-checkpointing caveat)."""
        if self._jit_eval_cached is None:
            model_fn = self.model_fn

            def eval_loss(params, *batch):
                out = model_fn(params, *batch)
                return out[0] if isinstance(out, (tuple, list)) else out

            if self._cpu_checkpointing_active():
                jitted = jax.jit(eval_loss)
            else:
                jitted = jax.jit(eval_loss, out_shardings=NamedSharding(self.mesh, P()))
            self._jit_eval_cached = self._watch("eval_loss", jitted)
        return self._jit_eval_cached

    def forward(self, *inputs):
        """Compute the loss (and cache this micro-batch's gradients for backward)."""
        if (self.telemetry is not None and self._in_training
                and self.micro_steps % self.gradient_accumulation_steps() == 0):
            # first micro-step of an optimizer-step window: trace-window bookkeeping
            self.telemetry.on_step_begin(self.global_steps)
            if self._cluster is not None:
                # arm the hang watchdog deadline around this optimizer step
                self._cluster.on_step_begin(self.global_steps)
            # goodput: construction -> first train step is the init interval
            self._goodput_close_init()
        if self.wall_clock_breakdown():
            self.timers("forward_microstep").start()
        batch = tuple(self.shard_batch(x) if not isinstance(x, jax.Array) else x for x in inputs)
        if self._in_training:
            use_fused = self._run_fused_step is not None
            if use_fused and self._cpu_checkpointing_active():
                # a post-construction act_ckpt.configure(checkpoint_in_cpu=True):
                # the fused jit's explicit out_shardings cannot combine with
                # host-placement custom-calls (see _jit_loss_and_grad) — fall back
                if not getattr(self, "_warned_fused_cpu_ckpt", False):
                    self._warned_fused_cpu_ckpt = True
                    logger.warning("[deepspeed_tpu] fused_step disabled: cpu "
                                   "activation checkpointing was enabled after "
                                   "engine construction; using the two-jit step")
                use_fused = False
            if use_fused:
                # fused single-jit step (gas==1): the update runs HERE — the old
                # state buffers are donated into the jit and the new state adopted
                # immediately (a checkpoint between forward and step must never see
                # deleted buffers); step() commits only the bookkeeping
                if self._fused_pending is not None:
                    raise RuntimeError(
                        "fused step: the previous forward()'s update was never "
                        "committed — call backward() and step() before the next "
                        "forward() (strict forward/backward/step rotation)")
                loss, self._fused_pending = self._run_fused_step(batch)
                self._pending_grads = _FUSED
                self._pending_loss = loss
            elif (self._loss_and_grad_comm_fn is not None
                  and self.global_steps >= self.config.comm_compress_start_step):
                # compressed phase of hierarchical_compressed: host-side step
                # switch (the two-phase warmup rule) — cheaper than a traced
                # cond around two full backward programs
                loss, grads, self._comm_we, self._comm_se = \
                    self._jit_loss_and_grad_comm(
                        self.params, self.scaler_state.cur_scale,
                        self._comm_we, self._comm_se, *batch)
                self._pending_grads = grads
                self._pending_loss = loss
            else:
                loss, grads = self._jit_loss_and_grad(self.params,
                                                      self.scaler_state.cur_scale, *batch)
                self._pending_grads = grads
                self._pending_loss = loss
        else:
            self._goodput_begin_eval()
            loss = self._jit_eval(self.params, *batch)
            self._pending_grads = None
            self._goodput_end_eval()
        if self.wall_clock_breakdown():
            self.timers("forward_microstep").stop()
        return loss

    def backward(self, loss, allreduce_gradients=True, release_loss=False):
        """Accumulate this micro-batch's gradients (engine.py:767-841 semantics)."""
        assert self._pending_grads is not None, \
            "backward() called without a preceding forward() in training mode"
        if self.wall_clock_breakdown():
            self.timers("backward_microstep").start()
        if self._pending_grads is _FUSED:
            # fused step: grads were consumed inside the forward's jit; mark the
            # window ready for step() to commit
            self._pending_grads = None
            self._grad_acc = _FUSED
            if self._pending_loss is not None:
                self._window_losses.append(self._pending_loss)
            self.micro_steps += 1
            if self.wall_clock_breakdown():
                self.timers("backward_microstep").stop()
            return loss
        if self._grad_acc is None:
            # First micro-batch of the window: adopt the grads directly (they already have
            # the right sharding/dtype) instead of paying a zeros+add pass. With
            # gradient_accumulation_steps == 1 this removes the accumulate kernel entirely.
            # (Offload with accumulation > 1 upcasts to the fp32 accumulator dtype here.)
            self._grad_acc = (self._pending_grads if self._jit_adopt_acc is None
                              else self._jit_adopt_acc(self._pending_grads))
        else:
            self._grad_acc = self._jit_accumulate(self._grad_acc, self._pending_grads)
        self._pending_grads = None
        if self._pending_loss is not None:
            # Defer the device sync: keep the per-micro-batch loss arrays and average at
            # emission time, so the monitor logs the accumulation-window mean (reference
            # logs the accumulated loss, not the last micro-batch's).
            self._window_losses.append(self._pending_loss)
        self.micro_steps += 1
        if self.wall_clock_breakdown():
            self.timers("backward_microstep").stop()
        return loss

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps) % self.gradient_accumulation_steps() == 0

    def zero_grad(self):
        self._grad_acc = None
        # Fused-step window (external-master, gas==1): the optimizer update was
        # already applied at forward() (its inputs were donated and cannot be
        # restored); zeroing mid-window abandons only the step bookkeeping.
        self._fused_pending = None

    def step(self):
        """Apply the optimizer at the gradient-accumulation boundary (engine.py:903-985)."""
        if self.is_gradient_accumulation_boundary() and self._grad_acc is not None:
            self._take_model_step()
        return None

    def _take_model_step(self):
        if self.telemetry is not None:
            # host-local dispatch boundary: every host-side phase of the step
            # (input pipeline, accumulation, offload prep, injected stalls) is
            # behind us; everything below — the update program and its grad
            # collectives, the overflow/loss fetches — can block on peers, and
            # on a synchronous-dispatch backend does. The cluster observatory
            # attributes stragglers from the window ENDING here: it measures
            # how late this host arrived at the step's barrier, which is the
            # one signal blocking collectives cannot equalise away.
            self.telemetry.mark_step_dispatched()
        if self.wall_clock_breakdown():
            self.timers("step_microstep").start()
        if self._fused_pending is not None:
            # state was adopted at forward() (its buffers were donated); commit the
            # host-side bookkeeping here
            overflow, norm, sent = self._fused_pending
            self._fused_pending = None
            self._last_grad_norm = norm
            self._pending_sentinel = sent
            self._finish_step(self.fp16_enabled() and bool(jax.device_get(overflow)))
            return
        if self._offload is not None:
            overflow_bool = self._offload_step()
            self._finish_step(overflow_bool)
            return
        hyper = self.optimizer.current_hyper()
        step = jnp.asarray(self.global_steps + 1 - self.skipped_steps, jnp.int32)
        if self._external_master:
            outs = self._jit_apply_update(
                self.opt_state, self.scaler_state, self._grad_acc, step, hyper)
            if self._sentinel_index is not None:
                (self.opt_state, self.scaler_state, overflow,
                 self._last_grad_norm, self._pending_sentinel) = outs
            else:
                (self.opt_state, self.scaler_state, overflow,
                 self._last_grad_norm) = outs
            self._finish_step(self.fp16_enabled() and bool(jax.device_get(overflow)))
            return
        outs = self._jit_apply_update(
            self.master_params, self.opt_state, self.scaler_state, self._grad_acc,
            self.params, step, hyper)
        if self._sentinel_index is not None:
            (self.master_params, self.opt_state, self.scaler_state, self.params,
             overflow, self._last_grad_norm, self._pending_sentinel) = outs
        else:
            (self.master_params, self.opt_state, self.scaler_state, self.params,
             overflow, self._last_grad_norm) = outs
        self._finish_step(self.fp16_enabled() and bool(jax.device_get(overflow)))

    def _offload_step(self) -> bool:
        """Host-tier optimizer step (ZeRO-Offload), partitioned and overlapped.

        Order of operations (reference stage2.py:750-907 + cpu_adam.cpp
        ds_adam_step_plus_copy):
          1. initiate async D2H of every LOCAL grad region (overlaps the stats jit and
             any still-running device work),
          2. one device stats pass -> global grad norm + fp16 overflow (replicated
             scalars; XLA emits the cross-host reduction),
          3. region-pipelined host step: wait for that region's transfer, run the native
             Adam kernel with loss-scale/clip fused in, async-push the updated
             compute-dtype slice back to its devices,
          4. one all-gather jit re-materializes the replicated/caller param layout from
             the pushed master-sharded partitions.
        Wall-clock ≈ max(D2H, host Adam) + all-gather instead of their sum.
        """
        handles = self._offload.begin_grad_fetch(self._grad_acc)
        if self._sentinel_index is not None:
            norm_dev, overflow_dev, sent_dev = self._jit_grad_stats(self._grad_acc)
        else:
            norm_dev, overflow_dev = self._jit_grad_stats(self._grad_acc)
            sent_dev = None
        scale = float(jax.device_get(self.scaler_state.cur_scale))
        overflow = bool(jax.device_get(overflow_dev)) if self.fp16_enabled() else False

        factor = 1.0
        if scale != 1.0 and scale > 0:
            factor = 1.0 / scale
        predivide = float(self.config.gradient_predivide_factor or 1.0)
        if self.config.prescale_gradients and predivide != 1.0:
            factor *= predivide
        norm = float(jax.device_get(norm_dev)) * factor
        self._last_grad_norm = norm
        # sumsq of the raw (still loss-scaled) grads; factor**2 converts to the
        # post-unscale semantics the standard path's sentinel reports. Captured
        # BEFORE the clip branch folds the clip coefficient into factor.
        unscale_sq = factor * factor
        clip = float(self.gradient_clipping() or 0.0)
        if clip > 0 and norm > clip:
            factor *= clip / (norm + 1e-6)

        if not overflow:
            group_hypers = self.optimizer.hyper_for_leaf_groups()
            leaf_hypers = None
            if self._group_index is not None:
                leaf_hypers = [group_hypers[gi]
                               for gi in jax.tree_util.tree_leaves(self._group_index)]
            g = group_hypers[0]
            step_count = self.global_steps + 1 - self.skipped_steps
            out_dtype = np.dtype(self.compute_dtype)
            pushed = self._offload.step_regions(
                handles, step_count, lr=g["lr"], beta1=g["beta1"], beta2=g["beta2"],
                eps=g["eps"], weight_decay=g["weight_decay"], grad_scale=factor,
                out_dtype=out_dtype, leaf_hypers=leaf_hypers)
            self.params = (pushed if self._jit_offload_push is None
                           else self._jit_offload_push(pushed))
        self.scaler_state = ls.update(
            self.scaler_state, jnp.asarray(overflow), dynamic=self._dynamic_scale,
            scale_window=self.config.loss_scale_window, min_scale=self.config.min_loss_scale,
            hysteresis=self.config.hysteresis)
        if sent_dev is not None:
            # this path already blocked on overflow/norm above, so the fetch
            # rides the existing sync — no new barrier
            host = jax.device_get(sent_dev)
            self._pending_sentinel = {
                "grad_sumsq": host["grad_sumsq"] * unscale_sq,
                "grad_nonfinite": host["grad_nonfinite"],
            }
        return overflow

    def _finish_step(self, overflowed: bool):
        self._grad_acc = None
        if overflowed:
            self.skipped_steps += 1
            logger.info("[deepspeed_tpu] OVERFLOW! Skipping step.")
        else:
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        report_progress = self.global_steps == 0 or (self.global_steps + 1) % self.steps_per_print() == 0
        if report_progress:
            self._report_progress(self.global_steps + 1)
        self.global_steps += 1
        if self.monitor is not None:
            # reference scalars: Train/Samples/train_loss + lr + loss_scale
            # (engine.py:779-790, 920-936)
            samples = self.global_steps * self.train_batch_size()
            if self._window_losses:
                window = [float(l) for l in jax.device_get(self._window_losses)]
                self.monitor.add_scalar("Train/Samples/train_loss",
                                        sum(window) / len(window), samples)
            lr = self.get_lr()
            if lr:
                self.monitor.add_scalar("Train/Samples/lr", lr[0], samples)
            if self.fp16_enabled():
                self.monitor.add_scalar("Train/Samples/loss_scale", self.loss_scale(),
                                        samples)
            if self._last_grad_norm is not None:
                self.monitor.add_scalar("Train/Samples/grad_norm",
                                        float(jax.device_get(self._last_grad_norm)), samples)
            self.monitor.flush()  # reference flushes per emission (engine.py:790)
        numerics_host = None
        if self.telemetry is not None:
            # non-perturbing step boundary: rides the loss fetch (above, or here
            # when no monitor is attached) — no extra barrier enters the step
            numerics_host = self.telemetry.end_step(
                self.global_steps, self.train_batch_size(),
                pending=self._window_losses, numerics=self._pending_sentinel,
                run_goodput=self._goodput_scalars())
        elif self._pending_sentinel is not None:
            numerics_host = jax.device_get(self._pending_sentinel)
        if self._numerics is not None:
            self._commit_numerics(numerics_host, overflowed, self._window_losses)
        if self._cluster is not None:
            # disarm the watchdog and allgather this step's heartbeat on the
            # host CPU world; host 0 derives and emits the Cluster/* scalars
            self._cluster.on_step_end(self.global_steps)
        self._window_losses = []
        interval = self.config.resilience_save_interval
        if (self._resilience is not None and interval > 0
                and self.global_steps % interval == 0):
            # snapshot on this thread (device->host of committed step state),
            # commit in the background — the next step never fences on the
            # filesystem. async_save=False degrades to the synchronous path.
            self._resilience.save(tag=f"global_step{self.global_steps}")
            if not self.config.resilience_async_save:
                self._resilience.wait()
        # goodput: close this step's wall-clock interval AFTER the save hook,
        # so its snapshot fence is carved out of this step, not the next
        self._goodput_close_train_step()
        if self.wall_clock_breakdown():
            self.timers("step_microstep").stop()
            self.timers.log(["forward_microstep", "backward_microstep", "step_microstep"],
                            memory_breakdown=self.config.memory_breakdown)

    def _report_progress(self, step):
        lr = self.get_lr()
        mom = self.get_mom()
        log_dist(f"step={step}, skipped={self.skipped_steps}, lr={lr}, mom={mom}", ranks=[0])

    # ------------------------------------------------------------------ numerics
    def _commit_numerics(self, numerics_host, overflowed, pending_losses):
        """Feed one step's host-side sentinel values into the numerics monitor
        and run the cross-rank desync audit when its interval is due. Every
        input is already on the host (the sentinel rode the loss fetch), so
        this adds no sync point to the step."""
        self._pending_sentinel = None
        loss_host = None
        if pending_losses:
            # these loss scalars were fetched above for the monitor/telemetry;
            # device_get on an already-materialized array is a copy, not a sync
            loss_host = float(jax.device_get(pending_losses[-1]))
        gn = None
        if self._last_grad_norm is not None:
            gn = float(jax.device_get(self._last_grad_norm))
        self._numerics.commit_step(self.global_steps, numerics_host,
                                   loss=loss_host, overflowed=bool(overflowed),
                                   grad_norm=gn)
        if self._numerics.audit_due(self.global_steps):
            self._desync_audit()

    # ------------------------------------------------------------------ goodput
    # Run-lifecycle ledger hooks (docs/goodput.md). All pure host arithmetic
    # over counters the other observatories already maintain — nothing here
    # touches a device value, so the no-host-sync guard and the HLO-identity
    # tests hold with the block enabled.

    def _goodput_scalars(self):
        """Run/Goodput/* scalar dict for end_step — the ledger's state through
        the PREVIOUS step boundary (this step's interval closes after the
        save hook below)."""
        if self._goodput is None \
                or not self.config.telemetry_goodput_emit_scalars:
            return None
        return dict(self._goodput.scalar_items())

    def _goodput_compile_delta(self):
        """Compile seconds accrued since the last carve, from the compile
        watchdog's cumulative record wall."""
        if self.telemetry is None or self.telemetry.watchdog is None:
            return 0.0
        comp = self.telemetry.watchdog.compile_seconds()
        delta = comp - self._goodput_compile_base
        self._goodput_compile_base = comp
        return max(delta, 0.0)

    def _goodput_close_init(self):
        """Close the construction -> first-step interval as init, with the
        construction-time compiles (_compile_steps) carved out."""
        if self._goodput is None or not self._goodput_init_open:
            return
        self._goodput_init_open = False
        self._goodput.close("init",
                            {"compile": self._goodput_compile_delta()})

    def _goodput_begin_eval(self):
        """The span between the last boundary and eval dispatch is host gap,
        not eval — classify it before the eval interval opens."""
        if self._goodput is None:
            return
        self._goodput_close_init()
        self._goodput.close("host_gap")

    def _goodput_end_eval(self):
        if self._goodput is None:
            return
        self._goodput.close("eval",
                            {"compile": self._goodput_compile_delta()})

    def _goodput_close_train_step(self):
        """Close one train step's interval: carve compile, the checkpoint
        snapshot fence (when a save ran this step), and this host's dispatch
        skew above the fleet median; a step during which the hang watchdog
        fired bills its remainder to hang, a replayed step to restart_replay,
        everything else to productive_step."""
        if self._goodput is None:
            return
        self._goodput_close_init()
        carve = {"compile": self._goodput_compile_delta()}
        if self._resilience is not None:
            started = self._resilience.saves_started
            if started != self._goodput_saves_base:
                self._goodput_saves_base = started
                carve["checkpoint_stall"] = \
                    self._resilience.last_stall_ms / 1000.0
        hang = False
        if self._cluster is not None:
            skew = self._cluster.last_local_skew_s
            if skew > 0.0:
                carve["straggler_skew"] = skew
                # consumed: a skipped-heartbeat step must not re-bill it
                self._cluster.last_local_skew_s = 0.0
            if self._cluster.watchdog is not None:
                fired = len(self._cluster.watchdog.fired)
                hang = fired != self._goodput_hang_base
                self._goodput_hang_base = fired
        self._goodput.close_step(self.global_steps, carve, hang=hang)

    def _desync_audit(self):
        """Cross-rank replica-consistency audit (docs/numerics.md §audit): one
        small all-gather of per-subtree uint32 checksums, ONLY on audit steps."""
        if self.dp_size <= 1:
            return
        if self._audit_fn_cached is None:
            try:
                self._audit_fn_cached = self._build_audit_fn() or False
            except Exception as e:
                logger.warning(f"[numerics] desync audit unavailable: {e!r}")
                self._audit_fn_cached = False
        if self._audit_fn_cached is False:
            return
        fn, names = self._audit_fn_cached
        try:
            t0 = time.perf_counter()
            matrix = jax.device_get(fn(
                self.params,
                getattr(self, "opt_state", None) if self._offload is None else None))
            seconds = time.perf_counter() - t0
        except Exception as e:
            logger.warning(f"[numerics] desync audit failed, disabling: {e!r}")
            self._audit_fn_cached = False
            return
        slice_rows = (self._comm_topo.slice_rows
                      if (self._comm_mode != COMM_MODE_FLAT
                          and self._comm_topo.is_hierarchical) else None)
        self._numerics.commit_audit(self.global_steps, matrix, names,
                                    seconds=seconds, slice_rows=slice_rows)

    def _build_audit_fn(self):
        """Compile the audit program once: per-subtree uint32 checksums of every
        REPLICATED param/optimizer leaf, all-gathered over the data axis so the
        host can compare rows. shard_map with replicated in_specs is what makes
        this observable — under plain GSPMD the compiler assumes replicated
        arrays are bit-identical across replicas and would fold the comparison
        away; shard_map hands the local copy of each replica to the program."""
        from ..parallel.mesh import shard_map
        from ..utils.numerics import leaf_checksum, subtree_name

        depth = self.config.numerics_subtree_depth
        repl = NamedSharding(self.mesh, P())
        trees = [("params", self.params, self._param_shardings, depth)]
        opt_state = getattr(self, "opt_state", None)
        opt_shardings = getattr(self, "_opt_shardings", None)
        if self._offload is None and opt_state is not None and opt_shardings is not None:
            # optimizer pytrees nest one level deeper (e.g. {"m": {...}, "v": {...}})
            trees.append(("opt", opt_state, opt_shardings, depth + 1))

        names, name_to_id, seg, picks = [], {}, [], []
        for ti, (tag, tree, shardings, d) in enumerate(trees):
            leaves_p = jax.tree_util.tree_flatten_with_path(tree)[0]
            sh_leaves = jax.tree_util.tree_leaves(shardings)
            for li, ((path, leaf), sh) in enumerate(zip(leaves_p, sh_leaves)):
                try:
                    if not sh.is_equivalent_to(repl, leaf.ndim):
                        continue  # sharded leaf: local shards legitimately differ
                except Exception:
                    continue
                name = f"{tag}/{subtree_name(path, d)}"
                if name not in name_to_id:
                    name_to_id[name] = len(names)
                    names.append(name)
                seg.append(name_to_id[name])
                picks.append((ti, li))
        if not picks:
            return None
        seg_arr = jnp.asarray(seg, jnp.int32)
        n = len(names)

        def local(*leaves):
            vals = jnp.stack([leaf_checksum(l) for l in leaves])
            vec = jax.ops.segment_sum(vals, seg_arr, num_segments=n)
            return jax.lax.all_gather(vec, DATA_AXIS)  # [dp, n_subtrees]

        mapped = shard_map(local, mesh=self.mesh,
                           in_specs=tuple(P() for _ in picks),
                           out_specs=P(), check_vma=False)
        n_trees = len(trees)

        def audit(params, opt_state):
            flat = [jax.tree_util.tree_leaves(params)]
            if n_trees > 1:
                flat.append(jax.tree_util.tree_leaves(opt_state))
            return mapped(*[flat[ti][li] for ti, li in picks])

        return self._watch("desync_audit", jax.jit(audit)), names

    # ------------------------------------------------------------------ checkpointing
    def _ckpt_export(self, tree, kind):
        """Convert an in-memory state tree to the canonical on-disk representation.

        Identity here. Engines whose runtime layout differs from the layer-keyed
        checkpoint layout (the SPMD pipeline's pipe-stacked stages) override this so
        checkpoints stay topology-portable — the reference's layer-keyed pipeline
        checkpoints reload under a different stage count (pipe/module.py:536-567).
        ``kind`` is one of {"params", "master", "opt"}."""
        del kind
        return tree

    def _ckpt_import(self, tree, kind):
        """Inverse of ``_ckpt_export``: canonical on-disk tree -> runtime layout."""
        del kind
        return tree

    def _place_master(self, tree):
        """Put a restored master tree where this engine keeps it: device shards
        normally; under an external-master optimizer there is no master storage
        (the master_params setter is a no-op — the view re-derives from params),
        so skip the device transfer entirely."""
        if getattr(self, "_external_master", False):
            return tree
        return jax.device_put(tree, self._master_shardings)

    def flops_profile(self, *inputs, peak_tflops=None):
        """Cost analysis of THIS engine's compiled train step (fwd + bwd + update)
        from XLA's own numbers — see ``utils/flops_profiler.py``. ``inputs`` is one
        micro-batch (host arrays fine; shapes are what matter). Under ZeRO-Offload
        the optimizer update runs on the host tier and only the device programs are
        counted. Returns the report dict (add ``peak_tflops`` for the roofline step
        time). ``report["flops"]`` covers one micro-batch plus one optimizer
        update; for gradient_accumulation_steps > 1 aggregate from
        ``report["program_flops"]``: ``gas * loss_and_grad + apply_update``
        (the update runs once per window)."""
        from ..utils.flops_profiler import profile as _profile
        batch = tuple(x if isinstance(x, (jax.Array, jax.ShapeDtypeStruct))
                      else self.shard_batch(x) for x in inputs)
        step_no = jnp.asarray(1, jnp.int32)
        hyper = self.optimizer.current_hyper()
        if self._jit_fused is not None:
            if self._external_master:
                args = (self.opt_state, self.scaler_state, self.params, step_no,
                        hyper) + batch
            else:
                args = (self.master_params, self.opt_state, self.scaler_state,
                        self.params, step_no, hyper) + batch
            report = _profile(self._jit_fused, *args, peak_tflops=peak_tflops)
            report["programs"] = ["fused_step"]
            report["program_flops"] = {"fused_step": report["flops"]}
        else:
            report = _profile(self._jit_loss_and_grad, self.params,
                              self.scaler_state.cur_scale, *batch,
                              peak_tflops=peak_tflops)
            report["programs"] = ["loss_and_grad"]
            report["program_flops"] = {"loss_and_grad": report["flops"]}
            if self._offload is None:
                # shapes from self.params (identical tree), NOT the master_params
                # property — under external-master that property materializes a
                # full fp32 view on device, the exact HBM spike the mode avoids.
                # 1-bit Adam stacked grads carry a leading per-worker dp axis.
                lead = (self.dp_size,) if self._use_stacked_grads else ()
                grads = jax.tree_util.tree_map(
                    lambda sh, l: jax.ShapeDtypeStruct(lead + l.shape,
                                                       self._acc_dtype,
                                                       sharding=sh),
                    self._grad_shardings, self.params)
                if self._external_master:
                    upd = _profile(self._jit_apply_update, self.opt_state,
                                   self.scaler_state, grads, step_no, hyper)
                else:
                    upd = _profile(self._jit_apply_update, self.master_params,
                                   self.opt_state, self.scaler_state, grads,
                                   self.params, step_no, hyper)
                for k in ("flops", "bytes_accessed"):
                    report[k] += upd[k]
                report["program_flops"]["apply_update"] = upd["flops"]
                report["temp_bytes"] = max(report["temp_bytes"], upd["temp_bytes"])
                report["arithmetic_intensity"] = (
                    report["flops"] / report["bytes_accessed"]
                    if report["bytes_accessed"] else 0.0)
                if peak_tflops:
                    report["optimal_seconds"] = report["flops"] / (peak_tflops * 1e12)
                report["programs"].append("apply_update")
        from .utils import param_count
        report["params"] = param_count(self.params)
        return report

    def save_checkpoint(self, save_dir, tag=None, client_state={}, save_latest=True):
        from ..checkpoint.checkpointing import save_checkpoint as _save
        return _save(self, save_dir, tag=tag, client_state=client_state, save_latest=save_latest)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True):
        from ..checkpoint.checkpointing import load_checkpoint as _load
        return _load(self, load_dir, tag=tag, load_optimizer_states=load_optimizer_states,
                     load_lr_scheduler_states=load_lr_scheduler_states)
