"""ZeRO sharding policies as GSPMD layouts.

This is the TPU-native core of what ``runtime/zero/stage1.py`` (983 LoC) and ``stage2.py``
(1850 LoC) implement with hand-rolled flatten/partition/reduce-scatter/all-gather over NCCL:

- stage 0: optimizer state + master weights replicated; gradients all-reduced over ``data``.
- stage 1 (optimizer-state sharding, stage1.py:302-442): master fp32 weights and Adam
  moments carry a data-axis-sharded layout; XLA turns the backward's gradient all-reduce
  + local update + param broadcast into reduce-scatter → sharded update → all-gather.
- stage 2 (+gradient sharding, stage2.py:590-745): additionally the gradient accumulation
  buffer carries the sharded layout, so accumulated grads are stored reduce-scattered —
  the IPG-bucket machinery becomes a sharding annotation.

``zero_spec`` picks, per parameter, the largest axis divisible by the DP degree to shard;
parameters too small to split stay replicated (the reference pads flat buffers instead —
on TPU padding tiny tensors wastes ICI latency for nothing).
"""

from typing import Optional

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.mesh import DATA_AXIS


def zero_spec(shape, dp_size: int, min_size: int = 1024) -> P:
    """PartitionSpec sharding the largest dp-divisible axis over 'data' (or replicated)."""
    if dp_size <= 1 or int(np.prod(shape)) < min_size:
        return P()
    best_axis = -1
    best_dim = 0
    for i, d in enumerate(shape):
        if d % dp_size == 0 and d > best_dim:
            best_axis = i
            best_dim = d
    if best_axis < 0:
        return P()
    spec = [None] * len(shape)
    spec[best_axis] = DATA_AXIS
    return P(*spec)


def zero_sharding(mesh: Mesh, tree, stage: int, min_size: int = 1024):
    """Tree of NamedShardings for optimizer state / master params under the given stage."""
    import jax
    dp = mesh.shape[DATA_AXIS]

    def leaf(p):
        if stage >= 1:
            return NamedSharding(mesh, zero_spec(p.shape, dp, min_size))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(leaf, tree)


def replicated_sharding(mesh: Mesh, tree):
    import jax
    return jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), tree)
