"""ZeRO sharding policies as GSPMD layouts.

This is the TPU-native core of what ``runtime/zero/stage1.py`` (983 LoC) and ``stage2.py``
(1850 LoC) implement with hand-rolled flatten/partition/reduce-scatter/all-gather over NCCL:

- stage 0: optimizer state + master weights replicated; gradients all-reduced over ``data``.
- stage 1 (optimizer-state sharding, stage1.py:302-442): master fp32 weights and Adam
  moments carry a data-axis-sharded layout; XLA turns the backward's gradient all-reduce
  + local update + param broadcast into reduce-scatter → sharded update → all-gather.
- stage 2 (+gradient sharding, stage2.py:590-745): additionally the gradient accumulation
  buffer carries the sharded layout, so accumulated grads are stored reduce-scattered —
  the IPG-bucket machinery becomes a sharding annotation.

``zero_spec`` picks, per parameter, the largest axis divisible by the DP degree to shard;
parameters too small to split stay replicated (the reference pads flat buffers instead —
on TPU padding tiny tensors wastes ICI latency for nothing).
"""

from typing import Optional

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.mesh import DATA_AXIS


def zero_spec(shape, dp_size: int, min_size: int = 1024, existing_spec: P = P()) -> P:
    """PartitionSpec sharding the largest *unclaimed* dp-divisible axis over 'data'.

    ``existing_spec`` lets ZeRO compose with a layout that already shards some axes
    (pipe-stacked stages, TP weights): only axes the existing spec leaves None are
    candidates, and the existing placements are preserved.
    """
    spec = list(existing_spec) + [None] * (len(shape) - len(existing_spec))
    if dp_size <= 1 or int(np.prod(shape)) < min_size:
        return P(*spec)
    best_axis = -1
    best_dim = 0
    for i, d in enumerate(shape):
        if spec[i] is None and d % dp_size == 0 and d > best_dim:
            best_axis = i
            best_dim = d
    if best_axis >= 0:
        spec[best_axis] = DATA_AXIS
    return P(*spec)


def zero_sharding(mesh: Mesh, tree, stage: int, min_size: int = 1024):
    """Tree of NamedShardings for optimizer state / master params under the given stage."""
    import jax
    dp = mesh.shape[DATA_AXIS]

    def leaf(p):
        if stage >= 1:
            return NamedSharding(mesh, zero_spec(p.shape, dp, min_size))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(leaf, tree)


def sharding_coverage(shardings_tree, tree):
    """(sharded_bytes, total_bytes) over the tree — how much state the ZeRO layout
    actually partitioned vs left replicated. zero_spec legitimately leaves a leaf
    replicated (no dp-divisible axis, or under min_size), but a user at dp=32 with
    awkward shapes could believe they run ZeRO-2 while most state is replicated;
    the engine logs this at construction and tests pin >90% for flagship configs."""
    import jax
    total = sharded = 0
    for sh, a in zip(jax.tree_util.tree_leaves(shardings_tree),
                     jax.tree_util.tree_leaves(tree)):
        nbytes = int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        total += nbytes
        if not sh.is_fully_replicated:
            sharded += nbytes
    return sharded, total


def chunk_spans(total: int, cap: Optional[int]):
    """Partition the flat range [0, total) into pipeline work spans of at most ``cap``
    elements: ``(lo, hi, win)`` triples where [lo, hi) is the span and ``win`` is the
    start of the fixed-width fetch window that covers it.

    Every window is exactly ``cap`` wide (the last one is right-aligned at
    ``total - cap``, overlapping its predecessor) so a single compiled fixed-width
    device slice serves every chunk of a region — the overlap re-fetches identical
    elements, which the consumer simply doesn't write twice. With ``cap`` None/0 or
    ``total <= cap`` the region stays whole: one span, window 0.
    """
    if not cap or cap <= 0 or total <= cap:
        return [(0, total, 0)]
    spans = []
    for lo in range(0, total, cap):
        hi = min(lo + cap, total)
        spans.append((lo, hi, lo if hi - lo == cap else total - cap))
    return spans


def elastic_split(arr, dp: int):
    """Split a host array into the ``dp`` flat checkpoint shards of the elastic
    optimizer-state layout (checkpoint/checkpointing.py). np.array_split
    semantics — first ``size % dp`` shards get one extra element — which is
    exactly what ``_merge_elastic`` concatenates back, so save@dp_a →
    restore@dp_b round-trips bit-exactly for any (dp_a, dp_b)."""
    return np.array_split(np.asarray(arr).reshape(-1), dp)


def replicated_sharding(mesh: Mesh, tree):
    import jax
    return jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), tree)


def merge_zero_into(mesh: Mesh, sharding_tree, tree, stage: int, min_size: int = 1024):
    """Compose ZeRO data-axis sharding into an existing layout (e.g. pipe-stacked stages).

    For each leaf, if stage >= 1, shard the largest *unsharded* dp-divisible axis over
    'data' on top of the leaf's existing PartitionSpec. This is how ZeRO composes with
    pipeline/tensor layouts into true 3-D parallelism.
    """
    import jax
    dp = mesh.shape[DATA_AXIS]

    def leaf(sh: NamedSharding, a):
        if stage < 1:
            return NamedSharding(mesh, sh.spec)
        return NamedSharding(mesh, zero_spec(a.shape, dp, min_size, existing_spec=sh.spec))

    return jax.tree_util.tree_map(leaf, sharding_tree, tree)
