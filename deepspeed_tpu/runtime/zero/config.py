"""ZeRO config object (mirrors deepspeed/runtime/zero/config.py: DeepSpeedZeroConfig l.11)."""

from ..config_utils import get_scalar_param
from ...utils import logger
from .constants import *


class DeepSpeedZeroConfig:

    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.cpu_offload = None
        self.elastic_checkpoint = None

        user_configured = ZERO_OPTIMIZATION in param_dict
        if user_configured:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                zero_config_dict = self.read_zero_config_deprecated(param_dict)
        else:
            zero_config_dict = ZERO_OPTIMIZATION_DEFAULT

        self._initialize(zero_config_dict, user_configured)

    def read_zero_config_deprecated(self, param_dict):
        zero_config_dict = {}
        zero_config_dict[ZERO_OPTIMIZATION_STAGE] = 1 if param_dict[ZERO_OPTIMIZATION] else 0
        if (zero_config_dict[ZERO_OPTIMIZATION_STAGE] > 0
                and ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED in param_dict):
            # only when the user actually set the companion key — inserting the
            # default here would trip the explicit-tuning-key warning spuriously
            zero_config_dict[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE] = param_dict[
                ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED]
        logger.warning("DeepSpeedConfig: this format of ZeRO optimization setup is deprecated: '{}'".format(
            ZERO_FORMAT))
        return zero_config_dict

    def _initialize(self, zero_config_dict, user_configured=True):
        # Buffer/bucket tuning keys steer the reference's hand-written collectives
        # (stage2.py bucketed allreduce); XLA/GSPMD schedules collectives here, so
        # they cannot act. Record which ones the user EXPLICITLY set (not the
        # defaults dict) so DeepSpeedConfig can warn instead of silently ignoring.
        _tuning_keys = (ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                        ZERO_OPTIMIZATION_REDUCE_SCATTER, ZERO_OPTIMIZATION_OVERLAP_COMM,
                        ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE)
        if user_configured:
            _acting_keys = _tuning_keys + (ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_CPU_OFFLOAD,
                                           ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT)
            self.explicit_tuning_keys = tuple(k for k in _tuning_keys if k in zero_config_dict)
            self.unknown_keys = tuple(k for k in zero_config_dict if k not in _acting_keys)
        else:
            self.explicit_tuning_keys = self.unknown_keys = ()
        self.stage = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_STAGE_DEFAULT)
        self.contiguous_gradients = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
                                                     ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                                                   ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_REDUCE_SCATTER,
                                               ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_OVERLAP_COMM,
                                             ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                                                     ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
                                                      ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.cpu_offload = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_CPU_OFFLOAD,
                                            ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.elastic_checkpoint = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                                                   ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        import json
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
