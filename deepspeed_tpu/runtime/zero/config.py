"""ZeRO config object (mirrors deepspeed/runtime/zero/config.py: DeepSpeedZeroConfig l.11)."""

from ..config_utils import get_scalar_param
from ...utils import logger
from .constants import *


class DeepSpeedZeroConfig:

    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.cpu_offload = None
        self.elastic_checkpoint = None
        self.offload_device = None
        self.offload_pipeline = None
        self.offload_pipeline_depth = None
        self.offload_max_region_elements = None

        user_configured = ZERO_OPTIMIZATION in param_dict
        if user_configured:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                zero_config_dict = self.read_zero_config_deprecated(param_dict)
        else:
            zero_config_dict = ZERO_OPTIMIZATION_DEFAULT

        self._initialize(zero_config_dict, user_configured)

    def read_zero_config_deprecated(self, param_dict):
        zero_config_dict = {}
        zero_config_dict[ZERO_OPTIMIZATION_STAGE] = 1 if param_dict[ZERO_OPTIMIZATION] else 0
        if (zero_config_dict[ZERO_OPTIMIZATION_STAGE] > 0
                and ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED in param_dict):
            # only when the user actually set the companion key — inserting the
            # default here would trip the explicit-tuning-key warning spuriously
            zero_config_dict[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE] = param_dict[
                ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED]
        logger.warning("DeepSpeedConfig: this format of ZeRO optimization setup is deprecated: '{}'".format(
            ZERO_FORMAT))
        return zero_config_dict

    def _initialize(self, zero_config_dict, user_configured=True):
        # Buffer/bucket tuning keys steer the reference's hand-written collectives
        # (stage2.py bucketed allreduce); XLA/GSPMD schedules collectives here, so
        # they cannot act. Record which ones the user EXPLICITLY set (not the
        # defaults dict) so DeepSpeedConfig can warn instead of silently ignoring.
        _tuning_keys = (ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                        ZERO_OPTIMIZATION_REDUCE_SCATTER, ZERO_OPTIMIZATION_OVERLAP_COMM,
                        ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE)
        if user_configured:
            _acting_keys = _tuning_keys + (ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_CPU_OFFLOAD,
                                           ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                                           ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER)
            self.explicit_tuning_keys = tuple(k for k in _tuning_keys if k in zero_config_dict)
            self.unknown_keys = tuple(k for k in zero_config_dict if k not in _acting_keys)
        else:
            self.explicit_tuning_keys = self.unknown_keys = ()
        self.stage = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_STAGE, ZERO_OPTIMIZATION_STAGE_DEFAULT)
        self.contiguous_gradients = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
                                                     ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                                                   ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_REDUCE_SCATTER,
                                               ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_OVERLAP_COMM,
                                             ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                                                     ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
                                                      ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.cpu_offload = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_CPU_OFFLOAD,
                                            ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.elastic_checkpoint = get_scalar_param(zero_config_dict, ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                                                   ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)
        self._init_offload_optimizer(zero_config_dict)

    def _init_offload_optimizer(self, zero_config_dict):
        """Parse the ``offload_optimizer`` sub-config (device + host-step pipeline
        knobs). Presence of the block implies ``cpu_offload: true`` — unless the user
        ALSO set ``cpu_offload: false`` explicitly, which wins with a warning (the
        legacy boolean is the enable switch; the block only configures the step)."""
        off = zero_config_dict.get(ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER)
        if off is not None and not isinstance(off, dict):
            raise ValueError(
                f"zero_optimization.{ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER} must be a dict "
                f"of {VALID_OFFLOAD_OPTIMIZER_KEYS}, got {type(off).__name__}")
        user_set = off is not None
        off = off or {}
        for k in off:
            if k not in VALID_OFFLOAD_OPTIMIZER_KEYS:
                # same discipline as DeepSpeedConfig's unknown-key warning: an accepted
                # key must act, warn, or error — never silently no-op
                logger.warning(
                    f"DeepSpeedZeroConfig: unknown {ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER} "
                    f"key '{k}' is IGNORED (valid: {VALID_OFFLOAD_OPTIMIZER_KEYS})")
        self.offload_device = get_scalar_param(off, OFFLOAD_OPTIMIZER_DEVICE,
                                               OFFLOAD_OPTIMIZER_DEVICE_DEFAULT)
        if self.offload_device not in VALID_OFFLOAD_OPTIMIZER_DEVICES:
            raise ValueError(
                f"{ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER}.{OFFLOAD_OPTIMIZER_DEVICE} "
                f"'{self.offload_device}' is not supported on the TPU-VM host tier "
                f"(valid: {VALID_OFFLOAD_OPTIMIZER_DEVICES})")
        self.offload_pipeline = bool(get_scalar_param(off, OFFLOAD_OPTIMIZER_PIPELINE,
                                                      OFFLOAD_OPTIMIZER_PIPELINE_DEFAULT))
        depth = get_scalar_param(off, OFFLOAD_OPTIMIZER_PIPELINE_DEPTH,
                                 OFFLOAD_OPTIMIZER_PIPELINE_DEPTH_DEFAULT)
        if not isinstance(depth, int) or isinstance(depth, bool) or depth < 1:
            raise ValueError(
                f"{ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER}.{OFFLOAD_OPTIMIZER_PIPELINE_DEPTH} "
                f"must be an integer >= 1, got {depth!r}")
        self.offload_pipeline_depth = depth
        cap = get_scalar_param(off, OFFLOAD_OPTIMIZER_MAX_REGION_ELEMENTS,
                               OFFLOAD_OPTIMIZER_MAX_REGION_ELEMENTS_DEFAULT)
        if not (cap == OFFLOAD_OPTIMIZER_MAX_REGION_ELEMENTS_DEFAULT
                or (isinstance(cap, int) and not isinstance(cap, bool) and cap >= 0)):
            raise ValueError(
                f"{ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER}.{OFFLOAD_OPTIMIZER_MAX_REGION_ELEMENTS} "
                f"must be 'auto' or a non-negative integer (0 = auto), got {cap!r}")
        self.offload_max_region_elements = cap
        if user_set:
            if (ZERO_OPTIMIZATION_CPU_OFFLOAD in zero_config_dict
                    and not zero_config_dict[ZERO_OPTIMIZATION_CPU_OFFLOAD]):
                logger.warning(
                    f"DeepSpeedZeroConfig: '{ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER}' is "
                    f"configured but '{ZERO_OPTIMIZATION_CPU_OFFLOAD}' is explicitly "
                    "false — offload stays DISABLED (the explicit boolean wins); the "
                    "pipeline knobs are kept for when it is enabled")
            else:
                self.cpu_offload = True

    def repr(self):
        return self.__dict__

    def __repr__(self):
        import json
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
