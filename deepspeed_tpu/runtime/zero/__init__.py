from .config import DeepSpeedZeroConfig
