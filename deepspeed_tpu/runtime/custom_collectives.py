"""Error-feedback sign-compressed allreduce over the mesh ``data`` axis.

TPU-native re-design of the reference's MPI+cupy compressed allreduce
(``deepspeed/runtime/custom_collectives.py:10-154`` and the two-phase algorithm in
``deepspeed/runtime/fp16/onebit_adam.py:104-228``):

- Phase 1 (reference ``gather_cuda/gather_host``): every worker sign-compresses its buffer
  (1 bit/element + one fp32 RMS scale) and sends chunk *j* to server *j*. Here that is one
  ``lax.all_to_all`` of **bit-packed uint8** signs (8/byte) inside ``shard_map`` — packed
  bytes stay on the ICI wire, the unpack + fp32 upcast happen after receipt — plus an
  ``all_gather`` of the dp scalar scales.
- Server reduction: each device averages the dp received sign·scale chunks, applies its
  server error feedback, and re-compresses (reference onebit_adam.py:168-189).
- Phase 2 (reference ``allgather_cuda/allgather_host``): ``all_gather`` of the bit-packed
  server signs + scalar server scales reconstructs the full averaged buffer everywhere.

Wire volume per device: signs are BIT-PACKED — 8 per uint8 byte (XLA has no
sub-byte wire type, so the pack/unpack is explicit VPU bit arithmetic around the
collectives) — so each phase ships n/8 bytes + O(dp·n_segs) fp32 scales, ~n/4
bytes total vs 7n for a ring fp32 allreduce: ~28× less communication at the
large-n asymptote, past the reference's packed-bits "5x" headline. Chunks not
divisible by 8 (callers using ``padded_size`` always are) fall back to int8
signs (1 byte each, the round-3 wire format).

The caller keeps persistent ``worker_error`` (dp, n) and ``server_error`` (dp, n/dp)
buffers sharded ``P('data', None)`` so each device's row is resident exactly where the
shard_map body needs it.
"""

from functools import partial

import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, shard_map

def _pack_signs(signs):
    """(..., m) int8 in {-1, +1} -> (..., m/8) uint8, 8 signs per byte (set bit
    = element positive). Lossless; m must be divisible by 8."""
    return jnp.packbits(signs > 0, axis=-1, bitorder="little")


def _unpack_signs(packed):
    """Inverse of ``_pack_signs``: (..., m/8) uint8 -> (..., m) int8 in {-1, +1}."""
    bits = jnp.unpackbits(packed, axis=-1, bitorder="little")
    return jnp.where(bits, jnp.int8(1), jnp.int8(-1))


def _signs_collective(collective, signs, packed):
    """Run ``collective`` over a signs array, bit-packed on the wire when the
    last dim divides by 8 (``packed``); shapes are unchanged either way."""
    if packed:
        return _unpack_signs(collective(_pack_signs(signs)))
    return collective(signs)


def compressed_allreduce(mesh: Mesh, x, worker_error, server_error,
                         axis_name: str = DATA_AXIS, seg_ids=None):
    """Average per-worker buffers ``x`` across the ``data`` axis with 1-bit compression.

    Args:
      mesh: the device mesh (collectives run over its ``axis_name`` axis).
      x: (dp, n) fp32 — row *i* is worker *i*'s buffer; sharded ``P(data, None)``.
      worker_error: (dp, n) fp32 persistent worker error feedback, sharded ``P(data, None)``.
      server_error: (dp, n // dp) fp32 persistent server error feedback, same sharding.
        ``n`` must be divisible by dp.
      seg_ids: optional STATIC (n,) int array mapping each element to a scale segment.
        The reference compresses per parameter TENSOR — each tensor gets its own RMS
        scale (onebit_adam.py keeps per-param state). A single global scale over the
        fused buffer overscales small-momentum tensors (LN scales, biases) to the
        buffer-wide RMS, and the error feedback then oscillates unboundedly — measured
        as training divergence a few steps after freeze_step. Segment scales restore the
        reference's per-tensor semantics at the cost of shipping an extra (n_segs,) fp32
        vector per phase. None = one segment (a single scale).

    Returns:
      (out, new_worker_error, new_server_error): ``out`` is the (n,) compressed average,
      replicated; the error buffers keep their (dp, ...) sharded layout.
    """
    dp = mesh.shape[axis_name]
    n = x.shape[-1]
    assert n % dp == 0, f"buffer size {n} must be divisible by dp={dp} (pad first)"
    chunk = n // dp
    seg_np = (np.zeros((n,), np.int32) if seg_ids is None
              else np.asarray(seg_ids, np.int32))
    assert seg_np.shape == (n,), f"seg_ids must be ({n},), got {seg_np.shape}"
    n_segs = int(seg_np.max()) + 1
    seg_const = jnp.asarray(seg_np)
    seg_counts = jnp.asarray(np.maximum(np.bincount(seg_np, minlength=n_segs), 1)
                             .astype(np.float32))

    def _seg_rms(buf, ids, counts):
        ss = jax.ops.segment_sum(jnp.square(buf), ids, num_segments=n_segs)
        return jnp.sqrt(ss / counts)

    def body(x_row, we_row, se_row):
        # Per-device shapes: x_row/we_row (1, n); se_row (1, chunk).
        corrected = x_row[0] + we_row[0]
        wscale = _seg_rms(corrected, seg_const, seg_counts)          # (n_segs,)
        signs = jnp.where(corrected >= 0, 1, -1).astype(jnp.int8)
        new_we = corrected - wscale[seg_const] * signs.astype(jnp.float32)

        # Phase 1: chunk j of my signs -> server j. Signs ride the wire
        # bit-packed (uint8, 8 signs/byte) when the chunk allows.
        packed = chunk % 8 == 0
        recv = _signs_collective(
            lambda s: jax.lax.all_to_all(s, axis_name, split_axis=0,
                                         concat_axis=0, tiled=False),
            signs.reshape(dp, chunk), packed)
        wscales = jax.lax.all_gather(wscale, axis_name)              # (dp, n_segs)

        my = jax.lax.axis_index(axis_name)
        seg_chunk = jax.lax.dynamic_slice(seg_const, (my * chunk,), (chunk,))
        per_elem_wscale = jnp.take_along_axis(wscales, seg_chunk[None, :]
                                              .repeat(dp, 0), axis=1)  # (dp, chunk)
        server_m = jnp.mean(recv.astype(jnp.float32) * per_elem_wscale, axis=0)
        corrected_s = server_m + se_row[0]
        chunk_counts = jnp.maximum(jax.ops.segment_sum(jnp.ones((chunk,), jnp.float32),
                                                       seg_chunk, num_segments=n_segs), 1.0)
        sscale = _seg_rms(corrected_s, seg_chunk, chunk_counts)      # (n_segs,)
        s_signs = jnp.where(corrected_s >= 0, 1, -1).astype(jnp.int8)
        new_se = corrected_s - sscale[seg_chunk] * s_signs.astype(jnp.float32)

        # Phase 2: allgather the compressed server chunks (bit-packed too).
        all_signs = _signs_collective(
            lambda s: jax.lax.all_gather(s, axis_name), s_signs, packed)
        sscales = jax.lax.all_gather(sscale, axis_name)              # (dp, n_segs)
        seg_by_chunk = seg_const.reshape(dp, chunk)
        per_elem_sscale = jnp.take_along_axis(sscales, seg_by_chunk, axis=1)
        out = (all_signs.astype(jnp.float32) * per_elem_sscale).reshape(n)
        return out, new_we[None], new_se[None]

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis_name, None), P(axis_name, None), P(axis_name, None)),
                   out_specs=(P(), P(axis_name, None), P(axis_name, None)),
                   check_vma=False)
    return fn(x, worker_error, server_error)


def padded_size(n: int, dp: int, lanes: int = 128) -> int:
    """Round ``n`` up so each of the dp server chunks is a whole multiple of the TPU
    lane width (reference pads to ``size * divider``, onebit_adam.py:294-299)."""
    quantum = dp * lanes
    return ((n + quantum - 1) // quantum) * quantum
