"""JSON config system.

TPU-native re-design of the reference's ``deepspeed/runtime/config.py`` (DeepSpeedConfig
l.464): same JSON keys and semantics — batch triple inference (config.py:562-608), the
``train_batch = micro_batch * grad_acc * world_size`` assertion (config.py:542-560),
duplicate-key rejection (config.py:455-457) — but world size comes from the JAX device/mesh
world instead of torch.distributed, and the default low-precision policy is bfloat16 (fp16
with dynamic loss scaling remains available for parity).
"""

import json
from typing import Optional

from ..utils import logger
from .config_utils import dict_raise_error_on_duplicate_keys, get_scalar_param
from .constants import *
from .zero.config import DeepSpeedZeroConfig
from .zero.constants import (MAX_STAGE_ZERO_OPTIMIZATION, ZERO_OPTIMIZATION_GRADIENTS,
                             ZERO_OPTIMIZATION_WEIGHTS)
from .activation_checkpointing.config import DeepSpeedActivationCheckpointingConfig

TENSOR_CORE_ALIGN_SIZE = 8  # MXU lane alignment hint (reference used tensor-core 8)


class SparseAttentionConfig:
    """Typed view of the ``sparse_attention`` block (reference config.py:156-324)."""

    def __init__(self, sparsity_dict):
        self.mode = get_scalar_param(sparsity_dict, SPARSE_MODE, SPARSE_MODE_DEFAULT)
        self.block = get_scalar_param(sparsity_dict, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
        self.different_layout_per_head = get_scalar_param(sparsity_dict, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                                                          SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
        self.num_local_blocks = get_scalar_param(sparsity_dict, SPARSE_NUM_LOCAL_BLOCKS,
                                                 SPARSE_NUM_LOCAL_BLOCKS_DEFAULT)
        self.num_global_blocks = get_scalar_param(sparsity_dict, SPARSE_NUM_GLOBAL_BLOCKS,
                                                  SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT)
        self.attention = get_scalar_param(sparsity_dict, SPARSE_ATTENTION_TYPE, SPARSE_ATTENTION_TYPE_DEFAULT)
        self.horizontal_global_attention = get_scalar_param(sparsity_dict, SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                                                            SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT)
        self.num_different_global_patterns = get_scalar_param(sparsity_dict, SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                                                              SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT)
        self.num_random_blocks = get_scalar_param(sparsity_dict, SPARSE_NUM_RANDOM_BLOCKS,
                                                  SPARSE_NUM_RANDOM_BLOCKS_DEFAULT)
        self.local_window_blocks = get_scalar_param(sparsity_dict, SPARSE_LOCAL_WINDOW_BLOCKS,
                                                    SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT)
        self.global_block_indices = get_scalar_param(sparsity_dict, SPARSE_GLOBAL_BLOCK_INDICES,
                                                     SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT)
        self.global_block_end_indices = get_scalar_param(sparsity_dict, SPARSE_GLOBAL_BLOCK_END_INDICES,
                                                         SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT)
        self.num_sliding_window_blocks = get_scalar_param(sparsity_dict, SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                                                          SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT)

    def repr(self):
        return self.__dict__


def get_pipeline_config(param_dict):
    """Engine-level pipeline block (reference config.py:340-360)."""
    default_pipeline = {
        PIPELINE_STAGES: PIPELINE_STAGES_DEFAULT,
        PIPELINE_PARTITION: PIPELINE_PARTITION_DEFAULT,
        PIPELINE_SEED_LAYERS: PIPELINE_SEED_LAYERS_DEFAULT,
        PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL: PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT,
    }
    config = default_pipeline.copy()
    for key, val in param_dict.get(PIPELINE, {}).items():
        config[key] = val
    return config


class DeepSpeedConfig:
    """Typed view over the DeepSpeed-style JSON config.

    ``world_size`` is the *data-parallel* world size used for batch inference — by default
    the number of addressable JAX devices divided by any model/pipe parallel degrees the
    caller's mesh/mpu implies (reference: dp world from mpu, config.py:470-480).
    """

    def __init__(self, json_file_or_dict, mpu=None, param_dict: Optional[dict] = None, world_size: Optional[int] = None):
        if param_dict is None:
            if isinstance(json_file_or_dict, dict):
                self._param_dict = json_file_or_dict
            else:
                with open(json_file_or_dict, "r") as f:
                    self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        else:
            self._param_dict = param_dict

        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            try:
                import jax
                self.world_size = jax.device_count()
            except ImportError:
                self.world_size = 1
            except Exception as e:
                # A broken backend must not silently shrink the world to 1 — the batch
                # triple would be inferred self-consistently wrong.
                raise RuntimeError(f"DeepSpeedConfig: could not determine device world size: {e}") from e
        self.global_rank = 0
        try:
            import jax
            self.global_rank = jax.process_index()
        except Exception:
            pass

        # warn about unrecognized keys BEFORE batch inference/error checks: a typo'd
        # batch key would otherwise abort on the missing-batch assertion without the
        # user ever seeing which key went unrecognized
        unknown = sorted(k for k in self._param_dict if k not in TOP_LEVEL_CONFIG_KEYS)
        if unknown:
            logger.warning(f"DeepSpeedConfig: unknown top-level config key(s) {unknown} "
                           "— ignored. Known keys: see docs/config-json.md.")
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    @staticmethod
    def _warn_unknown_nested(block, block_dict, known_keys):
        """Same unknown-key diagnostic as the top-level sweep, for a nested
        block — a typo'd "enable" must not silently leave a subsystem off."""
        if not isinstance(block_dict, dict):
            return
        unknown = sorted(k for k in block_dict if k not in known_keys)
        if unknown:
            logger.warning(f"DeepSpeedConfig: unknown {block} config key(s) "
                           f"{unknown} — ignored. Known keys: {sorted(known_keys)}.")

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_scalar_param(param_dict, TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)
        micro = get_scalar_param(param_dict, TRAIN_MICRO_BATCH_SIZE_PER_GPU, TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        if micro is None:
            micro = get_scalar_param(param_dict, TRAIN_MICRO_BATCH_SIZE_PER_DEVICE,
                                     TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = get_scalar_param(param_dict, GRADIENT_ACCUMULATION_STEPS,
                                                            GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = get_scalar_param(param_dict, STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(param_dict, DUMP_STATE, DUMP_STATE_DEFAULT)

        self.disable_allgather = get_scalar_param(param_dict, DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)
        self.allreduce_always_fp32 = get_scalar_param(param_dict, ALLREDUCE_ALWAYS_FP32,
                                                      ALLREDUCE_ALWAYS_FP32_DEFAULT)
        if get_scalar_param(param_dict, FP32_ALLREDUCE, FP32_ALLREDUCE_DEFAULT):
            # deprecated alias from the reference constants (constants.py:191-196):
            # fold into allreduce_always_fp32 rather than silently dropping it
            logger.warning(f"DeepSpeedConfig: '{FP32_ALLREDUCE}' is deprecated; it is "
                           f"honored as '{ALLREDUCE_ALWAYS_FP32}'.")
            self.allreduce_always_fp32 = True
        self.communication_data_type = get_scalar_param(param_dict, COMMUNICATION_DATA_TYPE,
                                                        COMMUNICATION_DATA_TYPE_DEFAULT)
        if self.communication_data_type is not None:
            allowed = ("fp32", "fp16", "bf16")
            if self.communication_data_type not in allowed:
                raise ValueError(f"DeepSpeedConfig: {COMMUNICATION_DATA_TYPE} must be one of "
                                 f"{allowed} (got {self.communication_data_type!r})")
        self.prescale_gradients = get_scalar_param(param_dict, PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT)
        self.fused_step = get_scalar_param(param_dict, FUSED_STEP, FUSED_STEP_DEFAULT)
        self.compilation_cache_dir = get_scalar_param(param_dict, COMPILATION_CACHE_DIR,
                                                      COMPILATION_CACHE_DIR_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(param_dict, GRADIENT_PREDIVIDE_FACTOR,
                                                          GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(param_dict, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = DeepSpeedActivationCheckpointingConfig(param_dict)

        self.gradient_clipping = get_scalar_param(param_dict, GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT)

        # Mixed-precision policy. fp16 block keeps reference semantics (loss scaling);
        # bf16 (TPU-native, no scaling) is the default compute dtype when neither is set.
        fp16_dict = param_dict.get(FP16, {})
        self.fp16_enabled = get_scalar_param(fp16_dict, FP16_ENABLED, FP16_ENABLED_DEFAULT)
        self.loss_scale = get_scalar_param(fp16_dict, FP16_LOSS_SCALE, FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = get_scalar_param(fp16_dict, FP16_INITIAL_SCALE_POWER,
                                                    FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = get_scalar_param(fp16_dict, FP16_LOSS_SCALE_WINDOW, FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = get_scalar_param(fp16_dict, FP16_HYSTERESIS, FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = get_scalar_param(fp16_dict, FP16_MIN_LOSS_SCALE, FP16_MIN_LOSS_SCALE_DEFAULT)

        bf16_dict = param_dict.get(BF16, {})
        self.bf16_enabled = get_scalar_param(bf16_dict, BF16_ENABLED, not self.fp16_enabled)

        amp_dict = param_dict.get(AMP, {})
        self.amp_enabled = get_scalar_param(amp_dict, AMP_ENABLED, AMP_ENABLED_DEFAULT)
        self.amp_params = {k: v for k, v in amp_dict.items() if k != AMP_ENABLED}
        if self.amp_enabled:
            # apex.amp is CUDA-only; its O1/O2 mixed precision maps to the TPU-native
            # bf16 policy (low-precision compute, fp32 master/optimizer state). Act,
            # don't no-op: enable the bf16 policy and say so. fp16+amp is rejected in
            # _do_error_check (reference engine.py:530-531).
            logger.warning("DeepSpeedConfig: 'amp' maps to the TPU-native bf16 mixed-"
                           "precision policy (apex is CUDA-only); amp opt-level params "
                           f"{self.amp_params or '{}'} are ignored. Prefer the 'bf16' "
                           "block (docs/config-json.md).")
            if not self.fp16_enabled:
                self.bf16_enabled = True

        self.zero_allow_untested_optimizer = get_scalar_param(
            param_dict, ZERO_ALLOW_UNTESTED_OPTIMIZER, ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        optimizer_dict = param_dict.get(OPTIMIZER, None)
        self.optimizer_name = None
        self.optimizer_params = None
        self.optimizer_legacy_fusion = False
        if optimizer_dict is not None:
            self.optimizer_name = optimizer_dict.get(TYPE, OPTIMIZER_TYPE_DEFAULT)
            if self.optimizer_name is not None:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = optimizer_dict.get(OPTIMIZER_PARAMS, None)
            self.optimizer_legacy_fusion = optimizer_dict.get(LEGACY_FUSION, LEGACY_FUSION_DEFAULT)

        scheduler_dict = param_dict.get(SCHEDULER, None)
        self.scheduler_name = None
        self.scheduler_params = None
        if scheduler_dict is not None:
            self.scheduler_name = scheduler_dict.get(TYPE, SCHEDULER_TYPE_DEFAULT)
            self.scheduler_params = scheduler_dict.get(SCHEDULER_PARAMS, None)

        self.wall_clock_breakdown = get_scalar_param(param_dict, WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(param_dict, MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)

        tb_dict = param_dict.get(TENSORBOARD, {})
        self.tensorboard_enabled = get_scalar_param(tb_dict, TENSORBOARD_ENABLED, TENSORBOARD_ENABLED_DEFAULT)
        self.tensorboard_output_path = get_scalar_param(tb_dict, TENSORBOARD_OUTPUT_PATH,
                                                        TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.tensorboard_job_name = get_scalar_param(tb_dict, TENSORBOARD_JOB_NAME, TENSORBOARD_JOB_NAME_DEFAULT)

        tel_dict = param_dict.get(TELEMETRY, {})
        self._warn_unknown_nested(TELEMETRY, tel_dict, TELEMETRY_CONFIG_KEYS)
        self.telemetry_enabled = get_scalar_param(tel_dict, TELEMETRY_ENABLED, TELEMETRY_ENABLED_DEFAULT)
        self.telemetry_trace_dir = get_scalar_param(tel_dict, TELEMETRY_TRACE_DIR, TELEMETRY_TRACE_DIR_DEFAULT)
        self.telemetry_trace_steps = get_scalar_param(tel_dict, TELEMETRY_TRACE_STEPS,
                                                      TELEMETRY_TRACE_STEPS_DEFAULT)
        if self.telemetry_trace_steps is not None:
            ts = self.telemetry_trace_steps
            if (not isinstance(ts, (list, tuple)) or len(ts) != 2
                    or not all(isinstance(v, int) and not isinstance(v, bool) and v >= 0 for v in ts)
                    or ts[1] <= ts[0]):
                raise ValueError(
                    "DeepSpeedConfig: telemetry.trace_steps must be a [start, stop] "
                    f"pair of non-negative ints with start < stop, got {ts!r}")
            self.telemetry_trace_steps = (int(ts[0]), int(ts[1]))
        self.telemetry_perturbing_breakdown = get_scalar_param(tel_dict, TELEMETRY_PERTURBING_BREAKDOWN,
                                                               TELEMETRY_PERTURBING_BREAKDOWN_DEFAULT)
        self.telemetry_peak_tflops = float(
            get_scalar_param(tel_dict, TELEMETRY_PEAK_TFLOPS, TELEMETRY_PEAK_TFLOPS_DEFAULT) or 0.0)
        self.telemetry_mfu_window = get_scalar_param(tel_dict, TELEMETRY_MFU_WINDOW,
                                                     TELEMETRY_MFU_WINDOW_DEFAULT)
        self.telemetry_recompile_warn = get_scalar_param(tel_dict, TELEMETRY_RECOMPILE_WARN,
                                                         TELEMETRY_RECOMPILE_WARN_DEFAULT)
        self.telemetry_output_path = get_scalar_param(tel_dict, TELEMETRY_OUTPUT_PATH,
                                                      TELEMETRY_OUTPUT_PATH_DEFAULT)
        self.telemetry_job_name = get_scalar_param(tel_dict, TELEMETRY_JOB_NAME, TELEMETRY_JOB_NAME_DEFAULT)
        pt_dict = tel_dict.get(TELEMETRY_PIPELINE_TRACE, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_PIPELINE_TRACE}",
                                  pt_dict, PIPELINE_TRACE_CONFIG_KEYS)
        self.pipeline_trace_enabled = get_scalar_param(pt_dict, PIPELINE_TRACE_ENABLED,
                                                       PIPELINE_TRACE_ENABLED_DEFAULT)
        self.pipeline_trace_capacity = get_scalar_param(pt_dict, PIPELINE_TRACE_CAPACITY,
                                                        PIPELINE_TRACE_CAPACITY_DEFAULT)
        cap = self.pipeline_trace_capacity
        if isinstance(cap, bool) or not isinstance(cap, int) or cap < 1:
            raise ValueError(
                "DeepSpeedConfig: telemetry.pipeline_trace.capacity must be an "
                f"int >= 1, got {cap!r}")
        self.pipeline_trace_dump_dir = get_scalar_param(pt_dict, PIPELINE_TRACE_DUMP_DIR,
                                                        PIPELINE_TRACE_DUMP_DIR_DEFAULT)
        an_dict = tel_dict.get(TELEMETRY_ANATOMY, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_ANATOMY}",
                                  an_dict, ANATOMY_CONFIG_KEYS)
        self.telemetry_anatomy_enabled = get_scalar_param(an_dict, ANATOMY_ENABLED,
                                                          ANATOMY_ENABLED_DEFAULT)
        self.telemetry_anatomy_chip = get_scalar_param(an_dict, ANATOMY_CHIP, ANATOMY_CHIP_DEFAULT)
        for attr, key, default in (("telemetry_anatomy_peak_tflops", ANATOMY_PEAK_TFLOPS,
                                    ANATOMY_PEAK_TFLOPS_DEFAULT),
                                   ("telemetry_anatomy_hbm_gbps", ANATOMY_HBM_GBPS,
                                    ANATOMY_HBM_GBPS_DEFAULT),
                                   ("telemetry_anatomy_ici_gbps", ANATOMY_ICI_GBPS,
                                    ANATOMY_ICI_GBPS_DEFAULT),
                                   ("telemetry_anatomy_dcn_gbps", ANATOMY_DCN_GBPS,
                                    ANATOMY_DCN_GBPS_DEFAULT)):
            val = get_scalar_param(an_dict, key, default)
            if isinstance(val, bool) or not isinstance(val, (int, float)) or val < 0:
                raise ValueError(
                    f"DeepSpeedConfig: telemetry.anatomy.{key} must be a "
                    f"number >= 0 (0 = use the chip table value), got {val!r}")
            setattr(self, attr, float(val))

        cl_dict = tel_dict.get(TELEMETRY_CLUSTER, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_CLUSTER}",
                                  cl_dict, CLUSTER_CONFIG_KEYS)
        self.telemetry_cluster_enabled = get_scalar_param(cl_dict, CLUSTER_ENABLED,
                                                          CLUSTER_ENABLED_DEFAULT)
        if self.telemetry_cluster_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.cluster.enabled requires "
                "telemetry.enabled — the heartbeat rides the end_step record "
                "the telemetry session produces")
        self.telemetry_cluster_heartbeat_interval = get_scalar_param(
            cl_dict, CLUSTER_HEARTBEAT_INTERVAL, CLUSTER_HEARTBEAT_INTERVAL_DEFAULT)
        hb = self.telemetry_cluster_heartbeat_interval
        if isinstance(hb, bool) or not isinstance(hb, int) or hb < 1:
            raise ValueError(
                "DeepSpeedConfig: telemetry.cluster.heartbeat_interval must be "
                f"an int >= 1, got {hb!r}")
        self.telemetry_cluster_hang_deadline_s = get_scalar_param(
            cl_dict, CLUSTER_HANG_DEADLINE_S, CLUSTER_HANG_DEADLINE_S_DEFAULT)
        dl = self.telemetry_cluster_hang_deadline_s
        if isinstance(dl, bool) or not isinstance(dl, (int, float)) or dl < 0:
            raise ValueError(
                "DeepSpeedConfig: telemetry.cluster.hang_deadline_s must be a "
                f"number >= 0 (0 = watchdog off), got {dl!r}")
        self.telemetry_cluster_hang_deadline_s = float(dl)
        self.telemetry_cluster_dump_dir = get_scalar_param(
            cl_dict, CLUSTER_DUMP_DIR, CLUSTER_DUMP_DIR_DEFAULT)
        self.telemetry_cluster_straggler_threshold = get_scalar_param(
            cl_dict, CLUSTER_STRAGGLER_THRESHOLD, CLUSTER_STRAGGLER_THRESHOLD_DEFAULT)
        st = self.telemetry_cluster_straggler_threshold
        if isinstance(st, bool) or not isinstance(st, (int, float)) or st <= 1:
            raise ValueError(
                "DeepSpeedConfig: telemetry.cluster.straggler_threshold must be "
                f"a number > 1, got {st!r}")
        self.telemetry_cluster_straggler_threshold = float(st)
        self.telemetry_cluster_signal_peers = get_scalar_param(
            cl_dict, CLUSTER_SIGNAL_PEERS, CLUSTER_SIGNAL_PEERS_DEFAULT)
        self.telemetry_cluster_warmup_steps = get_scalar_param(
            cl_dict, CLUSTER_WARMUP_STEPS, CLUSTER_WARMUP_STEPS_DEFAULT)
        wu = self.telemetry_cluster_warmup_steps
        if isinstance(wu, bool) or not isinstance(wu, int) or wu < 0:
            raise ValueError(
                "DeepSpeedConfig: telemetry.cluster.warmup_steps must be an "
                f"int >= 0 (steps before the watchdog arms / stragglers are "
                f"named — the compile steps), got {wu!r}")

        gp_dict = tel_dict.get(TELEMETRY_GOODPUT, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_GOODPUT}",
                                  gp_dict, GOODPUT_CONFIG_KEYS)
        self.telemetry_goodput_enabled = get_scalar_param(gp_dict, GOODPUT_ENABLED,
                                                          GOODPUT_ENABLED_DEFAULT)
        if self.telemetry_goodput_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.goodput.enabled requires "
                "telemetry.enabled — the ledger closes its step intervals on "
                "the end_step record the telemetry session produces")
        self.telemetry_goodput_ledger_dir = get_scalar_param(
            gp_dict, GOODPUT_LEDGER_DIR, GOODPUT_LEDGER_DIR_DEFAULT)
        if not isinstance(self.telemetry_goodput_ledger_dir, str):
            raise ValueError(
                "DeepSpeedConfig: telemetry.goodput.ledger_dir must be a string "
                f"path (\"\" = beside the flight-recorder dumps), got "
                f"{self.telemetry_goodput_ledger_dir!r}")
        self.telemetry_goodput_emit_scalars = get_scalar_param(
            gp_dict, GOODPUT_EMIT_SCALARS, GOODPUT_EMIT_SCALARS_DEFAULT)
        if not isinstance(self.telemetry_goodput_emit_scalars, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.goodput.emit_scalars must be a "
                f"bool, got {self.telemetry_goodput_emit_scalars!r}")
        self.telemetry_goodput_eval_tag = get_scalar_param(
            gp_dict, GOODPUT_EVAL_TAG, GOODPUT_EVAL_TAG_DEFAULT)
        if (not isinstance(self.telemetry_goodput_eval_tag, str)
                or not self.telemetry_goodput_eval_tag):
            raise ValueError(
                "DeepSpeedConfig: telemetry.goodput.eval_tag must be a "
                f"non-empty string, got {self.telemetry_goodput_eval_tag!r}")

        hbm_dict = tel_dict.get(TELEMETRY_HBM, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_HBM}",
                                  hbm_dict, HBM_CONFIG_KEYS)
        self.telemetry_hbm_enabled = get_scalar_param(hbm_dict, HBM_ENABLED,
                                                      HBM_ENABLED_DEFAULT)
        if self.telemetry_hbm_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.hbm.enabled requires "
                "telemetry.enabled — the Memory/* scalars ride the end_step "
                "record the telemetry session produces")
        if not isinstance(self.telemetry_hbm_enabled, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.hbm.enabled must be a bool, got "
                f"{self.telemetry_hbm_enabled!r}")

        prof_dict = tel_dict.get(TELEMETRY_PROFILE, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_PROFILE}",
                                  prof_dict, PROFILE_CONFIG_KEYS)
        self.telemetry_profile_enabled = get_scalar_param(
            prof_dict, PROFILE_ENABLED, PROFILE_ENABLED_DEFAULT)
        if not isinstance(self.telemetry_profile_enabled, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.profile.enabled must be a bool, "
                f"got {self.telemetry_profile_enabled!r}")
        if self.telemetry_profile_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.profile.enabled requires "
                "telemetry.enabled — the observatory ingests the trace window "
                "the telemetry session writes")
        self.telemetry_profile_reconcile_tolerance = get_scalar_param(
            prof_dict, PROFILE_RECONCILE_TOLERANCE,
            PROFILE_RECONCILE_TOLERANCE_DEFAULT)
        tol = self.telemetry_profile_reconcile_tolerance
        if isinstance(tol, bool) or not isinstance(tol, (int, float)) \
                or tol <= 0:
            raise ValueError(
                "DeepSpeedConfig: telemetry.profile.reconcile_tolerance must "
                f"be a number > 0, got {tol!r}")
        self.telemetry_profile_reconcile_tolerance = float(tol)
        self.telemetry_profile_emit_scalars = get_scalar_param(
            prof_dict, PROFILE_EMIT_SCALARS, PROFILE_EMIT_SCALARS_DEFAULT)
        if not isinstance(self.telemetry_profile_emit_scalars, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.profile.emit_scalars must be a "
                f"bool, got {self.telemetry_profile_emit_scalars!r}")

        met_dict = tel_dict.get(TELEMETRY_METRICS, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_METRICS}",
                                  met_dict, METRICS_CONFIG_KEYS)
        self.telemetry_metrics_enabled = get_scalar_param(
            met_dict, METRICS_ENABLED, METRICS_ENABLED_DEFAULT)
        if not isinstance(self.telemetry_metrics_enabled, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.metrics.enabled must be a bool, "
                f"got {self.telemetry_metrics_enabled!r}")
        if self.telemetry_metrics_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.metrics.enabled requires "
                "telemetry.enabled — the catalog router rides the "
                "SummaryMonitor the telemetry session owns")
        self.telemetry_metrics_ring_len = get_scalar_param(
            met_dict, METRICS_RING_LEN, METRICS_RING_LEN_DEFAULT)
        rl = self.telemetry_metrics_ring_len
        if isinstance(rl, bool) or not isinstance(rl, int) or rl < 1:
            raise ValueError(
                "DeepSpeedConfig: telemetry.metrics.ring_len must be an "
                f"int >= 1, got {rl!r}")
        self.telemetry_metrics_strict_catalog = get_scalar_param(
            met_dict, METRICS_STRICT_CATALOG, METRICS_STRICT_CATALOG_DEFAULT)
        if not isinstance(self.telemetry_metrics_strict_catalog, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.metrics.strict_catalog must be a "
                f"bool, got {self.telemetry_metrics_strict_catalog!r}")
        self.telemetry_metrics_export_path = get_scalar_param(
            met_dict, METRICS_EXPORT_PATH, METRICS_EXPORT_PATH_DEFAULT)
        if not isinstance(self.telemetry_metrics_export_path, str):
            raise ValueError(
                "DeepSpeedConfig: telemetry.metrics.export_path must be a "
                f"string, got {self.telemetry_metrics_export_path!r}")

        al_dict = tel_dict.get(TELEMETRY_ALERTS, {}) or {}
        self._warn_unknown_nested(f"{TELEMETRY}.{TELEMETRY_ALERTS}",
                                  al_dict, ALERTS_CONFIG_KEYS)
        self.telemetry_alerts_enabled = get_scalar_param(
            al_dict, ALERTS_ENABLED, ALERTS_ENABLED_DEFAULT)
        if not isinstance(self.telemetry_alerts_enabled, bool):
            raise ValueError(
                "DeepSpeedConfig: telemetry.alerts.enabled must be a bool, "
                f"got {self.telemetry_alerts_enabled!r}")
        if self.telemetry_alerts_enabled and not self.telemetry_enabled:
            raise ValueError(
                "DeepSpeedConfig: telemetry.alerts.enabled requires "
                "telemetry.enabled — the rules evaluate on the end_step "
                "boundary the telemetry session drives")
        rules = get_scalar_param(al_dict, ALERTS_RULES, ALERTS_RULES_DEFAULT)
        if rules is not None:
            if not isinstance(rules, (list, tuple)):
                raise ValueError(
                    "DeepSpeedConfig: telemetry.alerts.rules must be a list "
                    f"of rule dicts (or null for the default ruleset), got "
                    f"{rules!r}")
            from ..utils.alerts import validate_rules
            from ..utils.metrics import default_catalog
            try:
                rules = validate_rules(list(rules), default_catalog())
            except ValueError as e:
                raise ValueError(
                    f"DeepSpeedConfig: telemetry.alerts.rules: {e}")
        self.telemetry_alerts_rules = rules

        num_dict = param_dict.get(NUMERICS, {})
        self._warn_unknown_nested(NUMERICS, num_dict, NUMERICS_CONFIG_KEYS)
        self.numerics_enabled = get_scalar_param(num_dict, NUMERICS_ENABLED, NUMERICS_ENABLED_DEFAULT)
        self.numerics_subtree_depth = get_scalar_param(num_dict, NUMERICS_SUBTREE_DEPTH,
                                                       NUMERICS_SUBTREE_DEPTH_DEFAULT)
        self.numerics_audit_interval = get_scalar_param(num_dict, NUMERICS_AUDIT_INTERVAL,
                                                        NUMERICS_AUDIT_INTERVAL_DEFAULT)
        self.numerics_dump_dir = get_scalar_param(num_dict, NUMERICS_DUMP_DIR, NUMERICS_DUMP_DIR_DEFAULT)
        self.numerics_ring_size = get_scalar_param(num_dict, NUMERICS_RING_SIZE, NUMERICS_RING_SIZE_DEFAULT)
        self.numerics_consecutive_skip_trigger = get_scalar_param(
            num_dict, NUMERICS_CONSECUTIVE_SKIP_TRIGGER, NUMERICS_CONSECUTIVE_SKIP_TRIGGER_DEFAULT)
        self.numerics_trigger_on_nonfinite_loss = get_scalar_param(
            num_dict, NUMERICS_TRIGGER_ON_NONFINITE_LOSS, NUMERICS_TRIGGER_ON_NONFINITE_LOSS_DEFAULT)
        self.numerics_install_signal_handlers = get_scalar_param(
            num_dict, NUMERICS_INSTALL_SIGNAL_HANDLERS, NUMERICS_INSTALL_SIGNAL_HANDLERS_DEFAULT)
        for attr, minimum in ((("numerics_subtree_depth"), 1),
                              (("numerics_audit_interval"), 0),
                              (("numerics_ring_size"), 1),
                              (("numerics_consecutive_skip_trigger"), 0)):
            val = getattr(self, attr)
            if isinstance(val, bool) or not isinstance(val, int) or val < minimum:
                raise ValueError(
                    f"DeepSpeedConfig: numerics.{attr[len('numerics_'):]} must be an "
                    f"int >= {minimum}, got {val!r}")

        sv_dict = param_dict.get(SERVING, {})
        self._warn_unknown_nested(SERVING, sv_dict, SERVING_CONFIG_KEYS)
        self.serving_enabled = get_scalar_param(sv_dict, SERVING_ENABLED, SERVING_ENABLED_DEFAULT)
        self.serving_block_size = get_scalar_param(sv_dict, SERVING_BLOCK_SIZE, SERVING_BLOCK_SIZE_DEFAULT)
        self.serving_num_blocks = get_scalar_param(sv_dict, SERVING_NUM_BLOCKS, SERVING_NUM_BLOCKS_DEFAULT)
        self.serving_max_seqs = get_scalar_param(sv_dict, SERVING_MAX_SEQS, SERVING_MAX_SEQS_DEFAULT)
        self.serving_max_model_len = get_scalar_param(sv_dict, SERVING_MAX_MODEL_LEN,
                                                      SERVING_MAX_MODEL_LEN_DEFAULT)
        self.serving_prefill_chunk = get_scalar_param(sv_dict, SERVING_PREFILL_CHUNK,
                                                      SERVING_PREFILL_CHUNK_DEFAULT)
        self.serving_use_pallas_decode = get_scalar_param(sv_dict, SERVING_USE_PALLAS_DECODE,
                                                          SERVING_USE_PALLAS_DECODE_DEFAULT)
        for attr, minimum in (("serving_block_size", 1),
                              ("serving_num_blocks", 2),  # block 0 is the reserved null page
                              ("serving_max_seqs", 1),
                              ("serving_max_model_len", 1),
                              ("serving_prefill_chunk", 1)):
            val = getattr(self, attr)
            if isinstance(val, bool) or not isinstance(val, int) or val < minimum:
                raise ValueError(
                    f"DeepSpeedConfig: serving.{attr[len('serving_'):]} must be an "
                    f"int >= {minimum}, got {val!r}")
        if self.serving_max_model_len % self.serving_block_size != 0:
            # the paged gather reconstructs a [max_blocks * block_size] dense view;
            # it bit-matches the dense decode oracle only when the tiling is exact
            raise ValueError(
                "DeepSpeedConfig: serving.max_model_len must be a multiple of "
                f"serving.block_size, got {self.serving_max_model_len} % "
                f"{self.serving_block_size} != 0")

        rt_dict = sv_dict.get(SERVING_REQUEST_TRACE, {}) or {}
        self._warn_unknown_nested(f"{SERVING}.{SERVING_REQUEST_TRACE}",
                                  rt_dict, SERVING_REQUEST_TRACE_CONFIG_KEYS)
        self.serving_request_trace_enabled = get_scalar_param(
            rt_dict, SERVING_REQUEST_TRACE_ENABLED,
            SERVING_REQUEST_TRACE_ENABLED_DEFAULT)
        self.serving_request_trace_capacity = get_scalar_param(
            rt_dict, SERVING_REQUEST_TRACE_CAPACITY,
            SERVING_REQUEST_TRACE_CAPACITY_DEFAULT)
        self.serving_request_trace_iteration_capacity = get_scalar_param(
            rt_dict, SERVING_REQUEST_TRACE_ITERATION_CAPACITY,
            SERVING_REQUEST_TRACE_ITERATION_CAPACITY_DEFAULT)
        self.serving_request_trace_dump_dir = get_scalar_param(
            rt_dict, SERVING_REQUEST_TRACE_DUMP_DIR,
            SERVING_REQUEST_TRACE_DUMP_DIR_DEFAULT)
        for attr, minimum in (("serving_request_trace_capacity", 1),
                              ("serving_request_trace_iteration_capacity", 1)):
            val = getattr(self, attr)
            if isinstance(val, bool) or not isinstance(val, int) or val < minimum:
                raise ValueError(
                    f"DeepSpeedConfig: serving.request_trace."
                    f"{attr[len('serving_request_trace_'):]} must be an "
                    f"int >= {minimum}, got {val!r}")
        slo_dict = rt_dict.get(SERVING_REQUEST_TRACE_SLO, {}) or {}
        self._warn_unknown_nested(
            f"{SERVING}.{SERVING_REQUEST_TRACE}.{SERVING_REQUEST_TRACE_SLO}",
            slo_dict, SERVING_SLO_CONFIG_KEYS)
        self.serving_slo_ttft_ms = get_scalar_param(
            slo_dict, SERVING_SLO_TTFT_MS, SERVING_SLO_TTFT_MS_DEFAULT)
        self.serving_slo_tpot_ms = get_scalar_param(
            slo_dict, SERVING_SLO_TPOT_MS, SERVING_SLO_TPOT_MS_DEFAULT)
        for attr in ("serving_slo_ttft_ms", "serving_slo_tpot_ms"):
            val = getattr(self, attr)
            if isinstance(val, bool) or not isinstance(val, (int, float)) or val < 0:
                raise ValueError(
                    f"DeepSpeedConfig: serving.request_trace.slo."
                    f"{attr[len('serving_slo_'):]} must be a number >= 0 "
                    f"(0 = not gated), got {val!r}")

        sh_dict = sv_dict.get(SERVING_SHARDING, {}) or {}
        self._warn_unknown_nested(f"{SERVING}.{SERVING_SHARDING}",
                                  sh_dict, SERVING_SHARDING_CONFIG_KEYS)
        self.serving_sharding_model = get_scalar_param(
            sh_dict, SERVING_SHARDING_MODEL, SERVING_SHARDING_MODEL_DEFAULT)
        val = self.serving_sharding_model
        if isinstance(val, bool) or not isinstance(val, int) or val < 1:
            raise ValueError(
                "DeepSpeedConfig: serving.sharding.model must be an int >= 1 "
                f"(1 = single-chip), got {val!r}")

        pc_dict = sv_dict.get(SERVING_PREFIX_CACHE, {}) or {}
        self._warn_unknown_nested(f"{SERVING}.{SERVING_PREFIX_CACHE}",
                                  pc_dict, SERVING_PREFIX_CACHE_CONFIG_KEYS)
        self.serving_prefix_cache_enabled = get_scalar_param(
            pc_dict, SERVING_PREFIX_CACHE_ENABLED,
            SERVING_PREFIX_CACHE_ENABLED_DEFAULT)

        sp_dict = sv_dict.get(SERVING_SPECULATION, {}) or {}
        self._warn_unknown_nested(f"{SERVING}.{SERVING_SPECULATION}",
                                  sp_dict, SERVING_SPECULATION_CONFIG_KEYS)
        self.serving_speculation_enabled = get_scalar_param(
            sp_dict, SERVING_SPECULATION_ENABLED,
            SERVING_SPECULATION_ENABLED_DEFAULT)
        self.serving_speculation_draft_model = get_scalar_param(
            sp_dict, SERVING_SPECULATION_DRAFT_MODEL,
            SERVING_SPECULATION_DRAFT_MODEL_DEFAULT)
        self.serving_speculation_max_draft_tokens = get_scalar_param(
            sp_dict, SERVING_SPECULATION_MAX_DRAFT_TOKENS,
            SERVING_SPECULATION_MAX_DRAFT_TOKENS_DEFAULT)
        self.serving_speculation_draft_pool_blocks = get_scalar_param(
            sp_dict, SERVING_SPECULATION_DRAFT_POOL_BLOCKS,
            SERVING_SPECULATION_DRAFT_POOL_BLOCKS_DEFAULT)
        val = self.serving_speculation_max_draft_tokens
        if isinstance(val, bool) or not isinstance(val, int) or val < 1:
            raise ValueError(
                "DeepSpeedConfig: serving.speculation.max_draft_tokens must "
                f"be an int >= 1, got {val!r}")
        val = self.serving_speculation_draft_pool_blocks
        if isinstance(val, bool) or not isinstance(val, int) or (
                val != 0 and val < 2):  # block 0 is the reserved null page
            raise ValueError(
                "DeepSpeedConfig: serving.speculation.draft_pool_blocks must "
                "be 0 (inherit serving.num_blocks) or an int >= 2, "
                f"got {val!r}")

        fl_dict = sv_dict.get(SERVING_FLEET, {}) or {}
        self._warn_unknown_nested(f"{SERVING}.{SERVING_FLEET}",
                                  fl_dict, SERVING_FLEET_CONFIG_KEYS)
        self.serving_fleet_replicas = get_scalar_param(
            fl_dict, SERVING_FLEET_REPLICAS, SERVING_FLEET_REPLICAS_DEFAULT)
        self.serving_fleet_policy = get_scalar_param(
            fl_dict, SERVING_FLEET_POLICY, SERVING_FLEET_POLICY_DEFAULT)
        self.serving_fleet_affinity_weight = get_scalar_param(
            fl_dict, SERVING_FLEET_AFFINITY_WEIGHT,
            SERVING_FLEET_AFFINITY_WEIGHT_DEFAULT)
        self.serving_fleet_max_queue_depth = get_scalar_param(
            fl_dict, SERVING_FLEET_MAX_QUEUE_DEPTH,
            SERVING_FLEET_MAX_QUEUE_DEPTH_DEFAULT)
        self.serving_fleet_occupancy_cap = get_scalar_param(
            fl_dict, SERVING_FLEET_OCCUPANCY_CAP,
            SERVING_FLEET_OCCUPANCY_CAP_DEFAULT)
        self.serving_fleet_goodput_floor = get_scalar_param(
            fl_dict, SERVING_FLEET_GOODPUT_FLOOR,
            SERVING_FLEET_GOODPUT_FLOOR_DEFAULT)
        val = self.serving_fleet_replicas
        if isinstance(val, bool) or not isinstance(val, int) or val < 1:
            raise ValueError(
                "DeepSpeedConfig: serving.fleet.replicas must be an int >= 1 "
                f"(1 = no fleet, a single replica), got {val!r}")
        if self.serving_fleet_policy not in SERVING_FLEET_POLICIES:
            raise ValueError(
                f"DeepSpeedConfig: serving.fleet.policy must be one of "
                f"{SERVING_FLEET_POLICIES}, got "
                f"{self.serving_fleet_policy!r}")
        val = self.serving_fleet_affinity_weight
        if isinstance(val, bool) or not isinstance(val, (int, float)) or val < 0:
            raise ValueError(
                "DeepSpeedConfig: serving.fleet.affinity_weight must be a "
                f"number >= 0 (0 = pure least-loaded), got {val!r}")
        val = self.serving_fleet_max_queue_depth
        if isinstance(val, bool) or not isinstance(val, int) or val < 0:
            raise ValueError(
                "DeepSpeedConfig: serving.fleet.max_queue_depth must be an "
                f"int >= 0 (0 = unbounded), got {val!r}")
        val = self.serving_fleet_occupancy_cap
        if isinstance(val, bool) or not isinstance(val, (int, float)) or (
                not 0.0 < val <= 1.0):
            raise ValueError(
                "DeepSpeedConfig: serving.fleet.occupancy_cap must be a "
                f"number in (0, 1] (1 = occupancy shedding off), got {val!r}")
        val = self.serving_fleet_goodput_floor
        if isinstance(val, bool) or not isinstance(val, (int, float)) or (
                not 0.0 <= val <= 1.0):
            raise ValueError(
                "DeepSpeedConfig: serving.fleet.goodput_floor must be a "
                f"number in [0, 1] (0 = not gated), got {val!r}")

        cm_dict = param_dict.get(COMM, {})
        self._warn_unknown_nested(COMM, cm_dict, COMM_CONFIG_KEYS)
        self.comm_mode = get_scalar_param(cm_dict, COMM_MODE, COMM_MODE_DEFAULT)
        self.comm_dcn_slices = get_scalar_param(cm_dict, COMM_DCN_SLICES, COMM_DCN_SLICES_DEFAULT)
        self.comm_compress_start_step = get_scalar_param(cm_dict, COMM_COMPRESS_START_STEP,
                                                         COMM_COMPRESS_START_STEP_DEFAULT)
        if self.comm_mode not in COMM_MODES:
            raise ValueError(
                f"DeepSpeedConfig: comm.mode must be one of {COMM_MODES}, "
                f"got {self.comm_mode!r}")
        for attr in ("comm_dcn_slices", "comm_compress_start_step"):
            val = getattr(self, attr)
            if isinstance(val, bool) or not isinstance(val, int) or val < 0:
                raise ValueError(
                    f"DeepSpeedConfig: comm.{attr[len('comm_'):]} must be an "
                    f"int >= 0, got {val!r}")
        ov_dict = cm_dict.get(COMM_OVERLAP, {}) or {}
        self._warn_unknown_nested(f"{COMM}.{COMM_OVERLAP}", ov_dict,
                                  COMM_OVERLAP_CONFIG_KEYS)
        self.comm_overlap_mode = get_scalar_param(
            ov_dict, COMM_OVERLAP_MODE, COMM_OVERLAP_MODE_DEFAULT)
        self.comm_overlap_bucket_mb = get_scalar_param(
            ov_dict, COMM_OVERLAP_BUCKET_MB, COMM_OVERLAP_BUCKET_MB_DEFAULT)
        if self.comm_overlap_mode not in COMM_OVERLAP_MODES:
            raise ValueError(
                f"DeepSpeedConfig: comm.overlap.mode must be one of "
                f"{COMM_OVERLAP_MODES}, got {self.comm_overlap_mode!r}")
        bmb = self.comm_overlap_bucket_mb
        if isinstance(bmb, bool) or not isinstance(bmb, (int, float)) or bmb <= 0:
            raise ValueError(
                "DeepSpeedConfig: comm.overlap.bucket_mb must be a number > 0, "
                f"got {bmb!r}")
        self.comm_overlap_bucket_mb = float(bmb)

        rs_dict = param_dict.get(RESILIENCE, {})
        self._warn_unknown_nested(RESILIENCE, rs_dict, RESILIENCE_CONFIG_KEYS)
        self.resilience_enabled = get_scalar_param(rs_dict, RESILIENCE_ENABLED,
                                                   RESILIENCE_ENABLED_DEFAULT)
        self.resilience_save_dir = get_scalar_param(rs_dict, RESILIENCE_SAVE_DIR,
                                                    RESILIENCE_SAVE_DIR_DEFAULT)
        self.resilience_save_interval = get_scalar_param(rs_dict, RESILIENCE_SAVE_INTERVAL,
                                                         RESILIENCE_SAVE_INTERVAL_DEFAULT)
        self.resilience_async_save = get_scalar_param(rs_dict, RESILIENCE_ASYNC_SAVE,
                                                      RESILIENCE_ASYNC_SAVE_DEFAULT)
        self.resilience_auto_resume = get_scalar_param(rs_dict, RESILIENCE_AUTO_RESUME,
                                                       RESILIENCE_AUTO_RESUME_DEFAULT)
        val = self.resilience_save_interval
        if isinstance(val, bool) or not isinstance(val, int) or val < 0:
            raise ValueError(
                "DeepSpeedConfig: resilience.save_interval must be an int >= 0 "
                f"(0 = no periodic saves), got {val!r}")
        if self.resilience_enabled and self.resilience_save_interval > 0 \
                and not self.resilience_save_dir:
            raise ValueError(
                "DeepSpeedConfig: resilience.save_interval > 0 requires "
                "resilience.save_dir to be set")

        self.sparse_attention = None
        if SPARSE_ATTENTION in param_dict:
            self.sparse_attention = SparseAttentionConfig(param_dict[SPARSE_ATTENTION])

        sp_dict = param_dict.get(SEQUENCE_PARALLEL, {})
        self.sequence_parallel_enabled = get_scalar_param(sp_dict, SEQUENCE_PARALLEL_ENABLED,
                                                          SEQUENCE_PARALLEL_ENABLED_DEFAULT)
        self.sequence_parallel_axis = get_scalar_param(sp_dict, SEQUENCE_PARALLEL_AXIS,
                                                       SEQUENCE_PARALLEL_AXIS_DEFAULT)
        self.sequence_parallel_schedule = get_scalar_param(sp_dict, SEQUENCE_PARALLEL_SCHEDULE,
                                                           SEQUENCE_PARALLEL_SCHEDULE_DEFAULT)

        self.pipeline = get_pipeline_config(param_dict)

    # ---- batch triple inference (reference config.py:562-608) ----
    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per device: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            "Check batch related parameters. train_batch_size is not equal"
            " to micro_batch_per_device * gradient_acc_step * world_size: "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            return
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise AssertionError("Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()
        self._do_compat_check()

    def _do_compat_check(self):
        """Every accepted key must act, warn, or error — never silently no-op
        (reference: config.py:633-670 runs error/warning checks; this adds the
        TPU-migration diagnostics for keys whose CUDA mechanism has no GSPMD
        analog)."""
        if (ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED in self._param_dict
                and not isinstance(self._param_dict.get(ZERO_OPTIMIZATION), bool)):
            logger.warning(f"DeepSpeedConfig: '{ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED}' "
                           "is the deprecated companion of the boolean zero_optimization form and "
                           "is only honored there — ignored (use the zero_optimization block).")
        if self.disable_allgather:
            logger.warning(f"DeepSpeedConfig: '{DISABLE_ALLGATHER}' selects the reference's "
                           "allreduce-instead-of-allgather fallback for its hand-written ZeRO "
                           "collectives; XLA GSPMD chooses collectives from the sharding "
                           "layout here, so the key has no effect.")
        if self.optimizer_legacy_fusion:
            logger.warning(f"DeepSpeedConfig: optimizer '{LEGACY_FUSION}' switches the "
                           "reference's CUDA fused-kernel variant; the TPU optimizer update "
                           "is one XLA-fused jit either way, so the key has no effect.")
        zc = self.zero_config
        if getattr(zc, "explicit_tuning_keys", ()):
            logger.warning("DeepSpeedConfig: zero_optimization buffer-tuning key(s) "
                           f"{list(zc.explicit_tuning_keys)} tune the reference's bucketed "
                           "collectives; GSPMD schedules collectives from shardings here, "
                           "so they have no effect.")
        if getattr(zc, "unknown_keys", ()):
            logger.warning(f"DeepSpeedConfig: unknown zero_optimization key(s) "
                           f"{list(zc.unknown_keys)} — ignored.")
        if zc.elastic_checkpoint is False:
            logger.warning("DeepSpeedConfig: zero_optimization.elastic_checkpoint=false has "
                           "no effect — checkpoints are always elastic-loadable here (the "
                           "loader merges/repartitions optimizer shards across DP sizes).")

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, (
            f"DeepSpeedConfig: {TRAIN_MICRO_BATCH_SIZE_PER_GPU} is not defined")
        assert self.gradient_accumulation_steps, (
            f"DeepSpeedConfig: {GRADIENT_ACCUMULATION_STEPS} is not defined")
        if self.amp_enabled:
            # reference engine.py:530-531: amp and legacy fp16 are mutually exclusive
            assert not self.fp16_enabled, (
                "DeepSpeedConfig: cannot enable both amp and the fp16 block — pick one "
                "mixed-precision policy (on TPU, prefer the default bf16)")
        if self.zero_enabled:
            # Reference requires fp16 for ZeRO; on TPU any low-precision policy (bf16 default)
            # satisfies the same "mixed precision master weights" contract.
            assert self.fp16_enabled or self.bf16_enabled, (
                "DeepSpeedConfig: ZeRO is only supported if fp16 or bf16 is enabled")
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, (
                f"DeepSpeedConfig: Maximum supported ZeRO stage is {MAX_STAGE_ZERO_OPTIMIZATION}")
            if self.zero_config.cpu_offload is True:
                # stage 2 is reference parity; stage 3 + offload (sharded compute
                # params AND host-tier master/moments) composes here because the
                # offload tier is partitioned by the same master layout
                assert self.zero_optimization_stage in (
                    ZERO_OPTIMIZATION_GRADIENTS, ZERO_OPTIMIZATION_WEIGHTS), (
                    "DeepSpeedConfig: cpu-offload requires ZeRO stage "
                    f"{ZERO_OPTIMIZATION_GRADIENTS} or {ZERO_OPTIMIZATION_WEIGHTS}")

    def _do_warning_check(self):
        # Unlike the reference (zero implied fp16), bf16 ZeRO is first-class here: only an
        # actual fp16 wrapper takes over max_grad_norm; bf16/fp32 use engine clipping.
        fp16_enabled = self.fp16_enabled
        if self.communication_data_type == "fp16" and not fp16_enabled:
            # grads are PRODUCED in this dtype (the psum then rides it), so fp16
            # without the loss-scaling block risks overflow even at dp=1
            logger.warning(f"DeepSpeedConfig: {COMMUNICATION_DATA_TYPE}='fp16' without "
                           "the fp16 loss-scaling block: gradients are cast to fp16 "
                           "before reduction and may overflow (|g| > 65504). Prefer "
                           "'bf16', or enable the fp16 block.")
        if (self.allreduce_always_fp32 and self.communication_data_type is not None
                and self.communication_data_type != "fp32"):
            # engine.py resolves the comm dtype with communication_data_type LAST
            # (explicit dtype overrides the blanket fp32 switch) — say so instead of
            # letting the two keys silently disagree
            logger.warning(
                f"DeepSpeedConfig: both '{ALLREDUCE_ALWAYS_FP32}' and "
                f"'{COMMUNICATION_DATA_TYPE}'='{self.communication_data_type}' are set "
                f"with conflicting dtypes; the explicit {COMMUNICATION_DATA_TYPE} wins "
                f"and gradients reduce in {self.communication_data_type}.")
        vocabulary_size = self._param_dict.get(VOCABULARY_SIZE, VOCABULARY_SIZE_DEFAULT)
        if vocabulary_size and vocabulary_size % TENSOR_CORE_ALIGN_SIZE != 0:
            logger.warning("DeepSpeedConfig: vocabulary size {} is not aligned to {}, "
                           "may impact MXU utilization.".format(vocabulary_size, TENSOR_CORE_ALIGN_SIZE))
        if (self.optimizer_params is not None and MAX_GRAD_NORM in self.optimizer_params.keys()
                and self.optimizer_params[MAX_GRAD_NORM] > 0):
            if fp16_enabled:
                logger.warning("DeepSpeedConfig: In FP16 mode, DeepSpeed will pass {}:{} to FP16 wrapper".format(
                    MAX_GRAD_NORM, self.optimizer_params[MAX_GRAD_NORM]))
            elif self.bf16_enabled:
                logger.warning("DeepSpeedConfig: In BF16 mode, {}:{} is applied as engine gradient clipping".format(
                    MAX_GRAD_NORM, self.optimizer_params[MAX_GRAD_NORM]))
                if not self.gradient_clipping:
                    self.gradient_clipping = float(self.optimizer_params[MAX_GRAD_NORM])
                self.optimizer_params[MAX_GRAD_NORM] = 0.0
            else:
                logger.warning("DeepSpeedConfig: In FP32 mode, DeepSpeed does not permit MAX_GRAD_NORM ({}) > 0, "
                               "setting to zero".format(self.optimizer_params[MAX_GRAD_NORM]))
                self.optimizer_params[MAX_GRAD_NORM] = 0.0

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                dots = "." * (29 - len(arg))
                logger.info("  {} {} {}".format(arg, dots, getattr(self, arg)))
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4, separators=(",", ":"), default=repr)))
