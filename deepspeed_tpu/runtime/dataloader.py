"""Rank-sharded data loading.

Analog of ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader l.33, RepeatingLoader
l.10). In the single-controller JAX model there is no per-rank DistributedSampler: the
loader yields full global micro-batches as numpy/JAX arrays and the engine's
``device_put`` with a data-axis sharding performs the split (each device receives its
shard without a host-side copy per rank).
"""

import math
from typing import Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """Wrap an iterator so it restarts from the beginning when exhausted."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class DeepSpeedDataLoader:
    """Batches an indexable dataset into global micro-batches.

    ``dataset`` is any sequence of per-sample pytrees (tuples of arrays). Batches are
    stacked with numpy; sharding onto the mesh happens in the engine.
    """

    def __init__(self,
                 dataset: Sequence,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = False,
                 seed: int = 0,
                 drop_last: bool = True,
                 data_parallel_world_size: int = 1):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        if drop_last:
            self.len = len(dataset) // batch_size
        else:
            self.len = math.ceil(len(dataset) / batch_size)

    def __len__(self):
        return self.len

    def __iter__(self) -> Iterator:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        self.epoch += 1
        for b in range(self.len):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in idx]
            yield self.collate_fn(samples)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])
