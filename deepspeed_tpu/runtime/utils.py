"""Shared numeric/partitioning/diagnostic helpers.

TPU-native analog of ``deepspeed/runtime/utils.py`` (575 LoC): partitioning math
(partition_uniform l.295 / partition_balanced l.361 via binary-search + linear probe),
MP-aware norms (get_grad_norm l.154), PartitionedTensor (l.379), memory diagnostics
(see_memory_usage l.489), set_random_seed (l.33), call_to_str (l.556).

Norms operate on JAX pytrees; PartitionedTensor shards a flat array across a mesh axis
and is the activation-sharding primitive for pipeline+TP.
"""

import math
from bisect import bisect_left
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import logger


def param_count(params) -> int:
    """Total element count of a parameter pytree (shared by the model families)."""
    return sum(int(l.size) for l in jax.tree_util.tree_leaves(params))


def set_random_seed(seed: int):
    """Seed python/numpy and return a JAX PRNG key (stateless JAX analog of l.33)."""
    import random
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def call_to_str(base, *args, **kwargs) -> str:
    """Construct a string representation of a call: call_to_str('f', 1, b=2) == 'f(1, b=2)'."""
    name = f"{base}("
    if args:
        name += ", ".join(str(arg) for arg in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{key}={arg}" for key, arg in kwargs.items())
    name += ")"
    return name


# ---------------------------------------------------------------------------
# Pytree norms / overflow checks
# ---------------------------------------------------------------------------

def global_norm(tree) -> jnp.ndarray:
    """L2 norm over a full pytree (computed in fp32)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def get_grad_norm(grads, mp_axis: Optional[str] = None) -> jnp.ndarray:
    """Gradient L2 norm; when called inside shard_map with a model axis, sums the
    squared local norm over ``mp_axis`` first (MP-aware, reference utils.py:154-210)."""
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in jax.tree_util.tree_leaves(grads))
    if mp_axis is not None:
        sq = jax.lax.psum(sq, mp_axis)
    return jnp.sqrt(sq)


def get_weight_norm(params, mp_axis: Optional[str] = None) -> jnp.ndarray:
    return get_grad_norm(params, mp_axis)


def clip_grads_by_global_norm(grads, max_norm: float, norm: Optional[jnp.ndarray] = None):
    """Scale grads so the global norm is at most ``max_norm`` (no-op if already below)."""
    if norm is None:
        norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def has_inf_or_nan_tree(tree) -> jnp.ndarray:
    """True if any leaf contains inf/nan (fp16 overflow check, reference CheckOverflow l.41)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.bool_)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32)))) for l in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def detect_overflow(tree, fp16_active: bool, index=None):
    """Single overflow-detection entry point for every engine/optimizer branch.

    Replaces the three historically-divergent call sites (standard prep_grads,
    offload grad_stats, fused FP16_Optimizer). Returns ``(overflow, nonfinite)``:

    - ``index is None`` — exactly the historical semantics: a single global
      bool from :func:`has_inf_or_nan_tree` when fp16 is active, a constant
      False otherwise; ``nonfinite`` is None. The disabled-numerics step
      program stays HLO-identical to pre-sentinel code.
    - ``index`` set (a ``utils.numerics.SubtreeIndex``) — additionally returns
      the per-subtree nonfinite element counts (i32[index.n]) feeding the
      sentinel's overflow localization; the global bool is derived from that
      same vector so no second pass over the tree is emitted.
    """
    if index is None:
        overflow = has_inf_or_nan_tree(tree) if fp16_active \
            else jnp.zeros((), jnp.bool_)
        return overflow, None
    from ..utils.numerics import bucket_nonfinite
    nonfinite = bucket_nonfinite(tree, index)
    overflow = (jnp.sum(nonfinite) > 0) if fp16_active \
        else jnp.zeros((), jnp.bool_)
    return overflow, nonfinite


# ---------------------------------------------------------------------------
# Partitioning math (pipeline layer balancing, ZeRO sub-partitions)
# ---------------------------------------------------------------------------

def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    """Inclusive prefix sum: [3,4,5] -> [3,7,12]."""
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries of a uniform split of ``num_items`` into ``num_parts`` (len = parts+1)."""
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    for p in range(num_parts):
        parts[p] = min(chunksize * p, num_items)
    parts[num_parts] = num_items
    return parts


def _linear_probe(csum: List[float], num_parts: int, bottleneck: float):
    """Greedily place boundaries so no partition's weight exceeds ``bottleneck``.

    ``csum`` is the inclusive prefix sum. Returns (parts, feasible).
    """
    num_items = len(csum)
    total = csum[-1]
    parts = [0] * (num_parts + 1)
    for p in range(1, num_parts + 1):
        parts[p] = num_items

    target = bottleneck
    for p in range(1, num_parts):
        # boundary = first index whose prefix sum reaches the target
        parts[p] = bisect_left(csum, target, lo=parts[p - 1], hi=num_items)
        if parts[p] == num_items:
            # everything placed; feasible iff the last nonempty partition fits
            part_weight = total - (csum[parts[p - 1] - 1] if parts[p - 1] > 0 else 0.0)
            return parts, part_weight < bottleneck
        target = csum[parts[p] - 1] + bottleneck if parts[p] > 0 else bottleneck
    return parts, target >= total


def partition_balanced(weights: Sequence[float], num_parts: int, eps: float = 1e-3) -> List[int]:
    """Split items into parts minimizing the heaviest partition (binary search on the
    bottleneck + linear probe; same contract as reference utils.py:361)."""
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)

    csum = prefix_sum_inc(list(map(float, weights)))
    total = csum[-1]
    lower = total / num_parts
    upper = total
    while upper > lower + eps:
        mid = lower + (upper - lower) / 2
        _, feasible = _linear_probe(csum, num_parts, mid)
        if feasible:
            upper = mid
        else:
            lower = mid + eps
    parts, feasible = _linear_probe(csum, num_parts, upper)
    assert feasible
    return parts


# ---------------------------------------------------------------------------
# PartitionedTensor — flat sharded view of an array over a mesh-axis group
# ---------------------------------------------------------------------------

class PartitionedTensor:
    """Flatten → pad → split an array into ``world`` equal chunks; hold one chunk.

    Host-level analog of reference utils.py:379-473. Inside jitted/shard_map code the
    same role is played by sharding constraints; this class exists for the pipeline
    engine's activation-partitioning between stages and for checkpoint layouts, where an
    explicit (meta, local_data) pair must cross process boundaries.
    """

    def __init__(self, tensor: Optional[jnp.ndarray], world: int, rank: int, partition_meta=None,
                 local_data: Optional[jnp.ndarray] = None):
        self.world = world
        self.rank = rank
        if partition_meta is not None:
            # from_meta path
            self.orig_shape = tuple(partition_meta["orig_shape"])
            self.orig_size = int(np.prod(self.orig_shape))
            self.padded = int(partition_meta["padded"])
            self.local_data = local_data
            self.orig_dtype = partition_meta["dtype"]
            return
        assert tensor is not None
        self.orig_shape = tuple(tensor.shape)
        self.orig_dtype = tensor.dtype
        self.orig_size = tensor.size
        flat = tensor.reshape(-1)
        chunk = -(-flat.size // world)  # ceil
        self.padded = chunk * world
        if self.padded != flat.size:
            flat = jnp.pad(flat, (0, self.padded - flat.size))
        self.local_data = flat[rank * chunk:(rank + 1) * chunk]

    @classmethod
    def from_meta(cls, meta, local_part, world: int, rank: int):
        return cls(None, world, rank, partition_meta=meta, local_data=local_part)

    def to_meta(self):
        return {"orig_shape": list(self.orig_shape), "padded": self.padded, "dtype": self.orig_dtype}

    def local_size(self):
        return self.local_data.shape

    def full(self, gathered_parts: Optional[List[jnp.ndarray]] = None) -> jnp.ndarray:
        """Reassemble the full tensor. Single-process: the caller passes all parts (or we
        only have ours and world==1); multi-process callers gather parts over the mesh."""
        if gathered_parts is None:
            assert self.world == 1, "multi-chunk full() needs gathered_parts (use all_gather over the axis)"
            gathered_parts = [self.local_data]
        flat = jnp.concatenate(gathered_parts)[:self.orig_size]
        return flat.reshape(self.orig_shape).astype(self.orig_dtype)


# ---------------------------------------------------------------------------
# Memory diagnostics
# ---------------------------------------------------------------------------

def see_memory_usage(message: str, force: bool = False):
    from ..utils.hbm import device_memory_stats
    stats = device_memory_stats()
    if stats is None:
        logger.info(f"{message} | device memory stats unavailable")
        return
    ib = stats.get("bytes_in_use", 0) / (1024**3)
    pk = stats.get("peak_bytes_in_use", 0) / (1024**3)
    lim = stats.get("bytes_limit", 0) / (1024**3)
    logger.info(f"{message} | device mem in-use {ib:.2f} GB | peak {pk:.2f} GB | limit {lim:.2f} GB")


def memory_status(msg: str, print_rank: int = 0):
    see_memory_usage(f"MEMSTATS {msg}")
