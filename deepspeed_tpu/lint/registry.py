"""Registry of representative test-scale engine programs for ``ds-tpu lint``.

Each entry builds a real engine on the 8-virtual-device CPU mesh (the same
mesh the tier-1 HLO tests pin collectives on) and captures every program on
its active step path via ``engine.lint_programs`` — the engines themselves
declare the expected-collective manifests. Entries cover the step-path matrix
the bespoke tests grew one file at a time: standard two-jit ZeRO-2, the
external-master fused single-jit (the pinned 1.5B bench structure), the
unfused external-master accumulation window, ZeRO-Offload's host-tier split,
and the instruction-executor pipeline's per-stage programs.

The lint model computes in the engine's compute dtype (params enter already
cast; inputs are cast once at the boundary) — unlike the test-suite
SimpleModel, which casts params to ``x.dtype`` and therefore runs f32 dots
that would (correctly!) trip the dtype-promotion pass. The seeded-violation
fixtures use exactly that trick.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .program_passes import ProgramArtifact

HIDDEN = 32
BATCH = 8


class LintModel:
    """Two-layer MLP that computes in the dtype the engine handed it params
    in, with only the loss in f32 — the clean low-precision reference shape."""

    def __init__(self, hidden_dim=HIDDEN):
        self.hidden_dim = hidden_dim

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        h = self.hidden_dim
        return {"w1": jax.random.normal(k1, (h, h), jnp.float32) * 0.1,
                "b1": jnp.zeros((h,), jnp.float32),
                "w2": jax.random.normal(k2, (h, h), jnp.float32) * 0.1,
                "b2": jnp.zeros((h,), jnp.float32)}

    def apply(self, params, x, y):
        dt = params["w1"].dtype
        h = jnp.tanh(x.astype(dt) @ params["w1"] + params["b1"])
        out = h @ params["w2"] + params["b2"]
        return jnp.mean(jnp.square(out.astype(jnp.float32) - y))


def _external_master_pair(n):
    """Flat-shard external-master (init, apply) client pair — the 1.5B bench's
    optimizer structure (bench.py) at test scale."""
    def init(params):
        flat = jnp.concatenate([p.reshape(-1).astype(jnp.float32)
                                for p in jax.tree_util.tree_leaves(params)])
        shard = flat[: flat.shape[0] // n]
        return {"master": shard, "m1": jnp.zeros_like(shard),
                "m2": jnp.zeros_like(shard)}

    def apply(grads, opt_state, master, step, hyper):
        g = jnp.concatenate([x.reshape(-1).astype(jnp.float32)
                             for x in jax.tree_util.tree_leaves(grads)])
        gs = g[: opt_state["master"].shape[0]]
        m1 = 0.9 * opt_state["m1"] + 0.1 * gs
        m2 = 0.999 * opt_state["m2"] + 0.001 * gs * gs
        new_master = opt_state["master"] - hyper["lr"] * m1 / (jnp.sqrt(m2) + 1e-8)
        return None, {"master": new_master, "m1": m1, "m2": m2}

    apply.external_master = True
    return init, apply


def _config(batch=BATCH, **overrides):
    cfg = {"train_batch_size": batch, "steps_per_print": 1000,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    cfg.update(overrides)
    return cfg


def _sample_batch(rng_seed=0, batch=BATCH, hidden=HIDDEN):
    rng = np.random.default_rng(rng_seed)
    x = rng.normal(size=(batch, hidden)).astype(np.float32)
    return x, np.tanh(x)


def _build_standard():
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(zero_optimization={"stage": 2}))
    return eng, _sample_batch()


def _build_external_master_fused():
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        optimizer=_external_master_pair(4),
        config_params=_config(zero_optimization={"stage": 2},
                              zero_allow_untested_optimizer=True))
    return eng, _sample_batch()


def _build_external_master_accum():
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        optimizer=_external_master_pair(4),
        config_params=_config(batch=BATCH * 2, gradient_accumulation_steps=2,
                              zero_optimization={"stage": 2},
                              zero_allow_untested_optimizer=True))
    return eng, _sample_batch()


def _build_comm_hierarchical():
    # two-level ICI+DCN grad exchange (uncompressed): reduce-scatter/all-gather
    # ride inside the 2x4 slice factorization, one fp32 psum crosses slices
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(
            zero_optimization={"stage": 2},
            comm={"mode": "hierarchical", "dcn_slices": 2}))
    return eng, _sample_batch()


def _build_comm_compressed():
    # error-feedback 1-bit cross-slice exchange: the DCN phases ship packed u8
    # signs (all-to-all + all-gather) and fp32 per-segment scales
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(
            zero_optimization={"stage": 2},
            comm={"mode": "hierarchical_compressed", "dcn_slices": 2}))
    return eng, _sample_batch()


def _build_comm_overlap():
    # bucketed overlapped exchange over the two-level topology: 0.004 MB
    # buckets split the LintModel into three EQUAL padded buckets
    # ((b1, b2) / (w1) / (w2), 1024 elements each), so the backward issues
    # three independent reduce-scatter/psum/all-gather chains and every
    # bucket's ICI phases fit under the other buckets' in-flight DCN wire —
    # the exposed-ICI == 0 shape the anatomy golden pins (docs/overlap.md)
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(
            zero_optimization={"stage": 2},
            comm={"mode": "hierarchical", "dcn_slices": 2,
                  "overlap": {"mode": "bucketed", "bucket_mb": 0.004}}))
    if len(eng._overlap_plan) != 3:
        raise RuntimeError("lint registry: comm_overlap entry expects the "
                           f"equal 3-bucket plan, got {eng._overlap_plan}")
    return eng, _sample_batch()


def _build_comm_overlap_compressed():
    # bucketed compressed exchange: per-bucket 1-bit DCN phases with the
    # bucketed error-feedback layout — bucket k's all-to-all can overlap
    # bucket k+1's ICI reduce-scatter
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(
            zero_optimization={"stage": 2},
            comm={"mode": "hierarchical_compressed", "dcn_slices": 2,
                  "overlap": {"mode": "bucketed", "bucket_mb": 0.004}}))
    return eng, _sample_batch()


def _build_zero_offload():
    import deepspeed_tpu
    model = LintModel()
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=_config(zero_optimization={"stage": 2,
                                                 "cpu_offload": True}))
    return eng, _sample_batch()


def _build_pipeline():
    # instruction executor, not SPMD: differentiating through the SPMD
    # executor's shard_map needs jax >= 0.5 (tests/unit/oldjax.py), and the
    # registry must capture the same programs on every supported jax. The
    # per-stage local jits are the instruction path's real step programs.
    import deepspeed_tpu
    from ..parallel.pipe import LayerSpec, PipelineModule

    class Dense:
        def __init__(self, dim):
            self.dim = dim

        def init(self, rng, x):
            return {"w": jax.random.normal(rng, (x.shape[-1], self.dim),
                                           jnp.float32) * 0.3}

        def apply(self, p, x):
            return jnp.tanh(x.astype(p["w"].dtype) @ p["w"])

    def mse(out, tgt):
        return jnp.mean(jnp.square(out.astype(jnp.float32)
                                   - tgt.astype(jnp.float32)))

    module = PipelineModule(layers=[LayerSpec(Dense, HIDDEN) for _ in range(4)],
                            num_stages=4, loss_fn=mse)
    params = module.init_params(jax.random.PRNGKey(0),
                                jnp.zeros((4, HIDDEN), jnp.float32))
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config_params={"train_batch_size": 64, "gradient_accumulation_steps": 2,
                       "steps_per_print": 1000,
                       "pipeline": {"spmd": False},
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    if eng._spmd:
        raise RuntimeError("lint registry: pipeline entry must stay on the "
                           "instruction executor")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, HIDDEN)).astype(np.float32)  # one micro-batch
    return eng, (x, np.tanh(x))


def _tiny_gpt2():
    from ..models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


class _DecodeLintAdapter:
    """Engine-shaped wrapper so the gpt2 decode programs (prefill + greedy +
    beam, models/gpt2.py decode_lint_programs) ride the same capture path."""

    def __init__(self, model, params):
        self.model, self.params = model, params

    def lint_programs(self, sample_batch=None):
        return self.model.decode_lint_programs(self.params)

    def memory_manifest(self):
        # params are the only persistent device residents on the dense
        # decode path (caches are per-call arguments, not engine state)
        leaves = jax.tree_util.tree_leaves(self.params)
        psi = sum(int(np.prod(l.shape)) if l.shape else 1 for l in leaves)
        itemsize = int(jnp.dtype(leaves[0].dtype).itemsize) if leaves else 4
        return {"classes": {"params": self.params},
                "geometry": {"kind": "decode", "psi": psi,
                             "param_itemsize": itemsize}}


def _build_gpt2_decode():
    return _DecodeLintAdapter(*_tiny_gpt2()), None


def _build_serving():
    # fixed-shape paged serving programs: decode step, prefill chunk, CoW
    # page copy — the zero-recompile contract ds-tpu serve-sim replays
    from ..serve.engine import InferenceEngine
    model, params = _tiny_gpt2()
    eng = InferenceEngine(model, params, num_slots=4, block_size=4,
                          num_blocks=17, max_model_len=32, prefill_chunk=8)
    return eng, None


def _build_serving_speculative():
    # speculative decoding: the target-side spec_verify program (K+1-wide
    # chunked-prefill-shaped verification over the paged pool) plus the
    # draft-side decode/prefill programs over the draft's own small pool.
    # Self-draft (same model+params) keeps the builder cheap; the programs
    # are shape-identical to a real small-draft deployment. Only the spec
    # programs are captured here — the engine's base decode/prefill/copy
    # programs are geometry-identical to the ``serving`` entry's and already
    # linted there; re-lowering them would double the entry's cost for zero
    # extra coverage
    from ..serve.engine import InferenceEngine
    model, params = _tiny_gpt2()
    eng = InferenceEngine(model, params, num_slots=4, block_size=4,
                          num_blocks=17, max_model_len=32, prefill_chunk=8,
                          speculation={"enabled": True, "draft_model": model,
                                       "draft_params": params,
                                       "max_draft_tokens": 2})

    class _SpecPrograms:
        def lint_programs(self, sample_batch=None):
            return [e for e in eng.lint_programs(sample_batch)
                    if "spec" in e[0]]

        def memory_manifest(self):
            # the wrapped engine's full resident set: the entry captures only
            # the spec programs, so target-only classes report as unobserved
            # in the hbm sweep (resident, but outside this program subset)
            return eng.memory_manifest()

    return _SpecPrograms(), None


def _build_serving_sharded():
    # model-axis sharded serving: same programs lowered over a 2-way head
    # shard. The manifests tighten to a collective BUDGET — decode/prefill
    # must contain exactly n_layer f32 all-reduces (the per-layer proj psum)
    # and nothing else; copy_blocks must stay collective-free (the block axis
    # is unsharded, so GSPMD has nothing to exchange)
    from ..serve.engine import InferenceEngine
    model, params = _tiny_gpt2()
    eng = InferenceEngine(model, params, num_slots=4, block_size=4,
                          num_blocks=17, max_model_len=32, prefill_chunk=8,
                          sharding={"model": 2})
    return eng, None


BUILDERS = {
    "standard": _build_standard,
    "external_master_fused": _build_external_master_fused,
    "external_master_accum": _build_external_master_accum,
    "comm_hierarchical": _build_comm_hierarchical,
    "comm_compressed": _build_comm_compressed,
    "comm_overlap": _build_comm_overlap,
    "comm_overlap_compressed": _build_comm_overlap_compressed,
    "zero_offload": _build_zero_offload,
    "pipeline": _build_pipeline,
    "gpt2_decode": _build_gpt2_decode,
    "serving": _build_serving,
    "serving_speculative": _build_serving_speculative,
    "serving_sharded": _build_serving_sharded,
}


def capture_entry(entry):
    """[ProgramArtifact] for one registry entry, program names prefixed
    ``entry:program``."""
    engine, batch = BUILDERS[entry]()
    artifacts = []
    for name, jitted, args, manifest in engine.lint_programs(batch):
        artifacts.append(ProgramArtifact.capture(f"{entry}:{name}", jitted,
                                                 args, manifest))
    return artifacts


def capture_registry(entries=None):
    """Artifacts for the requested entries (default: all, in name order)."""
    names = sorted(BUILDERS) if not entries else list(entries)
    out = []
    for entry in names:
        out.extend(capture_entry(entry))
    return out
