"""AST lint passes: host-sync, tracer-hostile calls, recompile hazards.

Generalizes the original no-sync guard (tests/unit/test_no_sync_guard.py,
now a thin wrapper over :class:`HostSyncPass`) into reusable repo-wide passes.
Scoping differs by pass:

- ``HostSyncPass`` scans whole modules — it is applied only to modules that
  PROMISE never to sync (the observability stack under ``utils/``); the engine
  legitimately fetches the loss every step and must not be in its scope.
- ``TracerHostilePass`` / ``RecompileHazardPass`` scan only functions that are
  lexically jitted (``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators,
  ``jax.jit(f)`` / ``shard_map(f, ...)`` call sites naming a local def) plus
  their same-module call-graph closure, so host-side code may cast and read
  clocks freely. Full cross-module reachability is intractable statically;
  the lexical closure is exactly the code a trace is guaranteed to enter.

Subjects are ``<repo-relative-path>::<qualname>`` so vids survive unrelated
edits; the same primitive appearing N times in one function is one violation
with ``details["occurrences"] = N``.
"""

import ast
import os

from .model import Violation

HOST_SYNC_ATTRS = ("device_get", "block_until_ready")
HOST_SYNC_NUMPY = ("asarray",)
HOST_CASTS = ("float", "int", "bool")
# attribute chains whose call inside traced code is constant-folded at trace
# time — a different value next trace means silent staleness or a recompile
NONDETERMINISM_CHAINS = (
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("datetime", "now"), ("datetime", "utcnow"), ("date", "today"),
    ("random", "random"), ("random", "randint"), ("random", "uniform"),
)


def _qualname(stack):
    return ".".join(stack) or "<module>"


def parse_module(path, root=None):
    """(tree, repo-relative path) for one source file."""
    with open(path) as f:
        src = f.read()
    rel = os.path.relpath(path, root) if root else path
    return ast.parse(src, filename=path), rel.replace(os.sep, "/")


class _FunctionIndex(ast.NodeVisitor):
    """Collects every function def with its qualname, called names, and
    whether a jit/shard_map construct roots it."""

    def __init__(self):
        self.funcs = {}        # qualname -> node
        self.by_name = {}      # bare name -> [qualname] (lexical resolution)
        self.calls = {}        # qualname -> set of bare names it calls
        self.jit_roots = set() # qualnames lexically jitted
        self._stack = []

    def _mark_jit_target(self, node):
        """``jax.jit(f)`` / ``shard_map(f, ...)``: resolve f to local defs."""
        if isinstance(node, ast.Name):
            for q in self.by_name.get(node.id, ()):
                self.jit_roots.add(q)
        elif isinstance(node, ast.Lambda):
            # the lambda body is traced; it has no qualname of its own, so
            # attribute it to the enclosing function's scope
            self.jit_roots.add(_qualname(self._stack))

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        q = _qualname(self._stack)
        self.funcs[q] = node
        self.by_name.setdefault(node.name, []).append(q)
        self.calls.setdefault(q, set())
        for dec in node.decorator_list:
            if _is_jit_expr(dec):
                self.jit_roots.add(q)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._stack:
            q = _qualname(self._stack)
            if isinstance(node.func, ast.Name):
                self.calls.setdefault(q, set()).add(node.func.id)
        if _is_jit_expr(node.func) or _attr_tail(node.func) == "shard_map" \
                or (isinstance(node.func, ast.Name) and node.func.id == "shard_map"):
            for arg in node.args[:1]:
                self._mark_jit_target(arg)
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...) used as a
        # value: the jit target is whatever the partial is later applied to —
        # handled by the decorator check; nothing to do here.
        self.generic_visit(node)


def _attr_tail(node):
    return node.attr if isinstance(node, ast.Attribute) else None


def _is_jit_expr(node):
    """True for ``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` expressions."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Call):
        f = node.func
        is_partial = (isinstance(f, ast.Name) and f.id == "partial") or \
                     (isinstance(f, ast.Attribute) and f.attr == "partial")
        if is_partial and node.args and _is_jit_expr(node.args[0]):
            return True
    return False


def _jitted_closure(index):
    """Jit roots plus every same-module function transitively called by name.
    Two defs sharing a bare name both enter the closure — over-approximate
    rather than miss traced code."""
    reached = set(index.jit_roots)
    frontier = list(reached)
    while frontier:
        q = frontier.pop()
        for name in index.calls.get(q, ()):
            for callee in index.by_name.get(name, ()):
                if callee not in reached:
                    reached.add(callee)
                    frontier.append(callee)
    return reached


def _collect(tree, visit):
    """Run ``visit(qualname, node)`` over every node with scope tracking."""
    stack = []

    class W(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        def generic_visit(self, node):
            visit(_qualname(stack), node)
            super().generic_visit(node)

    W().visit(tree)


def _dedupe(pass_id, raw):
    """[(rule, subject, message)] -> [Violation] with occurrence counts."""
    seen = {}
    for rule, subject, message in raw:
        key = (rule, subject)
        if key in seen:
            seen[key].details["occurrences"] += 1
        else:
            seen[key] = Violation(pass_id, rule, subject, message,
                                  details={"occurrences": 1})
    return [seen[k] for k in sorted(seen)]


class HostSyncPass:
    """Forbidden host-sync primitives anywhere in the module: ``device_get``,
    ``block_until_ready``, ``np.asarray`` (which silently fetches a device
    array). Scope this pass to modules that promise non-perturbation."""

    pass_id = "ast-host-sync"

    def run(self, tree, rel):
        raw = []

        def visit(qual, node):
            if isinstance(node, ast.Attribute):
                if node.attr in HOST_SYNC_ATTRS:
                    raw.append((node.attr.replace("_", "-"), f"{rel}::{qual}",
                                f"host-sync primitive {node.attr} in {qual}"))
                elif node.attr in HOST_SYNC_NUMPY and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in ("np", "numpy"):
                    raw.append(("np-asarray", f"{rel}::{qual}",
                                f"np.{node.attr} in {qual} fetches device arrays"))

        _collect(tree, visit)
        return _dedupe(self.pass_id, raw)


class TracerHostilePass:
    """``float()``/``int()``/``bool()`` and ``.item()`` on values inside the
    lexically-jitted closure: on a tracer these either raise at trace time or
    force a concretization the author did not intend."""

    pass_id = "ast-tracer-hostile"

    def run(self, tree, rel):
        index = _FunctionIndex()
        index.visit(tree)
        index.visit(tree)  # second sweep: by_name is complete for call-site roots
        jitted = _jitted_closure(index)
        raw = []
        for q in sorted(jitted):
            node = index.funcs.get(q)
            if node is None:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Name) and f.id in HOST_CASTS and \
                        len(sub.args) == 1 and \
                        not isinstance(sub.args[0], ast.Constant):
                    raw.append(("host-cast", f"{rel}::{q}",
                                f"{f.id}() inside jitted {q} concretizes a tracer"))
                elif isinstance(f, ast.Attribute) and f.attr == "item":
                    raw.append(("item-call", f"{rel}::{q}",
                                f".item() inside jitted {q} blocks on the device"))
        return _dedupe(self.pass_id, raw)


class RecompileHazardPass:
    """Recompile / staleness hazards around jitted code: clock- or RNG-reads
    constant-folded into a trace, and ``static_argnums`` marking a parameter
    whose default is an unhashable literal (every call site then raises or
    re-traces)."""

    pass_id = "ast-recompile-hazard"

    def run(self, tree, rel):
        index = _FunctionIndex()
        index.visit(tree)
        index.visit(tree)
        jitted = _jitted_closure(index)
        raw = []
        for q in sorted(jitted):
            node = index.funcs.get(q)
            if node is None:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                    base = sub.func.value
                    base_name = base.id if isinstance(base, ast.Name) else None
                    if (base_name, sub.func.attr) in NONDETERMINISM_CHAINS:
                        raw.append((
                            "nondeterminism-in-trace", f"{rel}::{q}",
                            f"{base_name}.{sub.func.attr}() inside jitted {q} is "
                            "constant-folded at trace time"))
        raw += self._unhashable_static(tree, rel, index)
        return _dedupe(self.pass_id, raw)

    def _unhashable_static(self, tree, rel, index):
        raw = []
        unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                      ast.SetComp)
        for sub in ast.walk(tree):
            if not (isinstance(sub, ast.Call) and _is_jit_expr(sub.func)):
                continue
            statics = {}
            for kw in sub.keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    statics[kw.arg] = kw.value
            if not statics or not sub.args or not isinstance(sub.args[0], ast.Name):
                continue
            for q in index.by_name.get(sub.args[0].id, ()):
                fn = index.funcs.get(q)
                if fn is None:
                    continue
                params = fn.args.args
                defaults = fn.args.defaults
                offset = len(params) - len(defaults)
                for i, p in enumerate(params):
                    d = defaults[i - offset] if i >= offset else None
                    if d is None or not isinstance(d, unhashable):
                        continue
                    hit = False
                    nums = statics.get("static_argnums")
                    if isinstance(nums, ast.Constant) and nums.value == i:
                        hit = True
                    elif isinstance(nums, (ast.Tuple, ast.List)):
                        hit = any(isinstance(e, ast.Constant) and e.value == i
                                  for e in nums.elts)
                    names = statics.get("static_argnames")
                    if isinstance(names, ast.Constant) and names.value == p.arg:
                        hit = True
                    elif isinstance(names, (ast.Tuple, ast.List)):
                        hit = hit or any(isinstance(e, ast.Constant) and
                                         e.value == p.arg for e in names.elts)
                    if hit:
                        raw.append((
                            "unhashable-static", f"{rel}::{q}#{p.arg}",
                            f"static arg {p.arg!r} of {q} defaults to an "
                            "unhashable literal — every jit call raises or "
                            "re-traces"))
        return raw


def run_ast_passes(files, passes, root=None):
    """Run each pass over each file; returns all violations."""
    out = []
    for path in sorted(files):
        tree, rel = parse_module(path, root=root)
        for p in passes:
            out.extend(p.run(tree, rel))
    return out
