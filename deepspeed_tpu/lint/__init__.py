"""Static-analysis lint suite over the framework's compiled programs and source.

Two analysis surfaces share one violation/report model (``model.py``):

- **Program passes** (``program_passes.py``) run over AOT ``lower().compile()``
  artifacts — the same surface the compile watchdog uses — and check donation
  (declared ``donate_argnums`` XLA could not alias), per-program collective
  budgets (expected op kind/count/dtype manifests diffed against the optimized
  HLO), and dtype promotion (f32 dots / lossy convert round-trips inside a
  declared low-precision compute region).
- **AST passes** (``ast_passes.py``) generalize the no-sync guard: forbidden
  host-sync primitives, tracer-hostile host casts reachable from jitted
  functions, and recompile hazards.

``deepspeed_tpu/lint/config_pass.py`` adds the config-key reachability pass;
``registry.py`` builds the representative test-scale engines whose programs
``ds-tpu lint`` checks; ``cli.py`` is the subcommand. See docs/lint.md.
"""

from .model import Allowlist, LintReport, Violation  # noqa: F401
