"""Shared violation / allowlist / report model for both lint surfaces.

A violation's identity is its ``vid`` — ``pass_id:rule:subject`` — and every
subject is constructed deterministically (repo-relative paths, program-local
ordinals, flat argument indices) so the same tree state always produces the
same report bytes. The allowlist is a declarative JSON file of fnmatch globs
over vids, each with a mandatory human reason; ``ds-tpu lint`` exits nonzero
on any violation no glob covers, and reports (but does not fail on) allowlist
entries that matched nothing — a stale entry is how an invariant silently
stops being checked.
"""

import fnmatch
import json


class Violation:
    """One finding. ``severity`` is "error" (fails the run) or "warning"."""

    def __init__(self, pass_id, rule, subject, message, severity="error", details=None):
        self.pass_id = pass_id
        self.rule = rule
        self.subject = subject
        self.message = message
        self.severity = severity
        self.details = dict(details or {})

    @property
    def vid(self):
        return f"{self.pass_id}:{self.rule}:{self.subject}"

    def to_dict(self):
        d = {"id": self.vid, "pass": self.pass_id, "rule": self.rule,
             "subject": self.subject, "severity": self.severity,
             "message": self.message}
        if self.details:
            d["details"] = self.details
        return d

    def __repr__(self):
        return f"Violation({self.vid!r})"


class Allowlist:
    """Declarative vid allowlist: ``{"allow": [{"id": glob, "reason": str}]}``."""

    def __init__(self, entries=()):
        self.entries = []
        for e in entries:
            if not isinstance(e, dict) or "id" not in e or not e.get("reason"):
                raise ValueError(
                    f"allowlist entry needs 'id' and a non-empty 'reason': {e!r}")
            self.entries.append({"id": e["id"], "reason": e["reason"]})
        self._hits = {e["id"]: 0 for e in self.entries}

    @classmethod
    def load(cls, path):
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or not isinstance(data.get("allow", []), list):
            raise ValueError(
                f"{path}: allowlist must be {{\"allow\": [{{'id', 'reason'}}, ...]}}")
        return cls(data.get("allow", []))

    def match(self, vid):
        """First entry whose glob covers ``vid`` (entry order is priority)."""
        for e in self.entries:
            if fnmatch.fnmatchcase(vid, e["id"]):
                self._hits[e["id"]] += 1
                return e
        return None

    def unused(self):
        return sorted(g for g, n in self._hits.items() if n == 0)


class LintReport:
    """Deterministic aggregate of one lint run.

    ``to_json()`` is byte-stable for a given repo state: no timestamps, sorted
    keys, violations ordered by vid then message.
    """

    def __init__(self):
        self.violations = []       # non-allowlisted
        self.allowlisted = []      # (violation, reason)
        self.passes = []           # pass ids that ran
        self.programs = []         # program names analyzed
        self.unused_allow = []

    def add(self, violation, allowlist=None):
        entry = allowlist.match(violation.vid) if allowlist is not None else None
        if entry is not None:
            self.allowlisted.append((violation, entry["reason"]))
        else:
            self.violations.append(violation)

    def extend(self, violations, allowlist=None):
        for v in violations:
            self.add(v, allowlist)

    def finish(self, allowlist=None):
        if allowlist is not None:
            self.unused_allow = allowlist.unused()

    @property
    def failed(self):
        return any(v.severity == "error" for v in self.violations)

    def to_dict(self):
        def key(v):
            return (v.vid, v.message)

        return {
            "passes": sorted(self.passes),
            "programs": sorted(self.programs),
            "violations": [v.to_dict() for v in sorted(self.violations, key=key)],
            "allowlisted": [dict(v.to_dict(), allow_reason=reason)
                            for v, reason in sorted(self.allowlisted,
                                                    key=lambda p: key(p[0]))],
            "unused_allowlist_entries": list(self.unused_allow),
            "summary": {
                "violations": len(self.violations),
                "allowlisted": len(self.allowlisted),
                "failed": self.failed,
            },
        }

    def to_json(self):
        return json.dumps(self.to_dict(), sort_keys=True, indent=2,
                          separators=(",", ": ")) + "\n"
