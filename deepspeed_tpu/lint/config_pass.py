"""Config-key reachability pass.

``runtime/constants.py`` declares config keys as ``NAME = "json_key"`` paired
with ``NAME_DEFAULT = ...``. A key constant whose name is never referenced
from a config-consuming module is a key users can set that nothing reads —
exactly the silent no-op the config test sweep exists to prevent, but caught
at the *declaration* instead of needing a hand-written probe per key.
"""

import ast
import os

from .model import Violation

# modules that consume key constants (all use `from .constants import *` or
# explicit imports); a key referenced in any of them is reachable
CONSUMER_RELPATHS = (
    "runtime/config.py",
    "runtime/engine.py",
    "runtime/zero/config.py",
    "runtime/activation_checkpointing/config.py",
    "runtime/pipe/engine.py",
)


def declared_key_constants(constants_path):
    """{NAME: json_key} for every NAME = "str" with a NAME_DEFAULT sibling."""
    with open(constants_path) as f:
        tree = ast.parse(f.read(), filename=constants_path)
    assigns = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            assigns[node.targets[0].id] = node.value
    keys = {}
    for name, value in assigns.items():
        if name.endswith("_DEFAULT") or not isinstance(value, ast.Constant) \
                or not isinstance(value.value, str):
            continue
        if f"{name}_DEFAULT" in assigns:
            keys[name] = value.value
    return keys


def _referenced_names(path):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    return {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}


class ConfigKeysPass:
    pass_id = "config-keys"

    def __init__(self, package_dir):
        self.package_dir = package_dir

    def run(self):
        constants_path = os.path.join(self.package_dir, "runtime", "constants.py")
        keys = declared_key_constants(constants_path)
        referenced = set()
        for rel in CONSUMER_RELPATHS:
            path = os.path.join(self.package_dir, rel)
            if os.path.exists(path):
                referenced |= _referenced_names(path)
        out = []
        for name in sorted(keys):
            if name in referenced:
                continue
            out.append(Violation(
                self.pass_id, "unreachable-key",
                f"runtime/constants.py::{name}",
                f"config key constant {name} (json key {keys[name]!r}) has a "
                "_DEFAULT but is never referenced from any config-consuming "
                "module — users can set a key nothing reads",
                details={"json_key": keys[name]}))
        return out
