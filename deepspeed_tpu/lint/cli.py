"""``ds-tpu lint`` — run the static-analysis suite and emit a report.

Two surfaces, one report:

* **AST passes** walk every ``.py`` file under the installed ``deepspeed_tpu``
  package — host-sync primitives, tracer-hostile casts inside jitted closures,
  recompile hazards, and config-key reachability. Pure host work, no jax
  import needed.
* **Program passes** build the registry of representative test-scale engines
  on an 8-virtual-device CPU mesh, capture every program on each engine's
  active step path via ``engine.lint_programs``, and diff donation /
  collective-budget / dtype-promotion facts against the engines' own
  manifests.

Violations matching ``allowlist.json`` (shipped next to this module; override
with ``--allowlist``) are reported but do not fail the run; allowlist entries
that match nothing are flagged so the list cannot rot. Exit status is 1 iff
any non-allowlisted violation remains. ``--json`` output is deterministic
byte-for-byte for a given repo state: violations are sorted by id and carry
no timestamps or absolute paths.
"""

import argparse
import json
import os
import sys

from .model import Allowlist, LintReport

_DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "allowlist.json")


def _package_dir():
    import deepspeed_tpu
    return os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))


def _package_files(package_dir):
    out = []
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def run_ast_surface(report, allowlist, package_dir=None):
    from .ast_passes import (HostSyncPass, RecompileHazardPass,
                             TracerHostilePass, run_ast_passes)
    from .config_pass import ConfigKeysPass
    pkg = package_dir or _package_dir()
    root = os.path.dirname(pkg)
    # the host-sync (no-perturbation) contract covers the observability tier:
    # utils/ plus the serving request-trace ledger — the data path syncs on
    # purpose (loss fetch, batch placement). Tracer-hostility and recompile
    # hazards are properties of any jitted code, so those passes sweep the
    # whole package.
    utils_files = [f for f in _package_files(pkg)
                   if f.startswith(os.path.join(pkg, "utils") + os.sep)
                   or f == os.path.join(pkg, "serve", "request_trace.py")]
    host_sync = HostSyncPass()
    report.passes.append(host_sync.pass_id)
    report.extend(run_ast_passes(utils_files, (host_sync,), root=root),
                  allowlist)
    passes = (TracerHostilePass(), RecompileHazardPass())
    report.passes += [p.pass_id for p in passes]
    report.extend(run_ast_passes(_package_files(pkg), passes, root=root),
                  allowlist)
    config_pass = ConfigKeysPass(pkg)
    report.passes.append(config_pass.pass_id)
    report.extend(config_pass.run(), allowlist)


def run_program_surface(report, allowlist, entries=None):
    from . import registry
    from .program_passes import PROGRAM_PASSES, run_program_passes
    report.passes += [p.pass_id for p in PROGRAM_PASSES]
    for entry in (sorted(registry.BUILDERS) if not entries else list(entries)):
        artifacts = registry.capture_entry(entry)
        report.programs += [a.name for a in artifacts]
        report.extend(run_program_passes(artifacts), allowlist)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds-tpu lint",
        description="donation / collective / dtype / host-sync static "
                    "analysis over the package and its AOT-lowered programs")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON on stdout")
    parser.add_argument("--out", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--allowlist", metavar="PATH",
                        default=_DEFAULT_ALLOWLIST,
                        help="violation allowlist (default: the shipped one)")
    parser.add_argument("--ast-only", action="store_true",
                        help="skip the program surface (no engine builds)")
    parser.add_argument("--programs-only", action="store_true",
                        help="skip the AST surface")
    parser.add_argument("--entry", action="append", metavar="NAME",
                        help="limit the program surface to a registry entry "
                             "(repeatable)")
    args = parser.parse_args(argv)

    # stdout belongs to the report: the framework logger defaults to stdout,
    # which would interleave engine-build INFO lines into `--json > out.json`
    import logging
    for h in logging.getLogger("DeepSpeedTPU").handlers:
        if isinstance(h, logging.StreamHandler) and h.stream is sys.stdout:
            h.stream = sys.stderr

    allowlist = Allowlist.load(args.allowlist)
    report = LintReport()
    if not args.programs_only:
        run_ast_surface(report, allowlist)
    if not args.ast_only:
        run_program_surface(report, allowlist, entries=args.entry)
    report.finish(allowlist)

    text = report.to_json()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    if args.json:
        sys.stdout.write(text)
    else:
        for v in sorted(report.violations, key=lambda v: (v.vid, v.message)):
            print(f"FAIL {v.vid}\n     {v.message}")
        for v, reason in sorted(report.allowlisted,
                                key=lambda p: (p[0].vid, p[0].message)):
            print(f"allow {v.vid} ({reason})")
        for vid in report.unused_allow:
            print(f"stale-allowlist {vid}")
        n = len(report.violations)
        print(f"{n} violation(s), {len(report.allowlisted)} allowlisted, "
              f"{len(report.programs)} program(s), "
              f"{len(report.passes)} pass(es)")
    return 1 if report.failed else 0


if __name__ == "__main__":
    sys.exit(main())
