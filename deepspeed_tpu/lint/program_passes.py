"""Program lint passes over AOT ``lower().compile()`` artifacts.

A :class:`ProgramArtifact` captures everything one jitted program exposes
before it ever executes — flattened ``args_info`` donation flags, compile-time
warnings (XLA raises "Some donated buffers were not usable" here), the
optimized HLO text, and ``memory_analysis`` when the backend provides one.
The passes then check the artifact against the program's declared **manifest**:

``donation``
    ``{"check_unusable": bool, "min_undonated_bytes": int|None}`` —
    ``unusable-donation`` flags declared ``donate_argnums`` XLA did not alias
    (cross-checked against the module header's ``input_output_alias``);
    ``undonated-aliasable`` flags inputs >= ``min_undonated_bytes`` whose
    (shape, dtype) matches an entry result but which were not donated — each
    one is a buffer of avoidable peak HBM, reported as a waste estimate.

``collectives`` / ``any_reduction`` / ``strict``
    The expected-collective budget: ``{op: {"min", "max", "dtypes"}}`` diffed
    against the optimized HLO. Only instructions whose largest result exceeds
    ``small_element_threshold`` elements count — scalar loss pmeans and norm
    all-reduces ride free; "full-parameter-scale" traffic is what manifests
    constrain. ``any_reduction`` budgets all-reduce + reduce-scatter together
    because XLA's CPU pipeline does not run the reduce-scatter rewrite the TPU
    pipeline applies (tests/unit/test_collectives_hlo.py). With ``strict``,
    any large collective not covered by a budget is ``undeclared-collective``
    — a full-param all-gather appearing in a ZeRO-2 backward fails here.

``compute_dtype``
    When "bf16"/"f16", the dtype-promotion pass flags f32 dots fed by converts
    from the low-precision dtype and lossy d1→d2→d1 convert round-trips.
    Subjects use per-program ordinals (``prog#dot0``) so vids are stable
    across XLA instruction renamings.

Two analyses deliberately read different HLO stages. Collectives only exist
**after** SPMD partitioning, so the budget pass reads the optimized module.
But the CPU backend's float-normalization pass emulates bf16 arithmetic as
``convert→f32 op→convert`` in that same module, which would make every bf16
dot look like an author-written f32 promotion — so the dtype pass reads the
**unoptimized** (pre-backend) HLO, where the author's dtypes survive intact.
Float normalization also rewrites bf16 all-reduces to f32 on the wire, so on
the CPU platform a declared low-precision comm dtype implicitly admits f32.
"""

import warnings

import jax

from ..utils import hlo
from .model import Violation

SMALL_ELEMENT_THRESHOLD = 256
REDUCTION_OPS = ("all-reduce", "reduce-scatter")


class ProgramArtifact:
    """Static capture of one jitted program: HLO + arg metadata + warnings."""

    def __init__(self, name, hlo_text, args_info, compile_warnings, memory_stats,
                 manifest, lowered_text=None, platform=None, cost_stats=None):
        self.name = name
        self.hlo_text = hlo_text            # optimized (post-backend) HLO
        self.lowered_text = lowered_text or hlo_text  # pre-backend HLO
        self.platform = platform or ""
        self.args_info = args_info          # [(donated, shape, dtype_str)] flat
        self.compile_warnings = compile_warnings
        self.memory_stats = memory_stats    # dict or {}
        self.cost_stats = dict(cost_stats or {})  # cost_analysis flops/bytes
        self.manifest = dict(manifest or {})

    @classmethod
    def capture(cls, name, jitted, args, manifest=None, kwargs=None):
        lowered = jitted.lower(*args, **(kwargs or {}))
        try:
            lowered_text = lowered.as_text(dialect="hlo")
        except Exception:
            lowered_text = None
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            compiled = lowered.compile()
        info = []
        for ai in jax.tree_util.tree_leaves(lowered.args_info):
            aval = getattr(ai, "_aval", None) or getattr(ai, "aval", None)
            shape = tuple(getattr(aval, "shape", ()) or ())
            dtype = str(getattr(aval, "dtype", "")) or ""
            info.append((bool(getattr(ai, "donated", False)), shape, dtype))
        mem = {}
        try:
            ma = compiled.memory_analysis()
            for field in ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes", "alias_size_in_bytes",
                          "generated_code_size_in_bytes"):
                val = getattr(ma, field, None)
                if val is not None:
                    mem[field] = int(val)
        except Exception:
            pass
        cost = {}
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            for key, field in (("flops", "flops"),
                               ("bytes accessed", "bytes_accessed")):
                val = (ca or {}).get(key)
                if val is not None:
                    cost[field] = float(val)
        except Exception:
            pass
        return cls(name, compiled.as_text(),
                   info, [str(w.message) for w in caught], mem, manifest,
                   lowered_text=lowered_text, platform=jax.default_backend(),
                   cost_stats=cost)


# jnp dtype name -> HLO element type string
_HLO_DTYPE = {"float32": "f32", "float16": "f16", "bfloat16": "bf16",
              "float64": "f64", "int32": "s32", "int64": "s64", "int16": "s16",
              "int8": "s8", "uint32": "u32", "uint64": "u64", "uint16": "u16",
              "uint8": "u8", "bool": "pred"}


def _hlo_dtype(np_name):
    return _HLO_DTYPE.get(np_name, np_name)


def _elem_bytes(dt):
    return hlo.dtype_bytes(dt) or 0


def _nbytes(shape, dt):
    n = 1
    for d in shape:
        n *= d
    return n * _elem_bytes(dt)


class DonationPass:
    pass_id = "program-donation"

    def run(self, artifact):
        man = artifact.manifest.get("donation", {})
        out = []
        if man.get("check_unusable", True):
            out += self._unusable(artifact)
        min_bytes = man.get("min_undonated_bytes")
        if min_bytes is not None:
            out += self._undonated(artifact, int(min_bytes))
        return out

    def _unusable(self, artifact):
        aliases = hlo.input_output_aliases(artifact.hlo_text)
        params = hlo.entry_parameter_types(artifact.hlo_text)
        # flat jit-arg index == entry param number only when nothing was
        # hoisted; on a mismatch fall back to the compile warning alone.
        indexable = len(params) == len(artifact.args_info)
        warned = any("donated buffers were not usable" in w.lower()
                     for w in artifact.compile_warnings)
        out = []
        for i, (donated, shape, dtype) in enumerate(artifact.args_info):
            if not donated:
                continue
            if indexable and i in aliases:
                continue
            if not indexable and not warned:
                continue
            out.append(Violation(
                self.pass_id, "unusable-donation", f"{artifact.name}#arg{i}",
                f"{artifact.name}: donated arg {i} "
                f"({_hlo_dtype(dtype)}{list(shape)}) was not aliased by XLA — "
                "the buffer is held live anyway and the donation is a no-op",
                details={"shape": list(shape), "dtype": _hlo_dtype(dtype),
                         "bytes": _nbytes(shape, _hlo_dtype(dtype)),
                         "compile_warned": warned}))
        return out

    def _undonated(self, artifact, min_bytes):
        aliases = hlo.input_output_aliases(artifact.hlo_text)
        results = hlo.entry_result_types(artifact.hlo_text)
        result_shapes = {(dt, dims) for dt, dims in results}
        out = []
        for i, (donated, shape, dtype) in enumerate(artifact.args_info):
            if donated or i in aliases:
                continue
            dt = _hlo_dtype(dtype)
            nbytes = _nbytes(shape, dt)
            if nbytes < min_bytes:
                continue
            if (dt, tuple(shape)) not in result_shapes:
                continue
            out.append(Violation(
                self.pass_id, "undonated-aliasable", f"{artifact.name}#arg{i}",
                f"{artifact.name}: arg {i} ({dt}{list(shape)}, {nbytes} bytes) "
                "matches an output shape/dtype but is not donated — "
                f"~{nbytes} bytes of avoidable peak HBM",
                details={"shape": list(shape), "dtype": dt,
                         "hbm_waste_bytes": nbytes}))
        return out


def _large_collectives(artifact):
    """[(op, [dtypes of large results], max_elements)] per collective
    instruction whose largest result crosses the size threshold."""
    threshold = artifact.manifest.get("small_element_threshold",
                                      SMALL_ELEMENT_THRESHOLD)
    out = []
    for result_ty, op, is_start in hlo._collective_matches(artifact.hlo_text):
        shaped = hlo._result_shapes(result_ty, op, is_start)
        big = [(dt, dims) for dt, dims in shaped
               if hlo._elements(dims) > threshold]
        if big:
            out.append((op, sorted({dt for dt, _ in big}),
                        max(hlo._elements(dims) for _, dims in big)))
    return out


def _admitted_dtypes(allowed, platform):
    """Declared comm dtypes, widened with f32 on CPU where float
    normalization rewrites low-precision reductions to f32 on the wire."""
    admitted = set(allowed)
    if platform == "cpu" and admitted & {"bf16", "f16"}:
        admitted.add("f32")
    return admitted


class CollectiveBudgetPass:
    pass_id = "program-collectives"

    def run(self, artifact):
        man = artifact.manifest
        budgets = dict(man.get("collectives", {}))
        any_red = man.get("any_reduction")
        strict = bool(man.get("strict", True))
        large = _large_collectives(artifact)
        out = []

        counts = {}
        dtypes_seen = {}
        for op, dts, _n in large:
            counts[op] = counts.get(op, 0) + 1
            dtypes_seen.setdefault(op, set()).update(dts)

        red_count = sum(counts.get(op, 0) for op in REDUCTION_OPS)
        for op in sorted(set(counts) | set(budgets)):
            budget = budgets.get(op)
            n = counts.get(op, 0)
            covered_by_red = any_red is not None and op in REDUCTION_OPS
            if budget is None and covered_by_red:
                continue
            if budget is None:
                if strict and n > 0:
                    out.append(Violation(
                        self.pass_id, "undeclared-collective",
                        f"{artifact.name}#{op}",
                        f"{artifact.name}: {n} large {op} instruction(s) "
                        "appear but the manifest declares no budget for the op",
                        details={"count": n,
                                 "dtypes": sorted(dtypes_seen.get(op, ()))}))
                continue
            lo = budget.get("min", 0)
            hi = budget.get("max")
            if n < lo:
                out.append(Violation(
                    self.pass_id, "count-missing", f"{artifact.name}#{op}",
                    f"{artifact.name}: expected >= {lo} large {op}, found {n}",
                    details={"count": n, "min": lo}))
            if hi is not None and n > hi:
                out.append(Violation(
                    self.pass_id, "count-exceeded", f"{artifact.name}#{op}",
                    f"{artifact.name}: expected <= {hi} large {op}, found {n}",
                    details={"count": n, "max": hi}))
            allowed = budget.get("dtypes")
            if allowed:
                bad = sorted(dtypes_seen.get(op, set())
                             - _admitted_dtypes(allowed, artifact.platform))
                if bad:
                    out.append(Violation(
                        self.pass_id, "comm-dtype", f"{artifact.name}#{op}",
                        f"{artifact.name}: {op} carries {bad} on the wire but "
                        f"the manifest allows only {sorted(allowed)}",
                        details={"found": bad, "allowed": sorted(allowed)}))
        if any_red is not None:
            lo = any_red.get("min", 0)
            hi = any_red.get("max")
            subj = f"{artifact.name}#any-reduction"
            if red_count < lo:
                out.append(Violation(
                    self.pass_id, "count-missing", subj,
                    f"{artifact.name}: expected >= {lo} large reduction "
                    f"collective(s) (all-reduce/reduce-scatter), found {red_count}",
                    details={"count": red_count, "min": lo}))
            if hi is not None and red_count > hi:
                out.append(Violation(
                    self.pass_id, "count-exceeded", subj,
                    f"{artifact.name}: expected <= {hi} large reduction "
                    f"collective(s), found {red_count}",
                    details={"count": red_count, "max": hi}))
            allowed = any_red.get("dtypes")
            if allowed:
                seen = set()
                for op in REDUCTION_OPS:
                    seen |= dtypes_seen.get(op, set())
                bad = sorted(seen - _admitted_dtypes(allowed, artifact.platform))
                if bad:
                    out.append(Violation(
                        self.pass_id, "comm-dtype", subj,
                        f"{artifact.name}: reduction collectives carry {bad} "
                        f"but the manifest allows only {sorted(allowed)}",
                        details={"found": bad, "allowed": sorted(allowed)}))
        return out


class DtypePromotionPass:
    pass_id = "program-dtype"

    def run(self, artifact):
        compute = artifact.manifest.get("compute_dtype")
        if compute not in ("bf16", "f16"):
            return []
        # pre-backend HLO: CPU float-normalization has not yet rewritten the
        # author's bf16 arithmetic into convert-wrapped f32 ops
        text = artifact.lowered_text
        out = []
        dots = hlo.f32_dots_with_lowp_operands(text, lowp=(compute,))
        for i, (dot_name, operands) in enumerate(dots):
            out.append(Violation(
                self.pass_id, "f32-dot-in-lowp-region",
                f"{artifact.name}#dot{i}",
                f"{artifact.name}: f32 dot fed by convert(s) from {compute} — "
                "a matmul the author believed ran on the low-precision MXU "
                "path was silently promoted",
                details={"hlo_name": dot_name, "operands": operands}))
        trips = hlo.lossy_convert_roundtrips(text)
        for i, (name, chain) in enumerate(trips):
            out.append(Violation(
                self.pass_id, "lossy-convert-roundtrip",
                f"{artifact.name}#convert{i}",
                f"{artifact.name}: value round-trips {'->'.join(chain)} — the "
                "narrowing leg truncates mantissa and usually marks a dtype "
                "boundary drawn in the wrong place",
                details={"hlo_name": name, "chain": list(chain)}))
        return out


PROGRAM_PASSES = (DonationPass(), CollectiveBudgetPass(), DtypePromotionPass())


def run_program_passes(artifacts, passes=PROGRAM_PASSES):
    out = []
    for artifact in artifacts:
        for p in passes:
            out.extend(p.run(artifact))
    return out
