"""GPT-2 family model, TPU-first.

Flagship decoder LM for the framework benchmarks (BASELINE.json: GPT-2 1.5B ZeRO-2). The
reference trains GPT-2 through external Megatron-LM (tests/model/Megatron_GPT2); here the
model is in-tree, a pure-function pytree model:

- bf16-friendly: all matmuls carry ``preferred_element_type=float32`` accumulation;
- static shapes, layer loop unrolled (or remat-scanned) for XLA;
- attention dispatches to the Pallas flash-attention kernel on TPU when enabled, with a
  dense fallback (ops/pallas/flash_attention.py);
- weights laid out [in, out] so the ``model``-axis TP sharding (attention heads / MLP
  columns) is a pure PartitionSpec choice.
"""

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0          # dropout is applied via stateless PRNG when > 0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = False
    # Fused Pallas transformer-block kernel (ops/pallas/fused_block.py): the
    # whole attention half — LN + fused qkv + causal attention + output
    # projection + residual — runs as ONE kernel, so none of the block's
    # intermediate [B, T, E] tensors round-trip through HBM (the path the
    # anatomy roofline flags as HBM-bound). Takes precedence over
    # use_flash_attention when eligible; requires dropout == 0 and no
    # sparse_attention, and falls back to the unfused path under manual TP /
    # sequence parallelism (the kernel is single-chip, whole-row K/V).
    fused_block: bool = False
    remat: bool = False            # activation checkpointing over blocks
    remat_policy: Any = None       # None=full recompute; "dots"=save matmul outputs
    loss_chunk: int = 128          # seq-chunked fused CE (0 = materialize full logits)
    compute_dtype: Any = jnp.bfloat16
    # Mixture-of-Experts (parallel/moe.py): 0 = dense FFN everywhere. When > 0,
    # every ``moe_every``-th block replaces its MLP with a switch-style MoE FFN;
    # the training loss gains ``moe_aux_weight`` x the Switch load-balancing term.
    # Expert parallelism comes from param_shardings(mesh): expert weights shard
    # their leading E axis over the ``model`` mesh axis and GSPMD partitions the
    # batched expert einsums across it.
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Block-sparse attention (ops/sparse_attention + the Pallas kernel): a
    # SparsityConfig instance (BigBird/Fixed/Variable/BSLongformer...) replaces
    # dense/flash attention in every block — causal training over the layout's
    # block pattern (the kernel's causal mask composes with the layout, so
    # bidirectional layouts are safely clipped to the lower triangle). The
    # layout is built once per sequence length and cached on the model.
    # Constraints: no attention dropout (the sparse kernel has no in-kernel
    # PRNG), not composable with ring sequence parallelism; decode
    # (generate/beam_search) stays dense-incremental.
    sparse_attention: Any = None

    # named sizes for convenience
    @property
    def head_dim(self):
        return self.n_embd // self.n_head


def _dense_init(rng, shape, scale):
    return jax.random.normal(rng, shape, jnp.float32) * scale


def qkv_tp_permutation(n_embd: int, tp: int) -> "np.ndarray":
    """Column permutation turning the ``[q | k | v]`` fused-qkv layout into rank-grouped
    ``[q_0 k_0 v_0 | q_1 k_1 v_1 | ...]`` so a contiguous model-axis shard of width
    3*n_embd/tp is a valid local (q, k, v) triple for manual (shard_map) TP. GSPMD TP
    needs no permutation — it keeps global semantics through the qkv split."""
    import numpy as np
    per = n_embd // tp
    cols = []
    for r in range(tp):
        for third in range(3):
            start = third * n_embd + r * per
            cols.append(np.arange(start, start + per))
    return np.concatenate(cols)


class GPT2Model:
    """Pure-function GPT-2: ``init(rng) -> params``, ``apply(params, tokens[, labels])``.

    Tensor parallelism comes in two flavors (SURVEY §2.3: TP is first-class here where
    the reference delegated to Megatron's mpu):
    - GSPMD: pass ``param_shardings(mesh)`` to the engine; XLA inserts the collectives
      from the Megatron-style weight layouts (requires ``use_flash_attention=False`` —
      a Pallas call cannot be auto-partitioned over the model axis).
    - Manual (inside ``shard_map``, e.g. the SPMD pipeline): ``with_tp(axis, size)``
      returns a model whose attention/MLP consume model-axis weight shards and psum the
      row-parallel projections, the Megatron forward exactly.
    """

    def __init__(self, config: GPT2Config):
        self.config = config
        self.tp_axis = None   # set via with_tp() for manual-collective (shard_map) TP
        self.tp_size = 1
        self.seq_axis = None  # set via with_sequence_parallel() for ring attention
        self.seq_schedule = "zigzag"  # causal ring schedule ("zigzag" | "masked")
        self._sparse_layouts = {}  # seq_len -> block layout (host numpy), built once
        if config.sparse_attention is not None:
            assert config.dropout == 0.0, \
                "sparse_attention has no in-kernel dropout; set dropout=0"
        if config.fused_block:
            assert config.dropout == 0.0, \
                "fused_block has no in-kernel dropout; set dropout=0"
            assert config.sparse_attention is None, \
                "fused_block and sparse_attention are mutually exclusive"
        self._moe = None
        if config.moe_experts > 0:
            assert config.moe_every >= 1, \
                f"moe_every must be >= 1 (got {config.moe_every})"
            from ..parallel.moe import MoELayer
            # single-program dense dispatch, routed PER SEQUENCE ROW (the GShard
            # group convention — ungrouped dispatch is O((B*T)^2) memory); expert
            # PARALLELISM comes from param_shardings' leading-E layouts (GSPMD
            # partitions the batched expert einsums over the model axis)
            self._moe = MoELayer(config.n_embd, 4 * config.n_embd,
                                 config.moe_experts,
                                 capacity_factor=config.moe_capacity_factor,
                                 group_size=config.n_positions)

    def with_tp(self, axis: str, size: int) -> "GPT2Model":
        """A copy configured for manual tensor parallelism over mesh axis ``axis``."""
        assert self.config.n_head % size == 0, \
            f"n_head={self.config.n_head} must divide by tp size {size}"
        assert (4 * self.config.n_embd) % size == 0
        assert self.config.moe_experts == 0, \
            "MoE blocks do not compose with manual TP (use GSPMD expert sharding)"
        assert self.config.sparse_attention is None, \
            "sparse_attention does not compose with manual TP (per-rank head "\
            "layouts are not split)"
        m = GPT2Model(self.config)
        m.tp_axis = axis
        m.tp_size = size
        return m

    def with_sequence_parallel(self, axis: str, schedule: str = "zigzag") -> "GPT2Model":
        """A copy configured for ring-attention sequence parallelism over mesh axis
        ``axis``: call inside shard_map with tokens/activations sharded over the
        SEQUENCE dim (see ``sequence_parallel_loss_fn`` for the packaged wrapper).
        ``schedule`` picks the causal ring: ``"zigzag"`` (default — balanced
        early+late chunk layout, no masked-compute tax; tokens must arrive in the
        ``zigzag_shard`` order and positions follow the interleave) or
        ``"masked"`` (contiguous chunks, the original oracle). Position
        embeddings map local positions to global; attention runs the ppermute
        ring (parallel/ring_attention.py). Long-context path past the
        single-chip flash kernel's whole-K/V VMEM cap."""
        from ..parallel.ring_attention import SCHEDULES
        assert schedule in SCHEDULES, \
            f"schedule must be one of {SCHEDULES}, got {schedule!r}"
        assert self.tp_axis is None, \
            "sequence parallelism does not compose with manual TP yet"
        assert self.config.sparse_attention is None, \
            "sparse_attention does not compose with ring sequence parallelism " \
            "(the ring path would silently ignore the layout)"
        # MoE composes: the dense dispatch routes each rank's LOCAL sequence chunk
        # (per-chunk capacity; experts replicated inside the shard_map) and the aux
        # term is pmean'd unweighted alongside the count-weighted CE
        m = GPT2Model(self.config)
        m.seq_axis = axis
        m.seq_schedule = schedule
        return m

    def sequence_parallel_loss_fn(self, mesh, axis: str, schedule: str = "zigzag"):
        """``model_fn(params, tokens, labels, rng=None) -> loss`` for the engine:
        shard_map over ``axis`` with the sequence dim of tokens/labels sharded and
        ring attention inside. ``labels`` must be globally next-token-shifted
        BEFORE sharding (the shift crosses chunk boundaries). Pass ``rng`` to
        enable dropout (config.dropout > 0): attention dropout runs in-ring with
        global-coordinate masks; hidden dropout decorrelates per rank.

        Under the default ``schedule="zigzag"`` the wrapper reorders tokens AND
        labels into the zigzag layout (one static gather each) before sharding,
        so callers keep passing natural-order sequences; the scalar loss needs no
        inverse. The per-token CE is weighted by global valid counts, which is
        permutation-invariant, so the loss equals the masked schedule's exactly
        (up to flash-merge rounding)."""
        from jax.sharding import PartitionSpec as P
        sp = self.with_sequence_parallel(axis, schedule=schedule)
        n_ranks = mesh.shape[axis]
        tok_spec = P(None, axis)

        def model_fn(params, tokens, labels, rng=None):
            if schedule == "zigzag":
                from ..parallel.ring_attention import zigzag_shard
                tokens = zigzag_shard(tokens, n_ranks, axis=1)
                labels = zigzag_shard(labels, n_ranks, axis=1)
            def local(params, tokens, labels, *r):
                # sum-of-losses / sum-of-counts across ranks: with ignore labels
                # (-100) the per-rank VALID counts differ, so a pmean of per-rank
                # means would over-weight ranks holding masked positions (and a
                # fully-masked chunk would scale the loss by (sp-1)/sp). The MoE
                # aux term is a per-chunk load-balancing mean, NOT a per-token
                # loss — it stays a plain pmean so label masking can't reweight
                # (or, for a fully-masked rank, drop) its contribution.
                ce_mean, aux = sp.apply_parts(params, tokens, labels,
                                              rng=(r[0] if r else None))
                n_valid = jnp.sum((labels >= 0).astype(jnp.float32))
                total = jax.lax.psum(ce_mean * n_valid, axis)
                count = jax.lax.psum(n_valid, axis)
                return total / jnp.maximum(count, 1.0) + jax.lax.pmean(aux, axis)

            args = (params, tokens, labels) + (() if rng is None else (rng,))
            in_specs = (P(), tok_spec, tok_spec) + (() if rng is None else (P(),))
            from ..parallel.mesh import shard_map
            return shard_map(local, mesh=mesh, in_specs=in_specs,
                             out_specs=P(), check_vma=False)(*args)

        return model_fn

    def param_shardings(self, mesh):
        """Megatron-style TP layouts over the mesh's ``model`` axis for the GSPMD path:
        column-parallel c_attn/c_fc (output dim sharded), row-parallel c_proj (input dim
        sharded), vocab-sharded embedding; norms/biases-of-row-parallel replicated."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import MODEL_AXIS

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        repl = ns()
        ln = {"scale": repl, "bias": repl}
        block = {
            "ln_1": ln,
            "attn": {"c_attn_w": ns(None, MODEL_AXIS), "c_attn_b": ns(MODEL_AXIS),
                     "c_proj_w": ns(MODEL_AXIS, None), "c_proj_b": repl},
            "ln_2": ln,
            "mlp": {"c_fc_w": ns(None, MODEL_AXIS), "c_fc_b": ns(MODEL_AXIS),
                    "c_proj_w": ns(MODEL_AXIS, None), "c_proj_b": repl},
        }
        if self._moe is not None:
            moe_block = {k: v for k, v in block.items() if k != "mlp"}
            moe_block["moe"] = self._moe.param_shardings(mesh, MODEL_AXIS)
            blocks = [moe_block if self._is_moe_block(i) else block
                      for i in range(self.config.n_layer)]
        else:
            blocks = [block for _ in range(self.config.n_layer)]
        return {"wte": ns(MODEL_AXIS, None), "wpe": repl, "ln_f": dict(ln),
                "blocks": blocks}

    def _is_moe_block(self, i: int) -> bool:
        return (self._moe is not None
                and i % self.config.moe_every == self.config.moe_every - 1)

    # ------------------------------------------------------------- init
    def init(self, rng) -> Dict:
        c = self.config
        keys = jax.random.split(rng, 4 + c.n_layer)
        params = {
            "wte": _dense_init(keys[0], (c.vocab_size, c.n_embd), c.initializer_range),
            "wpe": _dense_init(keys[1], (c.n_positions, c.n_embd), c.initializer_range),
            "ln_f": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                     "bias": jnp.zeros((c.n_embd,), jnp.float32)},
            "blocks": [],
        }
        # residual-scaled init for output projections (GPT-2 paper)
        proj_scale = c.initializer_range / math.sqrt(2 * c.n_layer)
        for i in range(c.n_layer):
            k = jax.random.split(keys[4 + i], 4)
            block = {
                "ln_1": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                         "bias": jnp.zeros((c.n_embd,), jnp.float32)},
                "attn": {
                    "c_attn_w": _dense_init(k[0], (c.n_embd, 3 * c.n_embd), c.initializer_range),
                    "c_attn_b": jnp.zeros((3 * c.n_embd,), jnp.float32),
                    "c_proj_w": _dense_init(k[1], (c.n_embd, c.n_embd), proj_scale),
                    "c_proj_b": jnp.zeros((c.n_embd,), jnp.float32),
                },
                "ln_2": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                         "bias": jnp.zeros((c.n_embd,), jnp.float32)},
            }
            if self._is_moe_block(i):
                block["moe"] = self._moe.init(k[2])
            else:
                block["mlp"] = {
                    "c_fc_w": _dense_init(k[2], (c.n_embd, 4 * c.n_embd), c.initializer_range),
                    "c_fc_b": jnp.zeros((4 * c.n_embd,), jnp.float32),
                    "c_proj_w": _dense_init(k[3], (4 * c.n_embd, c.n_embd), proj_scale),
                    "c_proj_b": jnp.zeros((c.n_embd,), jnp.float32),
                }
            params["blocks"].append(block)
        return params

    # ------------------------------------------------------------- layers
    def _layer_norm(self, x, p, eps):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + eps)
        return (out * p["scale"] + p["bias"]).astype(x.dtype)

    def _dropout(self, x, rng):
        """Stateless inverted dropout (rate = config.dropout). The PRNG key is threaded
        explicitly, so recompute-under-remat reproduces identical masks — the TPU analog
        of the reference's CUDA RNG state tracker (checkpointing.py:147-262)."""
        keep = 1.0 - self.config.dropout
        if self.seq_axis is not None:
            # sequence-parallel: each rank sees only its LOCAL chunk shape, so an
            # unfolded (replicated) key would repeat the same mask on every chunk —
            # fold the rank in to decorrelate
            rng = jax.random.fold_in(rng, jax.lax.axis_index(self.seq_axis))
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / jnp.asarray(keep, x.dtype), jnp.zeros((), x.dtype))

    def _attention(self, x, p, dropout_rng=None):
        from jax.ad_checkpoint import checkpoint_name
        c = self.config
        B, T, E = x.shape
        nh = c.n_head // self.tp_size  # local heads under manual TP (all heads otherwise)
        # announce the fused-qkv dot to the flash remat policies: tagging the dot
        # input turns the policy's width-signature guess into an exact match
        x = checkpoint_name(x, "ds_dot:qkv")
        qkv = jnp.dot(x, p["c_attn_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) + p["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, nh, c.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, nh, c.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, nh, c.head_dim).transpose(0, 2, 1, 3)

        # in-kernel attention dropout: the seed is a traced operand so remat replays
        # identical masks. Under sequence parallelism every rank derives the SAME
        # seed from the replicated rng — the ring hashes GLOBAL coordinates, so the
        # sampled mask is exactly the single-chip kernel's for that seed.
        rate, seed = 0.0, None
        if dropout_rng is not None and c.dropout > 0:
            seed = jax.random.randint(dropout_rng, (), 0,
                                      jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
            rate = float(c.dropout)
        if self.seq_axis is not None:
            # sequence-parallel ring: T here is the LOCAL chunk; global causality
            # is handled by the schedule's layout + in-kernel global-coordinate
            # masks (zigzag) or chunk ordering + the diagonal mask (masked)
            from ..parallel.ring_attention import ring_attention
            y = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True,
                               dropout_rate=rate, dropout_seed=seed,
                               schedule=self.seq_schedule)
        elif c.sparse_attention is not None:
            from ..ops.pallas.block_sparse_attention import block_sparse_attention
            sc = c.sparse_attention
            if T not in self._sparse_layouts:
                layout = sc.make_layout(T)
                assert layout.shape[0] == nh, \
                    (f"sparse_attention config built for {layout.shape[0]} heads; "
                     f"model runs {nh} — construct it with num_heads={c.n_head}")
                self._sparse_layouts[T] = layout
            y = block_sparse_attention(q, k, v, self._sparse_layouts[T], sc.block,
                                       causal=True)
        elif c.use_flash_attention:
            from ..ops.pallas.flash_attention import flash_attention
            if seed is not None and self.tp_axis is not None:
                # the kernel hashes the LOCAL head index; decorrelate the
                # model-parallel ranks (which see the same program_ids) by
                # folding the tp rank into the seed (int32 wraparound is fine)
                seed = seed + (jax.lax.axis_index(self.tp_axis) + 1) \
                    * jnp.int32(-1640531527)  # 2654435761 as int32
            y = flash_attention(q, k, v, True, dropout_rate=rate, dropout_seed=seed)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32) / math.sqrt(c.head_dim)
            mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
            scores = jnp.where(mask, scores, jnp.float32(-1e9))
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            if dropout_rng is not None and c.dropout > 0:
                # attention-probability dropout; under manual TP fold the rank in —
                # a replicated key would give different GLOBAL heads (same local
                # slot on different ranks) byte-identical masks
                if self.tp_axis is not None:
                    dropout_rng = jax.random.fold_in(
                        dropout_rng, jax.lax.axis_index(self.tp_axis))
                probs = self._dropout(probs, dropout_rng)
            y = jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                           preferred_element_type=jnp.float32).astype(x.dtype)
        # tag for the "attn" remat policy: saving this tensor lets backward skip
        # replaying the attention kernel (the priciest recompute under full remat)
        y = checkpoint_name(y, "attn_out")
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * c.head_dim)
        # announce the square output projection (the 'dots+attn-lean' exclusion)
        y = checkpoint_name(y, "ds_dot:proj")
        y = jnp.dot(y, p["c_proj_w"].astype(x.dtype), preferred_element_type=jnp.float32)
        if self.tp_axis is not None:
            # row-parallel projection: partial sums over the model axis (Megatron fwd)
            y = jax.lax.psum(y, self.tp_axis)
        return y.astype(x.dtype) + p["c_proj_b"].astype(x.dtype)

    def _mlp(self, x, p):
        h = jnp.dot(x, p["c_fc_w"].astype(x.dtype),
                    preferred_element_type=jnp.float32).astype(x.dtype) + p["c_fc_b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=True)
        out = jnp.dot(h, p["c_proj_w"].astype(x.dtype), preferred_element_type=jnp.float32)
        if self.tp_axis is not None:
            out = jax.lax.psum(out, self.tp_axis)
        return out.astype(x.dtype) + p["c_proj_b"].astype(x.dtype)

    def _block(self, x, bp, rng=None):
        c = self.config
        k_attn = k_res1 = k_res2 = None
        if rng is not None and c.dropout > 0:
            k_attn, k_res1, k_res2 = jax.random.split(rng, 3)
        if (c.fused_block and self.tp_axis is None and self.seq_axis is None
                and k_attn is None):
            # whole attention half (LN + qkv + attention + proj + residual) in
            # one Pallas kernel; the parallel model copies fall through to the
            # unfused path (the kernel needs the full row on one chip)
            from ..ops.pallas.fused_block import fused_transformer_block
            ap = bp["attn"]
            x = fused_transformer_block(
                x, bp["ln_1"]["scale"], bp["ln_1"]["bias"],
                ap["c_attn_w"], ap["c_attn_b"], ap["c_proj_w"], ap["c_proj_b"],
                c.n_head, causal=True, eps=c.layer_norm_epsilon)
        else:
            a = self._attention(
                self._layer_norm(x, bp["ln_1"], c.layer_norm_epsilon),
                bp["attn"], dropout_rng=k_attn)
            if k_res1 is not None:
                a = self._dropout(a, k_res1)
            x = x + a
        h = self._layer_norm(x, bp["ln_2"], c.layer_norm_epsilon)
        if "moe" in bp:
            m, aux = self._moe.apply(bp["moe"], h)
        else:
            m, aux = self._mlp(h, bp["mlp"]), jnp.zeros((), jnp.float32)
        if k_res2 is not None:
            m = self._dropout(m, k_res2)
        return x + m, aux

    # ------------------------------------------------------------- apply
    def _backbone(self, params, tokens, rng=None):
        """Embeddings → transformer blocks → final layernorm: (B, T, H) hidden states.
        ``rng`` enables stateless dropout (config.dropout) — omit it for eval."""
        c = self.config
        B, T = tokens.shape
        pos = jnp.arange(T)
        if self.seq_axis is not None:
            rank = jax.lax.axis_index(self.seq_axis)
            if self.seq_schedule == "zigzag":
                # zigzag layout: this rank holds global chunks (rank, 2n-1-rank)
                # of size T/2 — positions follow the interleave
                from ..parallel.mesh import axis_size
                n = axis_size(self.seq_axis)
                assert T % 2 == 0, f"zigzag needs an even local seq, got {T}"
                C = T // 2
                pos = jnp.concatenate([rank * C + jnp.arange(C),
                                       (2 * n - 1 - rank) * C + jnp.arange(C)])
            else:
                # contiguous: this rank holds global positions [r*T, (r+1)*T)
                pos = pos + rank * T
        x = params["wte"][tokens].astype(c.compute_dtype) + params["wpe"][pos].astype(c.compute_dtype)
        use_dropout = rng is not None and c.dropout > 0
        if use_dropout:
            rng, k_embd = jax.random.split(rng)
            x = self._dropout(x, k_embd)

        block_fn = self._block
        if c.remat:
            # config-aware remat: honors partition_activations / cpu_checkpointing
            from ..runtime.activation_checkpointing.checkpointing import checkpoint_wrapper
            block_fn = checkpoint_wrapper(block_fn, policy=c.remat_policy)
        aux_total = jnp.zeros((), jnp.float32)
        for bp in params["blocks"]:
            if use_dropout:
                rng, kb = jax.random.split(rng)
                x, aux = block_fn(x, bp, kb)
            else:
                x, aux = block_fn(x, bp)
            aux_total = aux_total + aux
        return self._layer_norm(x, params["ln_f"], c.layer_norm_epsilon), aux_total

    def logits(self, params, tokens, rng=None):
        x, _ = self._backbone(params, tokens, rng=rng)
        # tied LM head: logits = x @ wte.T, contracted without materializing the
        # transposed table (153 MB HBM at 1.5B — see _chunked_ce)
        return jnp.einsum("bth,vh->btv", x, params["wte"].astype(x.dtype),
                          preferred_element_type=jnp.float32)

    def _chunked_ce(self, x, wte, labels, chunk):
        """Fused LM-head + softmax cross-entropy, scanned over sequence chunks so the
        (B, T, vocab) fp32 logits tensor never materializes — at GPT-2 vocab (50k) full
        logits for a 16×1024 batch are 3.3 GB and dominate HBM. The rematted scan body
        recomputes each chunk's logits in backward from the (tiny) hidden states."""
        B, T, H = x.shape
        n = T // chunk
        xs = x.reshape(B, n, chunk, H).swapaxes(0, 1)     # (n, B, C, H)
        ls = labels.reshape(B, n, chunk).swapaxes(0, 1)   # (n, B, C)
        w = wte.astype(x.dtype)                           # (V, H)

        def body(tot, xc_lc):
            xc, lc = xc_lc
            # contract against the UNtransposed table (dot_general picks the dim):
            # a materialized wte.T costs a 153 MB HBM temp at GPT-2 1.5B — measured
            # as an AllocateBuffer in the fused-step OOM breakdown
            logits = jnp.einsum("bch,vh->bcv", xc, w,
                                preferred_element_type=jnp.float32)  # (B, C, V)
            lse = jax.nn.logsumexp(logits, axis=-1)
            valid = (lc >= 0).astype(jnp.float32)  # < 0 = ignored (BERT's -100)
            gold = jnp.take_along_axis(logits, jnp.maximum(lc, 0)[..., None],
                                       axis=-1)[..., 0]
            return (tot[0] + jnp.sum((lse - gold) * valid),
                    tot[1] + jnp.sum(valid)), None

        (total, n_valid), _ = jax.lax.scan(
            jax.checkpoint(body),
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (xs, ls))
        return total / jnp.maximum(n_valid, 1.0)

    def apply_parts(self, params, tokens, labels, rng=None):
        """``(ce_mean, weighted_aux)`` — the two training-loss components kept
        separate. ``apply`` returns their sum; the sequence-parallel wrapper
        needs them apart (CE is psum-weighted across ranks by valid-label
        count, while the MoE load-balancing aux — already a per-chunk mean —
        is pmean'd unweighted so masked labels don't reweight it)."""
        c = self.config
        x, aux = self._backbone(params, tokens, rng=rng)
        aux = (c.moe_aux_weight * aux if self._moe is not None
               else jnp.zeros((), jnp.float32))
        T = x.shape[1]
        if c.loss_chunk:
            # largest divisor of T not exceeding loss_chunk (static shapes for XLA)
            chunk = next(cc for cc in range(min(c.loss_chunk, T), 0, -1) if T % cc == 0)
            if chunk < T:
                return self._chunked_ce(x, params["wte"], labels, chunk), aux
        logits = jnp.einsum("bth,vh->btv", x, params["wte"].astype(x.dtype),
                            preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = (labels >= 0).astype(jnp.float32)  # < 0 = ignored (BERT's -100)
        ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
        return -jnp.sum(ll * valid) / jnp.maximum(jnp.sum(valid), 1.0), aux

    def apply(self, params, tokens, labels=None, rng=None):
        """With labels: mean token cross-entropy loss (the training objective);
        negative labels (the -100 convention) are ignored — mask padding or the
        roll-wrapped last position with them. Without labels: fp32 logits.
        ``rng`` enables stateless dropout when config.dropout > 0."""
        if labels is None:
            return self.logits(params, tokens, rng=rng)
        ce, aux = self.apply_parts(params, tokens, labels, rng=rng)
        return ce + aux

    # ------------------------------------------------------------- generation
    def _cached_jit(self, key, fn, donate_argnums=()):
        """Per-model decode-program cache: generate and beam_search share it (the
        shape-keyed ``("prefill", ...)`` entries are deliberately common so any
        decode variant reuses the expensive prompt program).

        ``donate_argnums`` is forwarded to ``jax.jit``: the decode-path programs
        donate their KV-cache arguments so XLA aliases one buffer through
        input -> scan carry -> output instead of double-buffering the caches.
        Without the donation the caller's cache stays live across the call —
        at 1.5B batch-8 decode that is an extra 2x [L, B, nh, max_len, hd]
        (~5.7 GB) held through the prompt-forward activation peak, which is
        what pushed the relay-kill repros (tests/perf/decode_crash_repro.py)
        over the HBM cliff at execution time.

        The serving stack applies the same discipline to its paged pools:
        serve/paged.py donates the target KV pool through decode/prefill/
        verify, and the speculative DRAFT model's pool rides the identical
        builds at the draft's shapes (serve/speculative.py) — a second
        un-donated pool copy per drafting turn would price the draft model
        right back out of its speedup. The lint registry's
        ``serving_speculative`` entry pins all of it (check_unusable +
        min_undonated_bytes on every spec program)."""
        cache = getattr(self, "_gen_jit_cache", None)
        if cache is None:
            cache = self._gen_jit_cache = {}
        if key not in cache:
            cache[key] = jax.jit(fn, donate_argnums=donate_argnums)
        return cache[key]

    def _build_cached_forward(self, max_len: int):
        """Incremental forward over per-layer KV caches, shared by ``generate``
        and ``beam_search``: ``forward(p, toks [B, Tn], pos, kcs, vcs) ->
        (last-position logits [B, vocab] fp32, new_kcs, new_vcs)`` where
        kcs/vcs are ``[n_layer, B, nh, max_len, hd]`` and ``pos`` counts the
        tokens already cached."""
        c = self.config
        nh, hd = c.n_head, c.head_dim
        if c.sparse_attention is not None and not getattr(
                self, "_warned_sparse_decode", False):
            self._warned_sparse_decode = True
            from ..utils.logging import logger
            logger.warning(
                "[deepspeed_tpu] decode runs DENSE causal attention over the KV "
                "cache — the sparse_attention layout applies to training "
                "forwards only, so generated text reflects full attention")

        def attn_cached(x, bp, kcs, vcs, li, pos):
            B_, Tn, _ = x.shape
            qkv = jnp.dot(x, bp["c_attn_w"].astype(x.dtype),
                          preferred_element_type=jnp.float32).astype(x.dtype) \
                + bp["c_attn_b"].astype(x.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
            k = k.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
            v = v.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
            # write THROUGH the stacked [L, B, nh, max_len, hd] carry arrays:
            # per-layer slice-out + end-of-step jnp.stack kept L transient copies
            # of the whole cache live (measured: 1.5B batch-8 decode demanded
            # 37.1 G HBM and OOM'd); in-place dynamic_update_slice on the carry
            # lets XLA alias one buffer through the layer loop
            kcs = jax.lax.dynamic_update_slice(
                kcs, k.astype(kcs.dtype)[None], (li, 0, 0, pos, 0))
            vcs = jax.lax.dynamic_update_slice(
                vcs, v.astype(vcs.dtype)[None], (li, 0, 0, pos, 0))
            s = jnp.einsum("bhqd,bhkd->bhqk", q, kcs[li],
                           preferred_element_type=jnp.float32) / math.sqrt(hd)
            j = jnp.arange(max_len)[None, :]
            i = pos + jnp.arange(Tn)[:, None]
            s = jnp.where(j <= i, s, jnp.float32(-1e9))  # causal + not-yet-written mask
            p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
            y = jnp.einsum("bhqk,bhkd->bhqd", p, vcs[li],
                           preferred_element_type=jnp.float32).astype(x.dtype)
            y = y.transpose(0, 2, 1, 3).reshape(B_, Tn, nh * hd)
            return (jnp.dot(y, bp["c_proj_w"].astype(x.dtype),
                            preferred_element_type=jnp.float32).astype(x.dtype)
                    + bp["c_proj_b"].astype(x.dtype)), kcs, vcs

        def forward(p, toks, pos, kcs, vcs):
            Tn = toks.shape[1]
            positions = pos + jnp.arange(Tn)
            x = p["wte"][toks].astype(c.compute_dtype) \
                + p["wpe"][positions].astype(c.compute_dtype)
            for li, bp in enumerate(p["blocks"]):
                a, kcs, vcs = attn_cached(
                    self._layer_norm(x, bp["ln_1"], c.layer_norm_epsilon),
                    bp["attn"], kcs, vcs, li, pos)
                x = x + a
                h = self._layer_norm(x, bp["ln_2"], c.layer_norm_epsilon)
                m = (self._moe.apply(bp["moe"], h)[0] if "moe" in bp
                     else self._mlp(h, bp["mlp"]))
                x = x + m
            x = self._layer_norm(x, p["ln_f"], c.layer_norm_epsilon)
            logits = jnp.einsum("bh,vh->bv", x[:, -1], p["wte"].astype(x.dtype),
                                preferred_element_type=jnp.float32)
            return logits, kcs, vcs

        return forward

    def beam_search(self, params, tokens, max_new_tokens: int, num_beams: int = 4,
                    *, eos_token_id=None, length_penalty: float = 1.0):
        """KV-cached beam search: prefill once, expand to ``num_beams`` beams per
        batch row, then a ``lax.scan`` of single-token steps that keeps the K
        highest-scoring hypotheses (summed token log-probs). With
        ``eos_token_id`` a finished beam is frozen (only the EOS continuation at
        zero cost survives) and padded with EOS; scores are length-normalized by
        ``len**length_penalty`` (GNMT convention) for the final ranking.
        Returns ``(sequences [B, T0 + max_new_tokens], scores [B])`` — the best
        beam per row. Same caching/compile discipline as ``generate``."""
        assert self.tp_axis is None and self.seq_axis is None, \
            "beam_search() supports the plain (non-shard_map) model"
        assert max_new_tokens >= 1 and num_beams >= 1
        assert num_beams <= self.config.vocab_size, \
            f"num_beams {num_beams} exceeds vocab_size {self.config.vocab_size}"
        assert eos_token_id is None or 0 <= eos_token_id < self.config.vocab_size, \
            f"eos_token_id {eos_token_id} outside vocab [0, {self.config.vocab_size})"
        c = self.config
        B, T0 = tokens.shape
        K = int(num_beams)
        L = int(max_new_tokens)
        max_len = T0 + L
        assert max_len <= c.n_positions, \
            f"prompt {T0} + {L} new tokens exceeds n_positions {c.n_positions}"
        forward = self._build_cached_forward(max_len)
        V = c.vocab_size
        NEG = jnp.float32(-1e9)
        eos = -1 if eos_token_id is None else int(eos_token_id)

        def step_scores(logits, scores, live):
            """Per-beam next-token scores [B, K, V]: log-probs added to the beam
            score; a finished beam admits only the EOS continuation, at no cost."""
            logp = jax.nn.log_softmax(logits.reshape(B, K, V), axis=-1)
            cand = scores[:, :, None] + logp
            if eos >= 0:
                frozen = jnp.full((B, K, V), NEG).at[:, :, eos].set(scores)
                cand = jnp.where(live[:, :, None], cand, frozen)
            return cand

        def decode(p, first_logits, kcs, vcs):
            # beam init: top-K first tokens per row from the prefill logits.
            # kcs/vcs arrive ALREADY replicated per beam ([nl, B*K, ...]) and
            # donated — the expansion happens eagerly outside this program so
            # the donated input aliases the scan carry and the returned caches
            # (an in-jit repeat would leave the [nl, B, ...] input un-aliasable)
            logp0 = jax.nn.log_softmax(first_logits, axis=-1)      # [B, V]
            scores, tok0 = jax.lax.top_k(logp0, K)                  # [B, K]
            live = (tok0 != eos) if eos >= 0 else jnp.ones((B, K), bool)
            seqs = jnp.full((B, K, L), eos if eos >= 0 else 0, jnp.int32)
            seqs = seqs.at[:, :, 0].set(tok0)

            def step(carry, t):
                seqs, scores, live, kcs, vcs = carry
                # each beam's newest token is seqs[:, :, t] (written last round)
                prev = jax.lax.dynamic_slice_in_dim(seqs, t, 1, axis=2)
                logits, kcs, vcs = forward(p, prev.reshape(B * K, 1),
                                           T0 + t, kcs, vcs)
                cand = step_scores(logits, scores, live)            # [B, K, V]
                flat = cand.reshape(B, K * V)
                scores, idx = jax.lax.top_k(flat, K)                # [B, K]
                parent = idx // V                                   # [B, K]
                tok = (idx % V).astype(jnp.int32)
                # reorder: sequences + caches follow their parent beam
                seqs = jnp.take_along_axis(seqs, parent[:, :, None], axis=1)
                seqs = jax.lax.dynamic_update_slice_in_dim(
                    seqs, tok[:, :, None], t + 1, axis=2)
                flatp = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
                kcs = kcs[:, flatp]
                vcs = vcs[:, flatp]
                live = jnp.take_along_axis(live, parent, axis=1)
                if eos >= 0:
                    live = live & (tok != eos)
                return (seqs, scores, live, kcs, vcs), ()

            (seqs, scores, live, kcs, vcs), _ = jax.lax.scan(
                step, (seqs, scores, live, kcs, vcs), jnp.arange(L - 1))
            # GNMT length normalization: finished beams count tokens up to and
            # including EOS; an unfinished beam counts exactly L (clamped — the
            # +1 for EOS must not credit beams that never emitted one)
            if eos >= 0:
                lengths = jnp.minimum(jnp.sum(jnp.cumprod(
                    (seqs != eos).astype(jnp.float32), axis=2), axis=2) + 1.0,
                    float(L))
            else:
                lengths = jnp.full((B, K), float(L))
            final = scores / jnp.power(lengths, jnp.float32(length_penalty))
            best = jnp.argmax(final, axis=1)                        # [B]
            # returning the caches lets XLA alias donated input -> carry -> output
            return (jnp.take_along_axis(seqs, best[:, None, None], axis=1)[:, 0],
                    jnp.take_along_axis(final, best[:, None], axis=1)[:, 0],
                    kcs, vcs)

        # the prefill program depends only on shapes — key it separately so
        # varying num_beams/eos/length_penalty reuses the expensive prompt jit
        jit_forward = self._cached_jit(("prefill", B, T0, max_len), forward,
                                       donate_argnums=(3, 4))
        jit_decode = self._cached_jit(
            ("beam", B, T0, L, K, eos, float(length_penalty)), decode,
            donate_argnums=(2, 3))

        cache_shape = (c.n_layer, B, c.n_head, max_len, c.head_dim)
        kcs = jnp.zeros(cache_shape, c.compute_dtype)
        vcs = jnp.zeros(cache_shape, c.compute_dtype)
        first_logits, kcs, vcs = jit_forward(params, tokens, 0, kcs, vcs)
        # per-beam cache expansion [nl, B, ...] -> [nl, B*K, ...] happens here,
        # outside the jit, so the decode program's donated inputs already have
        # the carry/output shape and XLA keeps ONE cache buffer end to end
        kcs, vcs = (jnp.repeat(t, K, axis=1) for t in (kcs, vcs))
        gen, scores, _, _ = jit_decode(params, first_logits, kcs, vcs)
        return jnp.concatenate([tokens, gen.astype(tokens.dtype)], axis=1), scores

    def generate(self, params, tokens, max_new_tokens: int,
                 temperature: float = 0.0, rng=None, *, top_k: int = 0,
                 top_p: float = 1.0):
        """Autoregressive decode with per-layer KV caches: one jitted prefill over
        the prompt, then a ``lax.scan`` of single-token steps that append to
        static-length caches (no recompilation per step, no O(T²) re-forward).
        ``temperature == 0`` is greedy; otherwise categorical sampling with ``rng``,
        optionally truncated to the ``top_k`` highest-probability tokens and/or the
        nucleus of smallest-count tokens whose cumulative probability reaches
        ``top_p`` (both filters compose; at least the argmax token always survives).
        Eval semantics (no dropout). Dense configs decode EXACTLY as the full
        re-forward would; MoE configs route each decode step's B tokens with a
        per-step capacity, so outputs match the full forward only while capacity
        does not bind (raise moe_capacity_factor for decode if exactness matters).
        Not for manual-TP / sequence-parallel model copies. The jitted prefill and
        decode programs are cached on the model per (shape, temperature, top_k,
        top_p) signature."""
        assert self.tp_axis is None and self.seq_axis is None, \
            "generate() supports the plain (non-shard_map) model"
        assert max_new_tokens >= 1, f"max_new_tokens must be >= 1 (got {max_new_tokens})"
        c = self.config
        B, T0 = tokens.shape
        max_len = T0 + int(max_new_tokens)
        assert max_len <= c.n_positions, \
            f"prompt {T0} + {max_new_tokens} new tokens exceeds n_positions {c.n_positions}"
        nh, hd = c.n_head, c.head_dim
        if temperature > 0:
            assert rng is not None, "temperature > 0 requires an rng key"
        assert top_k >= 0, f"top_k must be >= 0 (got {top_k})"
        assert 0.0 < top_p <= 1.0, f"top_p must be in (0, 1] (got {top_p})"
        forward = self._build_cached_forward(max_len)
        out_dtype = tokens.dtype

        def sample(logits, key):
            if temperature == 0:
                return jnp.argmax(logits, axis=-1).astype(out_dtype)
            logits = logits / jnp.float32(temperature)
            if top_k > 0 and top_k < c.vocab_size:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, jnp.float32(-jnp.inf), logits)
            if top_p < 1.0:
                order = jnp.argsort(logits, axis=-1)[..., ::-1]
                sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                # exclusive cumulative mass BEFORE each token: a token stays while
                # the mass ahead of it is under top_p, so the kept set is the
                # smallest prefix reaching top_p (the argmax always stays). The
                # keep mask is scattered back by SORT POSITION, not logit value,
                # so tokens tying the cutoff logit don't expand the nucleus.
                mass_before = jnp.cumsum(probs, axis=-1) - probs
                kept_sorted = mass_before < top_p
                inv = jnp.argsort(order, axis=-1)
                kept = jnp.take_along_axis(kept_sorted, inv, axis=-1)
                logits = jnp.where(kept, logits, jnp.float32(-jnp.inf))
            return jax.random.categorical(key, logits, axis=-1).astype(out_dtype)

        def decode(p, first, kcs, vcs, keys):
            def step(carry, key):
                tok, pos, kcs, vcs = carry
                logits, kcs, vcs = forward(p, tok[:, None], pos, kcs, vcs)
                nxt = sample(logits, key)
                return (nxt, pos + 1, kcs, vcs), tok

            (last, _, kcs, vcs), outs = jax.lax.scan(
                step, (first, jnp.asarray(T0, jnp.int32), kcs, vcs), keys)
            # outs collects each step's INPUT token; the final sample is `last`.
            # The caches ride out so the donated inputs alias carry and output
            return jnp.concatenate([outs.T, last[:, None]], axis=1), kcs, vcs

        # one compile per signature, reused across calls — params are explicit
        # jit arguments, not closure captures. The prefill depends only on
        # shapes (same key beam_search uses), so sampling-parameter variants
        # share the expensive prompt program.
        jit_forward = self._cached_jit(("prefill", B, T0, max_len), forward,
                                       donate_argnums=(3, 4))
        jit_decode = self._cached_jit(
            (B, T0, int(max_new_tokens), float(temperature), int(top_k),
             float(top_p), str(out_dtype)), decode, donate_argnums=(2, 3))

        cache_shape = (c.n_layer, B, nh, max_len, hd)
        kcs = jnp.zeros(cache_shape, c.compute_dtype)
        vcs = jnp.zeros(cache_shape, c.compute_dtype)
        logits, kcs, vcs = jit_forward(params, tokens, 0, kcs, vcs)
        keys = jax.random.split(rng if rng is not None else jax.random.PRNGKey(0),
                                max_new_tokens)
        first = sample(logits, keys[0])
        if max_new_tokens == 1:
            return jnp.concatenate([tokens, first[:, None]], axis=1)
        gen, _, _ = jit_decode(params, first, kcs, vcs, keys[1:])
        return jnp.concatenate([tokens, gen], axis=1)

    def decode_lint_programs(self, params, *, batch=2, prompt_len=4,
                             max_new_tokens=4, num_beams=2):
        """``(name, jitted, example_args, manifest)`` for the decode-path
        programs, in the shape ``ds-tpu lint`` consumes (lint/registry.py).

        Runs a tiny ``generate`` (greedy) and ``beam_search`` to populate the
        per-model program cache, then hands the cached jitted functions back
        with FRESH example arguments — the lint capture only lowers/compiles,
        nothing executes, but the arrays the tiny runs donated are dead. The
        manifests pin the invariant the relay-kill crashes violated: every
        declared cache donation must actually alias (check_unusable), no
        cache-sized input may ride un-donated (min_undonated_bytes), and the
        single-host decode programs carry zero large collectives."""
        import numpy as np

        c = self.config
        B, T0, L, K = int(batch), int(prompt_len), int(max_new_tokens), int(num_beams)
        max_len = T0 + L
        tokens = jnp.asarray(np.arange(B * T0).reshape(B, T0) % c.vocab_size,
                             jnp.int32)
        self.generate(params, tokens, L)
        self.beam_search(params, tokens, L, num_beams=K)

        dt = jnp.dtype(c.compute_dtype).name
        compute = {"bfloat16": "bf16", "float16": "f16"}.get(dt, "f32")
        manifest = {"compute_dtype": compute,
                    "donation": {"check_unusable": True,
                                 "min_undonated_bytes": 1024},
                    "strict": True, "any_reduction": {"max": 0}}

        cache_shape = (c.n_layer, B, c.n_head, max_len, c.head_dim)

        def caches(beams=1):
            s = (cache_shape[0], B * beams) + cache_shape[2:]
            return jnp.zeros(s, c.compute_dtype), jnp.zeros(s, c.compute_dtype)

        cache = self._gen_jit_cache
        kcs, vcs = caches()
        keys = jax.random.split(jax.random.PRNGKey(0), L)
        first = jnp.zeros((B,), jnp.int32)
        first_logits = jnp.zeros((B, c.vocab_size), jnp.float32)
        bk, bv = caches(beams=K)
        return [
            ("gpt2_prefill", cache[("prefill", B, T0, max_len)],
             (params, tokens, 0) + caches(), manifest),
            ("gpt2_decode_greedy",
             cache[(B, T0, L, 0.0, 0, 1.0, str(tokens.dtype))],
             (params, first, kcs, vcs, keys[1:]), manifest),
            ("gpt2_decode_beam", cache[("beam", B, T0, L, K, -1, 1.0)],
             (params, first_logits, bk, bv), manifest),
        ]

    def param_count(self, params) -> int:
        from ..runtime.utils import param_count
        return param_count(params)
