"""GPT-2 family model, TPU-first.

Flagship decoder LM for the framework benchmarks (BASELINE.json: GPT-2 1.5B ZeRO-2). The
reference trains GPT-2 through external Megatron-LM (tests/model/Megatron_GPT2); here the
model is in-tree, a pure-function pytree model:

- bf16-friendly: all matmuls carry ``preferred_element_type=float32`` accumulation;
- static shapes, layer loop unrolled (or remat-scanned) for XLA;
- attention dispatches to the Pallas flash-attention kernel on TPU when enabled, with a
  dense fallback (ops/pallas/flash_attention.py);
- weights laid out [in, out] so the ``model``-axis TP sharding (attention heads / MLP
  columns) is a pure PartitionSpec choice.
"""

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0          # dropout is applied via stateless PRNG when > 0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = False
    remat: bool = False            # activation checkpointing over blocks
    remat_policy: Any = None       # None=full recompute; "dots"=save matmul outputs
    loss_chunk: int = 128          # seq-chunked fused CE (0 = materialize full logits)
    compute_dtype: Any = jnp.bfloat16

    # named sizes for convenience
    @property
    def head_dim(self):
        return self.n_embd // self.n_head


def _dense_init(rng, shape, scale):
    return jax.random.normal(rng, shape, jnp.float32) * scale


class GPT2Model:
    """Pure-function GPT-2: ``init(rng) -> params``, ``apply(params, tokens[, labels])``."""

    def __init__(self, config: GPT2Config):
        self.config = config

    # ------------------------------------------------------------- init
    def init(self, rng) -> Dict:
        c = self.config
        keys = jax.random.split(rng, 4 + c.n_layer)
        params = {
            "wte": _dense_init(keys[0], (c.vocab_size, c.n_embd), c.initializer_range),
            "wpe": _dense_init(keys[1], (c.n_positions, c.n_embd), c.initializer_range),
            "ln_f": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                     "bias": jnp.zeros((c.n_embd,), jnp.float32)},
            "blocks": [],
        }
        # residual-scaled init for output projections (GPT-2 paper)
        proj_scale = c.initializer_range / math.sqrt(2 * c.n_layer)
        for i in range(c.n_layer):
            k = jax.random.split(keys[4 + i], 4)
            block = {
                "ln_1": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                         "bias": jnp.zeros((c.n_embd,), jnp.float32)},
                "attn": {
                    "c_attn_w": _dense_init(k[0], (c.n_embd, 3 * c.n_embd), c.initializer_range),
                    "c_attn_b": jnp.zeros((3 * c.n_embd,), jnp.float32),
                    "c_proj_w": _dense_init(k[1], (c.n_embd, c.n_embd), proj_scale),
                    "c_proj_b": jnp.zeros((c.n_embd,), jnp.float32),
                },
                "ln_2": {"scale": jnp.ones((c.n_embd,), jnp.float32),
                         "bias": jnp.zeros((c.n_embd,), jnp.float32)},
                "mlp": {
                    "c_fc_w": _dense_init(k[2], (c.n_embd, 4 * c.n_embd), c.initializer_range),
                    "c_fc_b": jnp.zeros((4 * c.n_embd,), jnp.float32),
                    "c_proj_w": _dense_init(k[3], (4 * c.n_embd, c.n_embd), proj_scale),
                    "c_proj_b": jnp.zeros((c.n_embd,), jnp.float32),
                },
            }
            params["blocks"].append(block)
        return params

    # ------------------------------------------------------------- layers
    def _layer_norm(self, x, p, eps):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + eps)
        return (out * p["scale"] + p["bias"]).astype(x.dtype)

    def _attention(self, x, p, dropout_rng=None):
        c = self.config
        B, T, E = x.shape
        qkv = jnp.dot(x, p["c_attn_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) + p["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, c.n_head, c.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, c.n_head, c.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, c.n_head, c.head_dim).transpose(0, 2, 1, 3)

        if c.use_flash_attention:
            from ..ops.pallas.flash_attention import flash_attention
            y = flash_attention(q, k, v, True)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32) / math.sqrt(c.head_dim)
            mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
            scores = jnp.where(mask, scores, jnp.float32(-1e9))
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            y = jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                           preferred_element_type=jnp.float32).astype(x.dtype)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, E)
        y = jnp.dot(y, p["c_proj_w"].astype(x.dtype),
                    preferred_element_type=jnp.float32).astype(x.dtype) + p["c_proj_b"].astype(x.dtype)
        return y

    def _mlp(self, x, p):
        h = jnp.dot(x, p["c_fc_w"].astype(x.dtype),
                    preferred_element_type=jnp.float32).astype(x.dtype) + p["c_fc_b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=True)
        out = jnp.dot(h, p["c_proj_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) + p["c_proj_b"].astype(x.dtype)
        return out

    def _block(self, x, bp):
        c = self.config
        x = x + self._attention(self._layer_norm(x, bp["ln_1"], c.layer_norm_epsilon), bp["attn"])
        x = x + self._mlp(self._layer_norm(x, bp["ln_2"], c.layer_norm_epsilon), bp["mlp"])
        return x

    # ------------------------------------------------------------- apply
    def _backbone(self, params, tokens):
        """Embeddings → transformer blocks → final layernorm: (B, T, H) hidden states."""
        c = self.config
        B, T = tokens.shape
        pos = jnp.arange(T)
        x = params["wte"][tokens].astype(c.compute_dtype) + params["wpe"][pos].astype(c.compute_dtype)

        block_fn = self._block
        if c.remat:
            # config-aware remat: honors partition_activations / cpu_checkpointing
            from ..runtime.activation_checkpointing.checkpointing import checkpoint_wrapper
            block_fn = checkpoint_wrapper(block_fn, policy=c.remat_policy)
        for bp in params["blocks"]:
            x = block_fn(x, bp)
        return self._layer_norm(x, params["ln_f"], c.layer_norm_epsilon)

    def logits(self, params, tokens):
        x = self._backbone(params, tokens)
        # tied LM head: logits = x @ wte.T
        return jnp.dot(x, params["wte"].T.astype(x.dtype), preferred_element_type=jnp.float32)

    def _chunked_ce(self, x, wte, labels, chunk):
        """Fused LM-head + softmax cross-entropy, scanned over sequence chunks so the
        (B, T, vocab) fp32 logits tensor never materializes — at GPT-2 vocab (50k) full
        logits for a 16×1024 batch are 3.3 GB and dominate HBM. The rematted scan body
        recomputes each chunk's logits in backward from the (tiny) hidden states."""
        B, T, H = x.shape
        n = T // chunk
        xs = x.reshape(B, n, chunk, H).swapaxes(0, 1)     # (n, B, C, H)
        ls = labels.reshape(B, n, chunk).swapaxes(0, 1)   # (n, B, C)
        w = wte.T.astype(x.dtype)                         # (H, V)

        def body(tot, xc_lc):
            xc, lc = xc_lc
            logits = jnp.dot(xc, w, preferred_element_type=jnp.float32)  # (B, C, V)
            lse = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
            return tot + jnp.sum(lse - gold), None

        total, _ = jax.lax.scan(jax.checkpoint(body), jnp.zeros((), jnp.float32), (xs, ls))
        return total / (B * T)

    def apply(self, params, tokens, labels=None):
        """With labels: mean token cross-entropy loss (the training objective).
        Without: fp32 logits."""
        if labels is None:
            return self.logits(params, tokens)
        c = self.config
        x = self._backbone(params, tokens)
        T = x.shape[1]
        if c.loss_chunk:
            # largest divisor of T not exceeding loss_chunk (static shapes for XLA)
            chunk = next(cc for cc in range(min(c.loss_chunk, T), 0, -1) if T % cc == 0)
            if chunk < T:
                return self._chunked_ce(x, params["wte"], labels, chunk)
        logits = jnp.dot(x, params["wte"].T.astype(x.dtype), preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def param_count(self, params) -> int:
        return sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
