"""Small ResNet for CIFAR-class vision workloads, TPU-first.

The reference's canonical beginner workload is DeepSpeedExamples/cifar (a small CNN
driven through ``deepspeed.initialize`` — BASELINE.json lists it as a target config).
This is the in-tree equivalent: a pure-function CIFAR ResNet (conv stem → N residual
stages → global-pool → linear) built on ``lax.conv_general_dilated`` with NHWC layout
(TPU-native) and GroupNorm (batch-statistics-free, so train/eval and per-shard
data-parallel behavior match without cross-device BN syncs).

``apply(params, images, labels)`` -> mean cross-entropy; ``logits(params, images)``.
"""

import math
from dataclasses import dataclass
from typing import Any, Tuple

import jax
import jax.numpy as jnp


@dataclass
class ResNetConfig:
    num_classes: int = 10
    width: int = 32                      # stem channels
    stage_sizes: Tuple[int, ...] = (2, 2, 2)   # residual blocks per stage (ResNet-14ish)
    groups: int = 8                      # GroupNorm groups
    compute_dtype: Any = jnp.float32


def _conv_init(rng, shape):
    # He/Kaiming fan-in init for [kh, kw, cin, cout]
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(rng, shape, jnp.float32) * math.sqrt(2.0 / fan_in)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)


class ResNet:
    """Functional ResNet: ``init(rng) -> params``, ``apply(params, images[, labels])``."""

    def __init__(self, config: ResNetConfig = None):
        self.config = config or ResNetConfig()

    # ------------------------------------------------------------------ init
    def init(self, rng):
        c = self.config
        n_blocks = sum(c.stage_sizes)
        keys = iter(jax.random.split(rng, 3 + 3 * n_blocks))
        params = {"stem": {"w": _conv_init(next(keys), (3, 3, 3, c.width)),
                           "gn": self._gn_init(c.width)},
                  "stages": [], }
        cin = c.width
        for si, blocks in enumerate(c.stage_sizes):
            cout = c.width * (2 ** si)
            stage = []
            for bi in range(blocks):
                block = {
                    "conv1": {"w": _conv_init(next(keys), (3, 3, cin, cout)),
                              "gn": self._gn_init(cout)},
                    "conv2": {"w": _conv_init(next(keys), (3, 3, cout, cout)),
                              "gn": self._gn_init(cout)},
                }
                if cin != cout:
                    block["proj"] = {"w": _conv_init(next(keys), (1, 1, cin, cout))}
                stage.append(block)
                cin = cout
            params["stages"].append(stage)
        params["head"] = {"w": jax.random.normal(next(keys), (cin, c.num_classes),
                                                 jnp.float32) * 0.01,
                          "b": jnp.zeros((c.num_classes,), jnp.float32)}
        return params

    @staticmethod
    def _gn_init(ch):
        return {"scale": jnp.ones((ch,), jnp.float32), "bias": jnp.zeros((ch,), jnp.float32)}

    # ------------------------------------------------------------------ layers
    def _group_norm(self, x, p):
        c = self.config
        B, H, W, C = x.shape
        g = min(c.groups, C)
        xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
        mean = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
        var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
        xf = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
        xf = xf.reshape(B, H, W, C) * p["scale"] + p["bias"]
        return xf.astype(x.dtype)

    def _block(self, x, p, stride):
        y = _conv(x, p["conv1"]["w"], stride)
        y = jax.nn.relu(self._group_norm(y, p["conv1"]["gn"]))
        y = _conv(y, p["conv2"]["w"])
        y = self._group_norm(y, p["conv2"]["gn"])
        # stride=2 only occurs at a stage boundary, where channels also change, so the
        # projection conv always carries the downsample
        shortcut = _conv(x, p["proj"]["w"], stride) if "proj" in p else x
        return jax.nn.relu(y + shortcut)

    # ------------------------------------------------------------------ apply
    def logits(self, params, images):
        c = self.config
        x = images.astype(c.compute_dtype)
        x = jax.nn.relu(self._group_norm(_conv(x, params["stem"]["w"]), params["stem"]["gn"]))
        for si, stage in enumerate(params["stages"]):
            for bi, block in enumerate(stage):
                stride = 2 if (si > 0 and bi == 0) else 1
                x = self._block(x, block, stride)
        x = jnp.mean(x, axis=(1, 2))                      # global average pool
        head = params["head"]
        return jnp.dot(x, head["w"].astype(x.dtype),
                       preferred_element_type=jnp.float32) + head["b"]

    def apply(self, params, images, labels=None):
        logits = self.logits(params, images)
        if labels is None:
            return logits
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0])

    def param_count(self, params) -> int:
        from ..runtime.utils import param_count
        return param_count(params)
