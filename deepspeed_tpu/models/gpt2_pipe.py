"""GPT-2 on the SPMD pipeline: pipe-axis stages × data-axis DP in one jit.

The decoder stack partitions into homogeneous stages (n_layer % n_stages == 0); embedding
runs at stage 0 (first_stage_fn) and ln_f + tied LM head + loss at the last stage
(last_stage_fn). Block weights are stacked [S, L/S, ...] and sharded over ``pipe`` —
each device holds only its stage's blocks (true pipeline memory scaling). This is the
rebuild's Megatron-GPT2-on-pipeline configuration (reference tests/model/Megatron_GPT2 +
runtime/pipe) executed the TPU way.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..parallel.mesh import PIPE_AXIS
from ..parallel.pipeline_spmd import pipeline_apply, stacked_param_sharding
from .gpt2 import GPT2Config, GPT2Model


class GPT2Pipe:
    """Pipelined GPT-2. ``init`` returns {"io": embed/head params, "stages": stacked blocks}."""

    def __init__(self, config: GPT2Config, num_stages: int):
        assert config.n_layer % num_stages == 0, "n_layer must divide evenly into stages"
        self.config = config
        self.num_stages = num_stages
        self.layers_per_stage = config.n_layer // num_stages
        self._dense = GPT2Model(config)

    def init(self, rng) -> Dict[str, Any]:
        flat = self._dense.init(rng)
        blocks = flat.pop("blocks")
        # stack per-layer block params → [L, ...], then fold into [S, L/S, ...]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
        S, LpS = self.num_stages, self.layers_per_stage
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((S, LpS) + a.shape[1:]), stacked)
        return {"io": flat, "stages": stacked}

    def from_dense(self, dense_params) -> Dict[str, Any]:
        flat = dict(dense_params)
        blocks = flat.pop("blocks")
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((self.num_stages, self.layers_per_stage) + a.shape[1:]), stacked)
        return {"io": flat, "stages": stacked}

    def param_shardings(self, mesh, params):
        from jax.sharding import NamedSharding, PartitionSpec as P
        io_sh = jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), params["io"])
        return {"io": io_sh, "stages": stacked_param_sharding(mesh, params["stages"])}

    # ---- stage functions ----
    def _stage_fn(self, stage_params, x):
        c = self.config
        dense = self._dense

        def body(xx, layer_params):
            return jax.checkpoint(dense._block)(xx, layer_params) if c.remat \
                else dense._block(xx, layer_params), None

        # scan over this stage's layers ([L/S, ...] leaves)
        x, _ = jax.lax.scan(lambda xx, lp: body(xx, lp), x, stage_params)
        return x

    def _embed(self, tokens, io_params):
        c = self.config
        T = tokens.shape[-1]
        pos = jnp.arange(T)
        return (io_params["wte"][tokens].astype(c.compute_dtype) +
                io_params["wpe"][pos].astype(c.compute_dtype))

    def _head_loss(self, y, io_params, labels_mb, mb):
        c = self.config
        dense = self._dense
        y = dense._layer_norm(y, io_params["ln_f"], c.layer_norm_epsilon)
        logits = jnp.dot(y, io_params["wte"].T.astype(y.dtype), preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        labels = labels_mb[mb]
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    # ---- training loss over micro-batches ----
    def loss(self, params, tokens_mb, labels_mb, *, mesh):
        """Mean LM loss over [M, B, T] micro-batches through the pipe-axis pipeline."""
        io = params["io"]
        return pipeline_apply(
            self._stage_fn,
            params["stages"],
            tokens_mb,
            mesh=mesh,
            first_stage_fn=lambda toks, io_p: self._embed(toks, io_p),
            first_stage_args=(io,),
            last_stage_fn=lambda y, io_p, labels, mb: self._head_loss(y, io_p, labels, mb),
            last_stage_args=(io, labels_mb),
        )
