"""GPT-2 on the SPMD pipeline: pipe-axis stages × data-axis DP in one jit.

The decoder stack partitions into homogeneous stages (n_layer % n_stages == 0); embedding
runs at stage 0 (first_stage_fn) and ln_f + tied LM head + loss at the last stage
(last_stage_fn). Block weights are stacked [S, L/S, ...] and sharded over ``pipe`` —
each device holds only its stage's blocks (true pipeline memory scaling). This is the
rebuild's Megatron-GPT2-on-pipeline configuration (reference tests/model/Megatron_GPT2 +
runtime/pipe) executed the TPU way.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..parallel.mesh import MODEL_AXIS, PIPE_AXIS
from ..parallel.pipeline_spmd import pipeline_apply
from .gpt2 import GPT2Config, GPT2Model, qkv_tp_permutation

# per-leaf model-axis dims of a block's ORIGINAL (unstacked) weight shapes:
# column-parallel c_attn/c_fc shard the output dim, row-parallel c_proj the input dim
# (Megatron layout; reference delegated this to the external mpu, SURVEY §2.3)
_BLOCK_TP_DIMS = {
    "ln_1": {"scale": (None,), "bias": (None,)},
    "attn": {"c_attn_w": (None, MODEL_AXIS), "c_attn_b": (MODEL_AXIS,),
             "c_proj_w": (MODEL_AXIS, None), "c_proj_b": (None,)},
    "ln_2": {"scale": (None,), "bias": (None,)},
    "mlp": {"c_fc_w": (None, MODEL_AXIS), "c_fc_b": (MODEL_AXIS,),
            "c_proj_w": (MODEL_AXIS, None), "c_proj_b": (None,)},
}


class GPT2Pipe:
    """Pipelined GPT-2. ``init`` returns {"io": embed/head params, "stages": stacked blocks}.

    With ``tp > 1`` the block weights additionally shard over the ``model`` mesh axis
    (3D = pipe × data × model): the fused qkv columns are stored rank-grouped (see
    ``qkv_tp_permutation``) so each model rank's contiguous shard is a valid local
    (q, k, v), and the stage functions run the Megatron manual-collective forward.
    Note: checkpoints written with tp>1 store the permuted qkv layout, and the stacked
    tree's wte carries the stage-multiple vocab padding — both depend on (num_stages,
    tp). To move a checkpoint across topologies or export to the dense ``GPT2Model``,
    round-trip through ``to_dense`` (strips the padding, inverts the qkv permutation)
    and ``from_dense`` on the new topology.
    """

    def __init__(self, config: GPT2Config, num_stages: int, tp: int = 1):
        assert config.n_layer % num_stages == 0, "n_layer must divide evenly into stages"
        assert config.moe_experts == 0, \
            "MoE blocks do not compose with the SPMD pipeline yet (heterogeneous " \
            "block pytrees cannot stack over the pipe axis)"
        # the tied vocab table shards over pipe: pad it to a stage multiple internally
        # (padded logit columns are masked out of the vocab-parallel softmax)
        self.vocab_pad = (config.vocab_size + num_stages - 1) // num_stages * num_stages
        self.config = config
        self.num_stages = num_stages
        self.layers_per_stage = config.n_layer // num_stages
        self.tp = tp
        self._dense = GPT2Model(config) if tp == 1 else GPT2Model(config).with_tp(MODEL_AXIS, tp)

    def _stack(self, flat) -> Dict[str, Any]:
        flat = dict(flat)
        if self.vocab_pad != self.config.vocab_size:
            pad = self.vocab_pad - flat["wte"].shape[0]
            flat["wte"] = jnp.pad(flat["wte"], ((0, pad), (0, 0)))
        blocks = flat.pop("blocks")
        if self.tp > 1:
            perm = qkv_tp_permutation(self.config.n_embd, self.tp)
            # rebuild (never mutate) the caller's nested dicts: from_dense takes a tree
            # the user may keep using with the unpermuted dense model
            blocks = [{**b, "attn": {**b["attn"],
                                     "c_attn_w": b["attn"]["c_attn_w"][:, perm],
                                     "c_attn_b": b["attn"]["c_attn_b"][perm]}}
                      for b in blocks]
        # stack per-layer block params → [L, ...], then fold into [S, L/S, ...]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
        S, LpS = self.num_stages, self.layers_per_stage
        stacked = jax.tree_util.tree_map(
            lambda a: a.reshape((S, LpS) + a.shape[1:]), stacked)
        return {"io": flat, "stages": stacked}

    def init(self, rng) -> Dict[str, Any]:
        return self._stack(self._dense.init(rng))

    def from_dense(self, dense_params) -> Dict[str, Any]:
        return self._stack(dict(dense_params))

    def to_dense(self, params) -> Dict[str, Any]:
        """Invert ``_stack``: stacked pipe params -> the dense ``GPT2Model`` tree.

        Strips the stage-multiple vocab padding from wte and inverts the tp qkv
        permutation, so the result is topology-free — load it into ``GPT2Model``
        directly, or ``from_dense`` it on a different (num_stages, tp)."""
        io = dict(params["io"])
        if self.vocab_pad != self.config.vocab_size:
            io["wte"] = io["wte"][: self.config.vocab_size]
        S, LpS = self.num_stages, self.layers_per_stage
        flat_layers = jax.tree_util.tree_map(
            lambda a: a.reshape((S * LpS,) + a.shape[2:]), params["stages"])
        blocks = [jax.tree_util.tree_map(lambda a: a[l], flat_layers)
                  for l in range(S * LpS)]
        if self.tp > 1:
            perm = qkv_tp_permutation(self.config.n_embd, self.tp)
            inv = jnp.argsort(jnp.asarray(perm))
            blocks = [{**b, "attn": {**b["attn"],
                                     "c_attn_w": b["attn"]["c_attn_w"][:, inv],
                                     "c_attn_b": b["attn"]["c_attn_b"][inv]}}
                      for b in blocks]
        return {**io, "blocks": blocks}

    def _stacked_specs(self, stages):
        """P(pipe, None, *tp_dims) per stacked leaf (tp dims only meaningful for tp>1)."""
        from jax.sharding import PartitionSpec as P

        def leaf_spec(a, dims):
            tp_dims = tuple(d if self.tp > 1 else None for d in dims)
            assert a.ndim == 2 + len(dims), f"stacked leaf rank {a.ndim} vs dims {dims}"
            return P(PIPE_AXIS, None, *tp_dims)

        return jax.tree_util.tree_map(leaf_spec, stages, _BLOCK_TP_DIMS)

    def param_shardings(self, mesh, params):
        from jax.sharding import NamedSharding, PartitionSpec as P
        # the tied vocab table is SHARDED over pipe (vocab-parallel embedding + head):
        # per-rank param bytes ∝ 1/S including the embedding, and the tie costs nothing
        # (reference TiedLayerSpec replicated it on first+last stage and all-reduced
        # tied grads, runtime/pipe/module.py)
        io_sh = jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), params["io"])
        io_sh["wte"] = NamedSharding(mesh, P(PIPE_AXIS, None))
        stage_specs = self._stacked_specs(params["stages"])
        stages_sh = jax.tree_util.tree_map(lambda spec: NamedSharding(mesh, spec), stage_specs,
                                           is_leaf=lambda x: isinstance(x, P))
        return {"io": io_sh, "stages": stages_sh}

    # ---- stage functions ----
    def _stage_fn(self, stage_params, x):
        c = self.config
        dense = self._dense

        def body(xx, layer_params):
            # _block returns (hidden, moe_aux); aux is always 0 here (the pipe
            # model asserts moe_experts == 0) — drop it from the scan carry
            blk = jax.checkpoint(dense._block) if c.remat else dense._block
            out, _aux = blk(xx, layer_params)
            return out, None

        # scan over this stage's layers ([L/S, ...] leaves)
        x, _ = jax.lax.scan(body, x, stage_params)
        return x

    def _vp_embed(self, tokens, io_params):
        """Vocab-parallel embedding over the pipe axis (runs inside shard_map).

        ``io_params['wte']`` is this rank's [V/S, E] vocab slice: look up the ids that
        land in the local range, zero the rest, psum over pipe (Megatron
        VocabParallelEmbedding's structure, applied to the PIPE axis)."""
        c = self.config
        wte = io_params["wte"]
        v_local = wte.shape[0]
        s = jax.lax.axis_index(PIPE_AXIS)
        local = tokens - s * v_local
        ok = jnp.logical_and(local >= 0, local < v_local)
        emb = wte[jnp.clip(local, 0, v_local - 1)].astype(c.compute_dtype)
        emb = jnp.where(ok[..., None], emb, 0)
        emb = jax.lax.psum(emb, PIPE_AXIS)
        T = tokens.shape[-1]
        return emb + io_params["wpe"][jnp.arange(T)].astype(c.compute_dtype)

    def _vp_head_loss(self, y, io_params, labels_mb, mb):
        """Vocab-parallel tied head + cross-entropy over the pipe axis, one micro-batch.

        Runs on EVERY pipe rank against the psum-broadcast final activation
        (``last_stage_collective=True``): each rank computes logits only for its
        [V/S, E] vocab slice; softmax statistics and the correct-class logit combine
        with pipe collectives (Megatron vocab-parallel cross-entropy, ported to the
        pipe axis). Padded vocab columns (table padded to a stage multiple) are
        masked out of the softmax."""
        c = self.config
        dense = self._dense
        wte = io_params["wte"]
        v_local = wte.shape[0]
        s = jax.lax.axis_index(PIPE_AXIS)
        labels = labels_mb[mb]
        y = dense._layer_norm(y, io_params["ln_f"], c.layer_norm_epsilon)
        logits = jnp.einsum("bth,vh->btv", y, wte.astype(y.dtype),
                            preferred_element_type=jnp.float32)      # [B, T, V/S] fp32
        if self.vocab_pad != c.vocab_size:
            col = s * v_local + jnp.arange(v_local)
            logits = jnp.where(col < c.vocab_size, logits, -1e30)
        # stability shift only — cut the tangent BEFORE the collective (pmax has no
        # JVP rule; the softmax max-subtraction cancels in the gradient anyway)
        m = jax.lax.pmax(jax.lax.stop_gradient(jnp.max(logits, axis=-1)),
                         PIPE_AXIS)                                 # [B, T]
        sumexp = jax.lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                              PIPE_AXIS)
        local_label = labels - s * v_local
        ok = jnp.logical_and(local_label >= 0, local_label < v_local)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local_label, 0, v_local - 1)[..., None], axis=-1)[..., 0]
        ll = jax.lax.psum(jnp.where(ok, picked, 0.0), PIPE_AXIS)    # [B, T]
        return jnp.mean(m + jnp.log(sumexp) - ll)

    # ---- training loss over micro-batches ----
    def loss(self, params, tokens_mb, labels_mb, *, mesh,
             max_microbatches_per_flush=None, stream_segments=True):
        """Mean LM loss over [M, B, T] micro-batches through the pipe-axis pipeline.
        The segmentation knobs pass through to ``pipeline_apply`` (streamed
        single-fill segments by default)."""
        from jax.sharding import PartitionSpec as P
        if self.tp > 1:
            tp_in_mesh = mesh.shape.get(MODEL_AXIS, 1)
            assert tp_in_mesh == self.tp, \
                f"model constructed with tp={self.tp} but mesh model axis is {tp_in_mesh}"
        io = params["io"]
        io_specs = {k: (P(PIPE_AXIS, None) if k == "wte" else P()) for k in io}
        return pipeline_apply(
            self._stage_fn,
            params["stages"],
            tokens_mb,
            mesh=mesh,
            first_stage_fn=lambda toks, io_p: self._vp_embed(toks, io_p),
            first_stage_args=(io,),
            first_stage_args_specs=(io_specs,),
            last_stage_fn=lambda y, io_p, labels, mb: self._vp_head_loss(y, io_p, labels, mb),
            last_stage_collective=True,
            last_stage_args=(io, labels_mb),
            last_stage_args_specs=(
                io_specs, P(None, "data") if labels_mb.ndim >= 2 else P()),
            stacked_param_specs=self._stacked_specs(params["stages"]),
            max_microbatches_per_flush=max_microbatches_per_flush,
            stream_segments=stream_segments,
        )
