"""BERT model family built on the fused transformer layer.

The reference accelerates BERT pretraining by swapping HF/NVIDIA BertLayer for its fused
kernel layer (``docs/_tutorials/bert-pretraining.md``); here the model is in-tree: BERT
embeddings + N ``DeepSpeedTransformerLayer``s + MLM head, pure-function style.
"""

import math
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.transformer import DeepSpeedTransformerConfig, DeepSpeedTransformerLayer


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    pre_layer_norm: bool = False     # classic BERT is post-LN
    compute_dtype: Any = jnp.bfloat16
    use_flash_attention: bool = True


class BertModel:
    """``init(rng) -> params``; ``apply(params, input_ids, token_type_ids=None,
    attention_mask=None, rng=None, deterministic=True) -> [B, T, H]``."""

    def __init__(self, config: BertConfig):
        self.config = config
        self._layer = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(
            hidden_size=config.hidden_size,
            intermediate_size=config.intermediate_size,
            heads=config.num_attention_heads,
            attn_dropout_ratio=config.attention_probs_dropout_prob,
            hidden_dropout_ratio=config.hidden_dropout_prob,
            num_hidden_layers=config.num_hidden_layers,
            initializer_range=config.initializer_range,
            pre_layer_norm=config.pre_layer_norm,
            bf16=config.compute_dtype == jnp.bfloat16,
            fp16=config.compute_dtype == jnp.float16,
            use_flash_attention=config.use_flash_attention,
        ))

    def init(self, rng):
        c = self.config
        ks = jax.random.split(rng, 3 + c.num_hidden_layers)
        std = c.initializer_range
        params = {
            "embeddings": {
                "word": jax.random.normal(ks[0], (c.vocab_size, c.hidden_size), jnp.float32) * std,
                "position": jax.random.normal(ks[1], (c.max_position_embeddings, c.hidden_size),
                                              jnp.float32) * std,
                "token_type": jax.random.normal(ks[2], (c.type_vocab_size, c.hidden_size),
                                                jnp.float32) * std,
                "ln_scale": jnp.ones((c.hidden_size,), jnp.float32),
                "ln_bias": jnp.zeros((c.hidden_size,), jnp.float32),
            },
            "layers": [self._layer.init(ks[3 + i]) for i in range(c.num_hidden_layers)],
        }
        return params

    def _embed(self, params, input_ids, token_type_ids):
        c = self.config
        e = params["embeddings"]
        T = input_ids.shape[1]
        x = e["word"][input_ids] + e["position"][jnp.arange(T)][None]
        if token_type_ids is not None:
            x = x + e["token_type"][token_type_ids]
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        x = ((xf - mean) * jax.lax.rsqrt(var + 1e-12)) * e["ln_scale"] + e["ln_bias"]
        return x.astype(c.compute_dtype)

    def apply(self, params, input_ids, token_type_ids=None, attention_mask=None, rng=None,
              deterministic=True):
        x = self._embed(params, input_ids, token_type_ids)
        ext_mask = None
        if attention_mask is not None:
            # [B, T] 1/0 mask -> additive [B, 1, 1, T]
            ext_mask = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * -1e9
        for lp in params["layers"]:
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x = self._layer.apply(lp, x, attention_mask=ext_mask, rng=sub,
                                  deterministic=deterministic)
        return x


class BertForMaskedLM:
    """BERT + tied-embedding MLM head; apply returns the masked-LM loss."""

    def __init__(self, config: BertConfig):
        self.config = config
        self.bert = BertModel(config)

    def init(self, rng):
        return self.bert.init(rng)

    def logits(self, params, input_ids, token_type_ids=None, attention_mask=None,
               rng=None, deterministic=True):
        x = self.bert.apply(params, input_ids, token_type_ids, attention_mask, rng, deterministic)
        wte = params["embeddings"]["word"]
        return jnp.einsum("bth,vh->btv", x, wte.astype(x.dtype),
                          preferred_element_type=jnp.float32)

    def apply(self, params, input_ids, labels, token_type_ids=None, attention_mask=None,
              rng=None, deterministic=True):
        """labels: [B, T] with -100 for unmasked positions (ignored)."""
        logits = self.logits(params, input_ids, token_type_ids, attention_mask, rng, deterministic)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ids = jnp.maximum(labels, 0)
        ll = jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def param_count(self, params) -> int:
        from ..runtime.utils import param_count
        return param_count(params)


class BertForQuestionAnswering:
    """BERT + span-extraction head: the BingBertSquad fine-tuning workload of the
    reference (tests/model/BingBertSquad drives a SQuAD fine-tune through the engine).
    ``apply`` returns the mean of start- and end-position cross-entropies."""

    def __init__(self, config: BertConfig):
        self.config = config
        self.bert = BertModel(config)

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        params = self.bert.init(k1)
        h = self.config.hidden_size
        params["qa_outputs"] = {
            "w": jax.random.normal(k2, (h, 2), jnp.float32) * self.config.initializer_range,
            "b": jnp.zeros((2,), jnp.float32),
        }
        return params

    def logits(self, params, input_ids, token_type_ids=None, attention_mask=None,
               rng=None, deterministic=True):
        """-> (start_logits, end_logits), each [B, T] fp32."""
        x = self.bert.apply(params, input_ids, token_type_ids, attention_mask, rng,
                            deterministic)
        qa = params["qa_outputs"]
        out = jnp.dot(x, qa["w"].astype(x.dtype),
                      preferred_element_type=jnp.float32) + qa["b"]
        return out[..., 0], out[..., 1]

    def apply(self, params, input_ids, start_positions, end_positions,
              token_type_ids=None, attention_mask=None, rng=None, deterministic=True):
        start_logits, end_logits = self.logits(params, input_ids, token_type_ids,
                                               attention_mask, rng, deterministic)

        def ce(logits, pos):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, pos[:, None], axis=-1)[:, 0])

        return (ce(start_logits, start_positions) + ce(end_logits, end_positions)) / 2.0

    def param_count(self, params) -> int:
        from ..runtime.utils import param_count
        return param_count(params)
