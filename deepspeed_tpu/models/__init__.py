from .gpt2 import GPT2Config, GPT2Model
