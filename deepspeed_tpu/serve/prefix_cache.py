"""Cross-request prefix cache over the paged KV pool (host-only).

SGLang's RadixAttention observation, restated for this engine: for
shared-system-prompt traffic the dominant TTFT cost is re-prefilling tokens
whose KV already sits in the pool under some other request's block table. The
allocator's refcount/fork machinery (block_allocator.py) was built for exactly
this kind of sharing — beam lanes already read one prompt's pages through many
tables — so cross-request reuse is the same trick with a content key instead
of a parent lane.

Design:

- **Block-granular chained keys.** A prompt's cacheable unit is a *full*
  block of ``block_size`` tokens; block ``i``'s key is the exact chain
  ``(key_{i-1}, tokens_i)`` (nested tuples — collision-free by construction
  and deterministic across processes, which the byte-identical replay
  contract requires; "hashing" the chain would trade that for nothing at
  serving scale). A key therefore identifies the whole prefix up to and
  including its block, never a block out of context.
- **Only immutable pages are cached.** Decode writes land at positions
  ``>= prompt_len``, so prompt blocks fully inside ``[0, prompt_len)`` are
  written exactly once (during prefill) and never again; only those are
  registered. A hit is additionally capped at the last *full* block strictly
  before the final prompt token — the completing prefill chunk must run for
  real, because its logits seed the first token.
- **Lifecycle rides the allocator's cached tier.** Registration marks live
  pages; their last free parks them in the LRU tier instead of the free list
  (block_allocator.register_cached). A hit on a live page is one more
  reference; a hit on a parked page revives it. Pressure evicts parked pages
  oldest-first and the allocator's evict hook erases the key here — admission
  is refused only once both free and cached tiers are empty.
- **Two-phase admission.** ``peek`` is a pure read (the scheduler retries a
  blocked front request every iteration — counters must not inflate);
  ``acquire`` commits the references and records the hit/miss. Everything is
  a pure function of the request trace, so schedule replays stay
  byte-identical with the cache on.
"""

from .block_allocator import BlockAllocator


def key_to_chain(key):
    """Nested-tuple chain key -> JSON-serializable list of token lists
    (outermost block last, i.e. prompt order). Inverse of chain_to_key."""
    out = []
    while key is not None:
        key, toks = key
        out.append([int(t) for t in toks])
    out.reverse()
    return out


def chain_to_key(chain):
    """Fold a serialized chain back into the exact nested-tuple key — the
    rebuilt key is ``==``/hash-identical to the original, so a warm-restarted
    cache hits the same chains the pre-kill cache did."""
    key = None
    for toks in chain:
        key = (key, tuple(int(t) for t in toks))
    return key


class PrefixCache:
    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = int(block_size)
        self._by_key = {}                     # chain key -> block id
        allocator.set_evict_hook(self._on_evict)
        # admission-commit counters (peek never counts)
        self.hits = 0                         # admissions reusing >= 1 block
        self.misses = 0                       # admissions reusing none
        self.hit_tokens = 0                   # prompt tokens never prefetched
        self.lookup_tokens = 0                # prompt tokens of all admissions
        self.registered_blocks = 0            # cumulative register() inserts

    # -------------------------------------------------------------- keying
    def _chain(self, prompt, n_blocks):
        """Chained content keys for the first ``n_blocks`` full blocks."""
        BS, key, out = self.block_size, None, []
        for i in range(n_blocks):
            key = (key, tuple(prompt[i * BS:(i + 1) * BS]))
            out.append(key)
        return out

    def _max_hit_blocks(self, prompt_len):
        # full blocks strictly before the last prompt token: the chunk that
        # completes the prompt always prefills, so first-token logits exist
        return max(prompt_len - 1, 0) // self.block_size

    # -------------------------------------------------------------- lookup
    def peek(self, prompt):
        """Longest cached chain for this prompt: ``(blocks, hit_tokens)``.
        Pure read — no refcounts move, no counters advance."""
        blocks = []
        for key in self._chain(prompt, self._max_hit_blocks(len(prompt))):
            b = self._by_key.get(key)
            if b is None:
                break
            blocks.append(b)
        return blocks, len(blocks) * self.block_size

    def acquire(self, blocks, prompt_len):
        """Commit a peeked hit into a new table: live pages gain a reference,
        parked pages revive. Call only when admission is certain."""
        for b in blocks:
            if self.allocator.is_parked(b):
                self.allocator.revive(b)
            else:
                self.allocator.add_ref(b)
        if blocks:
            self.hits += 1
        else:
            self.misses += 1
        self.hit_tokens += len(blocks) * self.block_size
        self.lookup_tokens += int(prompt_len)

    # ------------------------------------------------------------ register
    def register(self, prompt, table, known_tokens):
        """Register every full, immutable prompt block whose KV the pool
        already holds (``known_tokens`` prefilled so far; at ``begin_decode``
        that is the whole prompt, at preemption the prefill frontier).
        Idempotent; first writer wins on a duplicate chain."""
        n = min(int(known_tokens), len(prompt)) // self.block_size
        n = min(n, len(table))
        for i, key in enumerate(self._chain(prompt, n)):
            if key in self._by_key:
                continue                      # same content already mapped
            self.allocator.register_cached(table[i], key)
            self._by_key[key] = table[i]
            self.registered_blocks += 1

    def _on_evict(self, block, key):
        # the page's device bytes are being reclaimed — forget the mapping
        self._by_key.pop(key, None)

    # ------------------------------------------------------- warm restart
    def state_dict(self) -> dict:
        return {
            "by_key": [[key_to_chain(k), b] for k, b in self._by_key.items()],
            "counters": {k: getattr(self, k) for k in
                         ("hits", "misses", "hit_tokens", "lookup_tokens",
                          "registered_blocks")},
        }

    def load_state_dict(self, state: dict) -> None:
        self._by_key = {chain_to_key(ch): int(b)
                        for ch, b in state["by_key"]}
        for k, v in state["counters"].items():
            setattr(self, k, int(v))

    # --------------------------------------------------------------- stats
    def stats(self):
        looked = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / looked) if looked else 0.0,
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "cached_token_fraction": ((self.hit_tokens / self.lookup_tokens)
                                      if self.lookup_tokens else 0.0),
            "registered_blocks": self.registered_blocks,
            "parked_blocks": self.allocator.num_cached,
            "evictions": self.allocator.cache_evictions,
            "revivals": self.allocator.cache_revivals,
        }
