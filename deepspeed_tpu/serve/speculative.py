"""Speculative decoding over the paged KV pool (Leviathan et al., greedy).

The draft model autoregressively proposes up to K tokens per scheduler
iteration against its OWN small paged pool (its programs are the same
fixed-shape ``decode_step`` / ``prefill_chunk`` builds from serve/paged.py,
pools donated end to end), then the target verifies all K+1 positions in ONE
``spec_verify`` execution over the main pool — a batched, chunked-prefill-
shaped step with per-position logits out. Greedy acceptance: walking the
verify rows in order, row i's argmax g_i commits unconditionally (it is what
plain decode would have sampled there); if it equals draft token d_{i+1} the
walk continues, else it stops — so every round commits between 1 and K+1
tokens and the emitted stream is token-identical to the target's own greedy
decode. The first rejection truncates the request's block table back to the
accepted frontier and refcount-releases the tail pages; garbage KV past the
frontier in the kept partial page is never attended (the causal mask stops at
the query position) and the next round's writes cover the same extent, so
rollback is free — no device work, exactly the CoW allocator's fork/release
machinery beam search already exercises.

Identity contract: the D-wide verify rows are argmax-identical to the 1-wide
``decode_step`` but NOT bitwise (XLA fuses the wider batch differently — ulp
drift, same precedent as the sharded engine's per-layer psum), so the engine
refuses speculation + mirror-oracle, and ``ds-tpu serve-sim
--compare-speculate`` pins token identity deterministically instead.

This module owns only the DRAFT side (pools, allocator, catch-up prefill,
proposal loop) plus the pure acceptance rule; the engine owns the target
``spec_verify`` program and the commit/rollback of the target block table.
Draft state is best-effort by construction: a preempted or finished group's
draft pages are dropped (``sync``) and rebuilt from the request's committed
context on its next speculative turn, so preemption, warm restart and the
latest-admitted-first victim policy are untouched.
"""

import numpy as np

import jax.numpy as jnp

from .block_allocator import AllocationError, BlockAllocator, NULL_BLOCK
from .paged import build_paged_programs


def accept_greedy(row_argmax, draft_tokens):
    """The speculative acceptance rule on host ints. ``row_argmax[i]`` is the
    target's greedy token after consuming the last committed token plus
    ``draft_tokens[:i]``; returns ``(committed, accepted)`` where ``committed``
    is the token run plain greedy decode would have emitted (always at least
    one: row 0 IS the plain decode step) and ``accepted`` counts the draft
    tokens that matched. The caller cuts ``committed`` early on EOS /
    max_new_tokens — this rule knows nothing about stop conditions."""
    committed, accepted = [], 0
    m = len(draft_tokens)
    for i, t in enumerate(row_argmax):
        committed.append(int(t))
        if i < m and int(draft_tokens[i]) == int(t):
            accepted += 1
        else:
            break
    return committed, accepted


class SpeculativeDecoder:
    """Draft-side state machine for one engine: a private paged KV pool for
    the draft model, per-group draft block tables, and the propose loop.

    The draft pool mirrors the target pool's geometry knobs (block size,
    table width, chunk length) at the DRAFT model's layer/head shapes, and is
    sized by ``draft_pool_blocks``. Draft pages are never shared (no beam
    lanes, no prefix cache), so there is no CoW here — truncation after a
    rejection is a plain refcount release. Draft allocation failure is never
    fatal: the group simply decodes plainly this iteration (deterministic —
    a pure function of pool state, itself a pure function of the trace)."""

    def __init__(self, draft_model, draft_params, *, num_slots, block_size,
                 max_blocks, prefill_chunk, draft_pool_blocks,
                 max_draft_tokens, target_config, watch=None):
        dc = draft_model.config
        if dc.vocab_size != target_config.vocab_size:
            raise ValueError(
                f"draft vocab_size {dc.vocab_size} != target vocab_size "
                f"{target_config.vocab_size}: speculative acceptance compares "
                "token ids, the vocabularies must be the same")
        max_model_len = int(max_blocks) * int(block_size)
        if dc.n_positions < max_model_len:
            raise ValueError(
                f"draft n_positions {dc.n_positions} < max_model_len "
                f"{max_model_len}: the draft must reach every position the "
                "target serves")
        if getattr(dc, "moe_experts", 0) or getattr(dc, "sparse_attention",
                                                    None):
            raise ValueError("speculative drafting supports dense draft "
                             "models only (same rule as the serving engine)")
        if max_draft_tokens < 1:
            raise ValueError(f"max_draft_tokens must be >= 1, "
                             f"got {max_draft_tokens}")
        self.model = draft_model
        self.params = draft_params
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks)
        self.prefill_chunk = int(prefill_chunk)
        self.max_draft_tokens = int(max_draft_tokens)
        self.allocator = BlockAllocator(int(draft_pool_blocks),
                                        int(block_size))
        raw = build_paged_programs(
            draft_model, num_slots=self.num_slots,
            block_size=self.block_size, max_blocks=self.max_blocks,
            prefill_chunk=self.prefill_chunk)
        self._raw = raw
        watch = watch or (lambda name, fn: fn)
        self._decode = watch("serve:spec_draft_decode", raw["decode_step"])
        self._prefill = watch("serve:spec_draft_prefill", raw["prefill_chunk"])
        pool_shape = (dc.n_layer, int(draft_pool_blocks), self.block_size,
                      dc.n_head, dc.head_dim)
        self.k_pool = jnp.zeros(pool_shape, dc.compute_dtype)
        self.v_pool = jnp.zeros(pool_shape, dc.compute_dtype)
        # (req_id, admission_idx) -> {"table": [...], "done": int}: ``done``
        # counts positions with valid draft KV; the key is unique per Group
        # instance (a preempt-restart re-admits under a new admission_idx),
        # so stale state can never alias a restarted request
        self._state = {}

    # ----------------------------------------------------------- group state
    @staticmethod
    def _key(g):
        return (g.req.req_id, g.admission_idx)

    def sync(self, running):
        """Drop draft state for groups no longer running (finished, preempted
        or quiesced) — their pages go back to the draft pool. Called at the
        top of every speculative turn, so no removal path needs a hook."""
        alive = {self._key(g) for g in running}
        for key in [k for k in self._state if k not in alive]:
            self.allocator.free(self._state.pop(key)["table"])

    def release(self, g):
        st = self._state.pop(self._key(g), None)
        if st is not None:
            self.allocator.free(st["table"])

    def drop_all(self):
        for key in list(self._state):
            self.allocator.free(self._state.pop(key)["table"])

    def prepare(self, g, m):
        """Host-only reservation for one speculative round: make the group's
        draft table cover every position the catch-up + proposal pass will
        write (up to ``next_pos + m - 1``). Returns False — group plain-
        decodes this iteration — when the draft pool cannot cover it; any
        state it already has stays valid (``done`` just lags further)."""
        st = self._state.setdefault(self._key(g), {"table": [], "done": 0})
        need = self.allocator.blocks_for_tokens(g.next_pos(0) + m)
        ext = need - len(st["table"])
        if ext <= 0:
            return True
        try:
            st["table"].extend(self.allocator.allocate(ext))
        except AllocationError:
            return False
        return True

    # -------------------------------------------------------------- proposal
    def _pad_table(self, table):
        out = np.full(self.max_blocks, NULL_BLOCK, np.int32)
        out[:len(table)] = table
        return out

    def _catch_up(self, g, st):
        """Feed the draft every committed token it has not consumed yet —
        ``ctx[done:]`` — through the fixed-shape prefill program, one chunk
        at a time. The chunk that reaches the context frontier returns the
        draft's next-token logits, i.e. the first proposal. Returns that
        logits row ([V] f32 np)."""
        ctx = g.req.prompt + g.generated[0]
        C = self.prefill_chunk
        table = jnp.asarray(self._pad_table(st["table"]))
        logits = None
        for pos in range(st["done"], len(ctx), C):
            chunk = ctx[pos:pos + C]
            n = len(chunk)
            chunk = chunk + [0] * (C - n)
            logits, self.k_pool, self.v_pool = self._prefill(
                self.params, jnp.asarray([chunk], jnp.int32), jnp.int32(pos),
                jnp.int32(n), table, self.k_pool, self.v_pool)
        st["done"] = len(ctx)
        return np.asarray(logits[0])

    def propose(self, plan):
        """Run one drafting turn for every (group, m) in ``plan``: per-group
        catch-up prefill (first proposal falls out of the chunk that completes
        the context), then batched greedy draft-decode steps for the rest —
        groups that want fewer proposals go inactive in later steps. Returns
        ``{key(g): [d_1..d_m]}``. Every program call has the one baked shape,
        so a drafting turn never recompiles anything."""
        drafts, alive = {}, []
        for g, m in plan:
            st = self._state[self._key(g)]
            row = self._catch_up(g, st)
            drafts[self._key(g)] = [int(np.argmax(row))]
            if m > 1:
                alive.append((g, m, st))
        steps = max((m - 1 for _, m, _ in alive), default=0)
        S = self.num_slots
        for j in range(steps):
            toks = np.zeros(S, np.int32)
            pos = np.zeros(S, np.int32)
            tables = np.full((S, self.max_blocks), NULL_BLOCK, np.int32)
            active = np.zeros(S, bool)
            stepping = []
            for g, m, st in alive:
                if j >= m - 1:
                    continue
                slot = g.slots[0]
                ds = drafts[self._key(g)]
                toks[slot] = ds[-1]
                pos[slot] = st["done"]
                tables[slot] = self._pad_table(st["table"])
                active[slot] = True
                stepping.append((g, st))
            logits, self.k_pool, self.v_pool = self._decode(
                self.params, jnp.asarray(toks), jnp.asarray(pos),
                jnp.asarray(tables), jnp.asarray(active),
                self.k_pool, self.v_pool)
            logits_np = np.asarray(logits)
            for g, st in stepping:
                drafts[self._key(g)].append(
                    int(np.argmax(logits_np[g.slots[0]])))
                st["done"] += 1
        return drafts

    def observe(self, g, p0, accepted, drafted):
        """Reconcile draft state with a verify outcome: positions past the
        accepted frontier hold rejected-token KV, so ``done`` falls back to
        ``min(p0 + accepted + 1, p0 + drafted)`` and the table truncates to
        match — the draft-side twin of the target-table rollback (plain
        refcount release; draft pages are never shared)."""
        st = self._state.get(self._key(g))
        if st is None:
            return
        st["done"] = min(p0 + accepted + 1, p0 + drafted)
        keep = self.allocator.blocks_for_tokens(st["done"])
        if keep < len(st["table"]):
            self.allocator.free(st["table"][keep:])
            del st["table"][keep:]

    # ------------------------------------------------------------------ misc
    def pool_stats(self):
        st = self.allocator.stats()
        return {"free": st["free"], "used": st["used"]}

    def lint_programs(self, manifest):
        """Draft program entries for the lint registry — same donation +
        zero-collective budgets as the engine's own serving programs."""
        dc = self.model.config
        S, MB, C = self.num_slots, self.max_blocks, self.prefill_chunk
        pool_shape = (dc.n_layer, self.allocator.num_blocks, self.block_size,
                      dc.n_head, dc.head_dim)
        kp = jnp.zeros(pool_shape, dc.compute_dtype)
        vp = jnp.zeros(pool_shape, dc.compute_dtype)
        zs = jnp.zeros(S, jnp.int32)
        return [
            ("serve_spec_draft_decode", self._raw["decode_step"],
             (self.params, zs, zs, jnp.zeros((S, MB), jnp.int32),
              jnp.zeros(S, bool), kp, vp), manifest),
            ("serve_spec_draft_prefill", self._raw["prefill_chunk"],
             (self.params, jnp.zeros((1, C), jnp.int32), jnp.int32(0),
              jnp.int32(1), jnp.zeros(MB, jnp.int32), kp, vp), manifest),
        ]
