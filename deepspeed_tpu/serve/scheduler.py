"""Iteration-granular continuous-batching scheduler (host-only, deterministic).

Orca's insight (OSDI '22): schedule at *iteration* granularity — every device
step, finished sequences leave, waiting sequences join, and one long prompt
prefills one chunk while everyone else decodes. This module is the pure host
half of that loop: admission, chunked-prefill selection, per-step write-block
accounting (with beam copy-on-write), preemption, and beam table forking. It
never touches the device — the engine executes the plan each method returns.

Determinism contract (pinned by tests/unit/test_serving_scheduler.py): every
decision is a pure function of the submitted trace, so a replay produces a
byte-identical schedule log. Concretely: the waiting queue orders by
``(arrival, submit index)`` and is *front-blocking* (an unadmittable front
blocks later arrivals — no overtaking); free slots and KV pages are handed
out in index order; preemption victims are the latest-admitted groups first;
and preemption is full restart (vLLM's recompute mode) — the restarted run
recomputes bit-identical logits because every device program has one fixed
shape, so discarding progress never changes the tokens (the preempt-resume
equivalence test pins exactly this).

With ``prefix_cache=True`` admission first consults the cross-request prefix
cache (serve/prefix_cache.py): cached full prompt blocks are mapped into the
new table by reference (live pages) or revival (parked pages) and prefill
starts past them — the chunk positions a warm start skips produce KV that is
bit-identical to a cold prefill's, because every per-row op in the fixed-shape
programs depends only on that row's inputs, so the downstream logits (and
tokens) cannot change. Preemption registers the prefill frontier before
freeing, which is what makes a preempt-restart warm instead of a full
re-prefill. Cache decisions are pure functions of the trace too, so replays
stay byte-identical with the cache on.
"""

from .block_allocator import AllocationError, BlockAllocator
from .prefix_cache import PrefixCache

_REQ_FIELDS = ("req_id", "prompt", "max_new_tokens", "arrival", "num_beams",
               "length_penalty", "temperature", "top_k", "top_p", "seed")
_REQ_CARRY = ("_preemptions_carry", "_replay_prefill_hwm", "_replay_decode_hwm")


def pack_request(req) -> dict:
    """Request -> plain dict (warm-restart serialization). The replay
    high-water marks and preemption count a preempted attempt carries ride
    along, so the restarted replica's waste accounting stays truthful."""
    d = {k: getattr(req, k) for k in _REQ_FIELDS}
    d["prompt"] = list(req.prompt)
    # the ctor normalizes None -> -1; -1 round-trips through int() unchanged
    d["eos_token_id"] = req.eos_token_id
    for k in _REQ_CARRY:
        if hasattr(req, k):
            d[k] = getattr(req, k)
    return d


def unpack_request(d: dict):
    req = Request(d["req_id"], d["prompt"], d["max_new_tokens"],
                  arrival=d["arrival"], num_beams=d["num_beams"],
                  eos_token_id=d["eos_token_id"],
                  length_penalty=d["length_penalty"],
                  temperature=d["temperature"], top_k=d["top_k"],
                  top_p=d["top_p"], seed=d["seed"])
    for k in _REQ_CARRY:
        if k in d:
            setattr(req, k, d[k])
    return req


class Request:
    """One serving request. ``arrival`` is the iteration index at which the
    scheduler may first admit it (request traces are replayed in iteration
    time, keeping schedules machine-independent).

    Sampling (single-lane requests only): ``temperature <= 0`` is exact greedy
    (np.argmax, first-max tie-break); ``temperature > 0`` draws from the
    temperature-scaled softmax after optional top-k / nucleus (top-p)
    truncation. Draws are counter-based on ``(seed, token position)`` — no
    mutable RNG state — so a trace replay, and a preempt-restarted prefill
    (which recomputes bit-identical logits), regenerate the exact same tokens."""

    def __init__(self, req_id, prompt, max_new_tokens, arrival=0, num_beams=1,
                 eos_token_id=None, length_penalty=1.0, temperature=0.0,
                 top_k=0, top_p=1.0, seed=0):
        self.req_id = req_id
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.arrival = int(arrival)
        self.num_beams = int(num_beams)
        self.eos_token_id = -1 if eos_token_id is None else int(eos_token_id)
        self.length_penalty = float(length_penalty)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = disabled), got {top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if self.temperature > 0.0 and self.num_beams > 1:
            raise ValueError("sampling (temperature > 0) is incompatible with "
                             "beam search — beams rank exact log-probs")


class RequestOutput:
    def __init__(self, req_id, status, tokens=None, score=None, refusal=None,
                 ttft_iters=None, ttft_ms=None, finished_it=None,
                 preemptions=0):
        self.req_id = req_id
        self.status = status            # "finished" | "refused" | "shed"
        self.tokens = tokens or []      # generated tokens (best beam)
        self.score = score              # beam: GNMT-normalized score
        self.refusal = refusal          # refusal reason when status=="refused"
        self.ttft_iters = ttft_iters
        self.ttft_ms = ttft_ms
        self.finished_it = finished_it
        self.preemptions = preemptions


class Group:
    """One admitted request in flight: 1 lane (greedy) or K beam lanes.
    ``tables[k]`` is lane k's block table; ``generated[k]`` its tokens."""

    def __init__(self, req, submit_idx, admission_idx, slots, table):
        self.req = req
        self.submit_idx = submit_idx
        self.admission_idx = admission_idx
        self.slots = slots                      # K slot ids, lane order
        self.tables = [table]                   # lanes fork at prefill end
        self.prefill_done = 0
        self.cached_prefix_tokens = 0           # prompt tokens a cache hit skipped
        self.phase = "prefill"
        self.generated = []                     # per lane after first token
        self.scores = None                      # beam lane scores (host floats)
        self.live = None
        self.entered_decode_it = None
        self.first_token_it = None
        self.first_token_ms = None
        self.preemptions = 0
        # replay high-water marks: how far a previous (preempted) attempt got.
        # Prefill positions below the prefill mark and decode steps below the
        # decode mark recompute work the pool eviction threw away — the
        # request-trace ledger classifies exactly those tokens as waste.
        self.replay_prefill_hwm = getattr(req, "_replay_prefill_hwm", 0)
        self.replay_decode_hwm = getattr(req, "_replay_decode_hwm", 0)
        self.evicted_blocks = 0                 # KV pages freed by _preempt

    @property
    def lanes(self):
        return self.req.num_beams

    @property
    def prompt_len(self):
        return len(self.req.prompt)

    def next_pos(self, lane):
        """Cache position the lane's next decode step writes (= position of
        its newest token, which that step consumes)."""
        return self.prompt_len + len(self.generated[lane]) - 1

    def prefill_replay_tokens(self, pos, n):
        """Of a prefill chunk covering positions [pos, pos+n), how many were
        already computed by a preempted attempt (bit-identical recompute)."""
        return min(max(self.replay_prefill_hwm - pos, 0), n)

    def decode_is_replay(self):
        """True when the coming decode step regenerates a token a preempted
        attempt had already produced (call before the step appends)."""
        return bool(self.generated) and len(self.generated[0]) < self.replay_decode_hwm


class Scheduler:
    def __init__(self, *, num_slots, num_blocks, block_size, max_model_len,
                 prefill_chunk, prefix_cache=False):
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len)
        self.prefill_chunk = int(prefill_chunk)
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.prefix_cache = (PrefixCache(self.allocator, block_size)
                             if prefix_cache else None)
        self.free_slots = list(range(self.num_slots))
        self.waiting = []                       # Groups-to-be: (req, submit_idx)
        self.running = []                       # admission order
        self._submit_counter = 0
        self._admission_counter = 0

    # ------------------------------------------------------------ submission
    def infeasible_reason(self, req):
        T0, L, K = len(req.prompt), req.max_new_tokens, req.num_beams
        BS = self.block_size
        usable = self.allocator.num_blocks - 1
        if T0 < 1 or L < 1:
            return f"prompt ({T0}) and max_new_tokens ({L}) must be >= 1"
        if K < 1 or K > self.num_slots:
            return f"num_beams {K} exceeds {self.num_slots} slots"
        if T0 + L > self.max_model_len:
            return (f"prompt {T0} + {L} new tokens exceeds max_model_len "
                    f"{self.max_model_len}")
        shared = T0 // BS                       # full prompt blocks stay shared
        per_lane = -(-(T0 + L) // BS) - shared  # worst-case exclusive suffix
        worst = shared + K * per_lane
        if worst > usable:
            return (f"needs up to {worst} KV pages ({K} beam(s), "
                    f"{T0 + L} tokens) but the pool has {usable}")
        return None

    def submit(self, req):
        """Queue a request. Returns None on acceptance, or the refusal reason
        for a request that can NEVER fit (refusal, not a crash)."""
        reason = self.infeasible_reason(req)
        if reason is not None:
            return reason
        self.waiting.append((req, self._submit_counter))
        self._submit_counter += 1
        self.waiting.sort(key=lambda e: (e[0].arrival, e[1]))
        return None

    @property
    def idle(self):
        return not self.waiting and not self.running

    def next_arrival(self):
        return self.waiting[0][0].arrival if self.waiting else None

    # ------------------------------------------------------------- admission
    def _admit_blocks_needed(self, req):
        # prompt + first decode write, plus one-block fork headroom per extra
        # beam — enough that an admitted group always reaches its first tokens
        return (self.allocator.blocks_for_tokens(len(req.prompt) + 1)
                + (req.num_beams - 1))

    def admit(self, it):
        """FIFO, front-blocking admission of every due request that fits.
        With the prefix cache on, cached prompt blocks don't count against
        the pool (they are reused, not allocated) — but parked hit blocks
        stop counting as reclaimable, since the hit is about to pin them."""
        admitted = []
        while self.waiting:
            req, submit_idx = self.waiting[0]
            if req.arrival > it:
                break
            hit_blocks, hit_tokens = ([], 0)
            if self.prefix_cache is not None:
                hit_blocks, hit_tokens = self.prefix_cache.peek(req.prompt)
            parked = sum(1 for b in hit_blocks
                         if self.allocator.is_parked(b))
            fresh_needed = self._admit_blocks_needed(req) - len(hit_blocks)
            if (req.num_beams > len(self.free_slots)
                    or fresh_needed > self.allocator.num_free - parked):
                break                            # front-blocking: no overtaking
            self.waiting.pop(0)
            slots = [self.free_slots.pop(0) for _ in range(req.num_beams)]
            if self.prefix_cache is not None:
                self.prefix_cache.acquire(hit_blocks, len(req.prompt))
            table = list(hit_blocks) + self.allocator.allocate(
                self.allocator.blocks_for_tokens(len(req.prompt))
                - len(hit_blocks))
            g = Group(req, submit_idx, self._admission_counter, slots, table)
            g.cached_prefix_tokens = hit_tokens
            g.prefill_done = hit_tokens          # resume prefill past the hit
            self._admission_counter += 1
            self.running.append(g)
            admitted.append(g)
        return admitted

    # ------------------------------------------------------------ preemption
    def _preempt(self, g):
        """Full restart: free everything, requeue at the group's original
        queue position. The fixed-shape programs make the restarted run
        bit-identical, so no generated state needs saving. With the prefix
        cache on, the prefill frontier's full blocks are registered first, so
        the freed prompt pages park in the cached tier and the restart remaps
        them instead of re-prefilling — unless pressure evicts them first."""
        g.evicted_blocks = len({b for t in g.tables for b in t})
        if self.prefix_cache is not None and g.tables:
            self.prefix_cache.register(g.req.prompt, g.tables[0],
                                       g.prefill_done)
        for t in g.tables:
            self.allocator.free(t)
        g.tables = []
        self.free_slots.extend(g.slots)
        self.free_slots.sort()
        self.running.remove(g)
        g.preemptions += 1
        req = g.req
        req._preemptions_carry = g.preemptions  # survives the restart
        # the restart recomputes everything up to where this attempt got —
        # record that frontier so the ledger can bill the replay as waste
        req._replay_prefill_hwm = max(g.replay_prefill_hwm, g.prefill_done)
        req._replay_decode_hwm = max(
            g.replay_decode_hwm, len(g.generated[0]) if g.generated else 0)
        self.waiting.append((req, g.submit_idx))
        self.waiting.sort(key=lambda e: (e[0].arrival, e[1]))

    def ensure_decode_room(self):
        """Give every decode-phase lane an exclusive write block for this
        iteration's token, preempting latest-admitted groups when the pool
        runs dry. Returns (preempted_groups, copies) — ``copies`` are the
        (src, dst) page copies the engine must run before decode."""
        preempted, copies = [], []
        i = 0
        while i < len(self.running):
            g = self.running[i]
            if g.phase != "decode":
                i += 1
                continue
            try:
                # appends into ``copies`` in place so CoW pages claimed
                # before a mid-group AllocationError keep their device copy
                self._ensure_group_blocks(g, copies)
            except AllocationError:
                victim = self._pick_victim(g)
                # copies targeting the victim's pages die with it (their dst
                # pages go back to the pool and could be re-handed out)
                victim_pages = set()
                for t in victim.tables:
                    victim_pages.update(t)
                copies = [cp for cp in copies if cp[1] not in victim_pages]
                self._preempt(victim)
                preempted.append(victim)
                continue          # retry index i (g again, or next if g died)
            i += 1
        return preempted, copies

    def _pick_victim(self, needy):
        later = [g for g in self.running if g.admission_idx > needy.admission_idx]
        if later:
            return max(later, key=lambda g: g.admission_idx)
        return needy

    def _ensure_group_blocks(self, g, copies):
        BS = self.block_size
        for lane in range(g.lanes):
            bi = g.next_pos(lane) // BS
            table = g.tables[lane]
            if bi == len(table):
                table.append(self.allocator.allocate(1)[0])
            elif bi < len(table):
                blk, copy = self.allocator.ensure_exclusive(table[bi])
                if copy is not None:
                    table[bi] = blk
                    copies.append(copy)
            else:  # can't happen: positions grow one token at a time
                raise AssertionError("write block beyond table end")

    # --------------------------------------------------------------- prefill
    def next_prefill(self, it):
        """Earliest-admitted group still prefilling gets one chunk. Returns
        (group, pos, n_valid, chunk_tokens) or None; ``chunk_tokens`` is
        padded to the fixed chunk length."""
        for g in self.running:
            if g.phase != "prefill":
                continue
            pos = g.prefill_done
            n = min(self.prefill_chunk, g.prompt_len - pos)
            chunk = g.req.prompt[pos:pos + n]
            chunk = chunk + [0] * (self.prefill_chunk - n)
            return g, pos, n, chunk
        return None

    def finish_prefill_chunk(self, g, n_valid, it):
        """Advance prefill progress; returns True when the prompt completed
        (the engine then samples the first token and calls begin_decode)."""
        g.prefill_done += n_valid
        return g.prefill_done == g.prompt_len

    def begin_decode(self, g, first_tokens, it, scores=None, live=None):
        """Move a group to decode. ``first_tokens`` is [K] (greedy: [tok]);
        beam lanes fork the prefilled table. First decode runs NEXT iteration
        (its write block is ensured at that iteration's start)."""
        g.generated = [[int(t)] for t in first_tokens]
        g.scores = scores
        g.live = live
        g.phase = "decode"
        g.entered_decode_it = it
        g.first_token_it = it
        base = g.tables[0]
        if self.prefix_cache is not None:
            # the whole prompt is in the pool now; its full blocks are
            # immutable from here on (decode writes land past prompt_len)
            self.prefix_cache.register(g.req.prompt, base, g.prompt_len)
        g.tables = [base] + [self.allocator.fork(base)
                             for _ in range(g.lanes - 1)]

    # ---------------------------------------------------------------- decode
    def decode_lanes(self):
        """[(group, lane, slot)] for every decode-phase lane, admission/lane
        order — the deterministic decode-batch composition."""
        out = []
        for g in self.running:
            if g.phase == "decode":
                for lane, slot in enumerate(g.slots):
                    out.append((g, lane, slot))
        return out

    def reorder_beams(self, g, parents):
        """Apply a beam step's parent selection to tables and generated
        tokens — the paged analog of the dense path's ``kcs[:, flatp]``
        cache shuffle, done with refcount forks instead of copies."""
        old_tables = g.tables
        g.tables = [self.allocator.fork(old_tables[p]) for p in parents]
        for t in old_tables:
            self.allocator.free(t)
        g.generated = [list(g.generated[p]) for p in parents]

    def finish_group(self, g):
        for t in g.tables:
            self.allocator.free(t)
        g.tables = []
        self.free_slots.extend(g.slots)
        self.free_slots.sort()
        self.running.remove(g)

    # ------------------------------------------------------- warm restart
    def quiesce(self):
        """Preempt every running group (latest-admitted first — the same
        victim order pool pressure uses). After this the ledger is fully
        serializable: no Group objects, every in-flight request requeued at
        its original position with its prefill frontier registered in the
        prefix cache — a restart resumes warm instead of re-prefilling.
        Returns the preempted groups (their pages are now parked or free)."""
        victims = sorted(self.running, key=lambda g: -g.admission_idx)
        for g in victims:
            self._preempt(g)
        return victims

    def state_dict(self) -> dict:
        """Serializable scheduler ledger. Call ``quiesce`` first — running
        groups hold live page tables this snapshot cannot represent."""
        if self.running:
            raise RuntimeError("state_dict requires a quiesced scheduler "
                               f"({len(self.running)} groups still running)")
        return {
            "waiting": [[pack_request(r), idx] for r, idx in self.waiting],
            "free_slots": list(self.free_slots),
            "submit_counter": self._submit_counter,
            "admission_counter": self._admission_counter,
            "allocator": self.allocator.state_dict(),
            "prefix_cache": (self.prefix_cache.state_dict()
                             if self.prefix_cache is not None else None),
        }

    def load_state_dict(self, state: dict) -> None:
        if (state["prefix_cache"] is not None) != (self.prefix_cache is not None):
            raise ValueError("prefix_cache on/off mismatch between the "
                             "checkpointed scheduler and this one")
        self.allocator.load_state_dict(state["allocator"])
        if self.prefix_cache is not None:
            self.prefix_cache.load_state_dict(state["prefix_cache"])
        # rebuilt directly, NOT via submit(): submit would re-number
        # submit_idx and lose the original queue positions
        self.waiting = [(unpack_request(d), int(idx))
                        for d, idx in state["waiting"]]
        self.waiting.sort(key=lambda e: (e[0].arrival, e[1]))
        self.free_slots = [int(s) for s in state["free_slots"]]
        self._submit_counter = int(state["submit_counter"])
        self._admission_counter = int(state["admission_counter"])
        self.running = []

    # ------------------------------------------------------------------ misc
    def occupancy(self):
        return 1.0 - len(self.free_slots) / self.num_slots

    def pool_stats(self):
        """One block-pool timeline point for the request-trace ledger:
        allocator free/used/shared/CoW counters plus internal fragmentation —
        the fraction of token slots in used pages holding no token (a page is
        billed at its fullest lane; prompt pages shared across beam lanes
        count once)."""
        st = self.allocator.stats()
        BS = self.block_size
        fill = {}
        for g in self.running:
            for lane in range(len(g.tables)):
                if g.phase == "prefill":
                    n_tok = g.prefill_done
                else:
                    # newest token's KV is written by the NEXT decode step
                    n_tok = g.prompt_len + len(g.generated[lane]) - 1
                for i, b in enumerate(g.tables[lane]):
                    f = min(n_tok - i * BS, BS)
                    if f > 0:
                        fill[b] = max(fill.get(b, 0), f)
        capacity = st["used"] * BS
        frag = (1.0 - sum(fill.values()) / capacity) if capacity else 0.0
        return {"free": st["free"], "used": st["used"],
                "shared": st["shared"], "cow_copies": st["cow_copies"],
                "frag": frag}
