"""Host-side block allocator for the paged KV cache.

The device holds one fixed pool of ``num_blocks`` pages per layer
(``[n_layer, num_blocks, block_size, n_head, head_dim]``, serve/paged.py);
this allocator hands out page indices. Pure host bookkeeping — allocation
never touches the device, so admission control is a free-list length check,
not an OOM recovery path.

Design points (vLLM's PagedAttention memory model):

- **Block 0 is reserved** as the null page: padded/inactive lanes of the
  fixed-shape programs route their writes there, and unallocated block-table
  entries point at it. It is never handed out, so a stray write can never
  corrupt a live sequence.
- **Free list is FIFO** (appendleft/pop would be LIFO; we pop from the left
  of a deque seeded in index order) — allocation order is deterministic for
  the byte-identical schedule-replay tests.
- **Refcounts + copy-on-write**: beam search forks a parent sequence's table
  by incrementing refcounts; a writer that needs an exclusive page calls
  :meth:`ensure_exclusive`, which returns the ``(src, dst)`` page copy the
  caller must mirror on-device (paged.copy_blocks) when the page was shared.
  Speculative-decoding rollback (serve/speculative.py) is the same machinery
  run backwards: a rejected draft tail is undone by truncating the block
  table and :meth:`free`-ing the tail pages — pure refcount bookkeeping, no
  device work — and because verify writes went through ``ensure_exclusive``
  first, the rollback can never touch a page another holder still reads.
- **Cached tier** (SGLang's RadixAttention eviction model): a page registered
  through :meth:`register_cached` parks in an LRU *cached* tier when its last
  reference drops instead of returning to the free list — its KV bytes stay
  valid on device, so a later prefix hit revives it for free. Allocation
  drains the free list first and only then evicts cached pages oldest-first
  (``evict_hook`` tells the prefix cache its key died), so cached prefixes
  are reclaimed under pressure *before* admission is ever refused. With no
  registrations the tier is empty and every path below is bit-identical to
  the pre-cache allocator.
"""

from collections import OrderedDict, deque

NULL_BLOCK = 0


class AllocationError(RuntimeError):
    """Out of KV pages (or a request can never fit) — admission refusal, not
    a crash: callers catch this and keep the request waiting or reject it."""


class BlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved null "
                             f"page), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = deque(range(1, self.num_blocks))   # block 0 reserved
        self._refcount = {}                              # block -> int (>0)
        # prefix-cache tier: block -> cache key while registered (live OR
        # parked); parked zero-ref pages sit in ``_cached`` oldest-first
        self._cache_keys = {}
        self._cached = OrderedDict()
        self._evict_hook = None
        # cumulative free-list traffic counters for the serving request-trace
        # pool timeline (monotonic; never reset)
        self.alloc_count = 0        # pages handed out
        self.free_count = 0         # pages returned to the free list
        self.fork_count = 0         # page references added by table forks
        self.cow_copies = 0         # shared pages copied by ensure_exclusive
        self.cached_count = 0       # pages parked in the cached tier
        self.cache_evictions = 0    # parked pages reclaimed under pressure
        self.cache_revivals = 0     # parked pages brought back by a hit

    # ------------------------------------------------------------- queries
    @property
    def num_free(self) -> int:
        """Allocatable pages: truly free plus evictable cached prefixes —
        admission control must see cached pages as reclaimable, or the cache
        would shrink effective pool capacity."""
        return len(self._free) + len(self._cached)

    @property
    def num_cached(self) -> int:
        return len(self._cached)

    @property
    def num_used(self) -> int:
        return self.num_blocks - 1 - self.num_free

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return -(-int(num_tokens) // self.block_size)  # ceil div

    def can_allocate(self, num_blocks: int) -> bool:
        return num_blocks <= self.num_free

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    def stats(self) -> dict:
        return {"num_blocks": self.num_blocks, "block_size": self.block_size,
                "free": self.num_free, "used": self.num_used,
                "shared": sum(1 for c in self._refcount.values() if c > 1),
                "alloc_count": self.alloc_count, "free_count": self.free_count,
                "fork_count": self.fork_count, "cow_copies": self.cow_copies}

    # ------------------------------------------------------- alloc/free/fork
    def allocate(self, num_blocks: int) -> list:
        if num_blocks > len(self._free) + len(self._cached):
            raise AllocationError(
                f"requested {num_blocks} KV pages with {len(self._free)} free "
                f"+ {len(self._cached)} cached (pool {self.num_blocks - 1} "
                f"usable pages of {self.block_size} tokens)")
        out = []
        for _ in range(num_blocks):
            if self._free:
                b = self._free.popleft()
            else:
                # pressure: reclaim the least-recently-parked cached prefix
                b, key = self._cached.popitem(last=False)
                del self._cache_keys[b]
                self.cache_evictions += 1
                if self._evict_hook is not None:
                    self._evict_hook(b, key)
            self._refcount[b] = 1
            out.append(b)
        self.alloc_count += num_blocks
        return out

    def free(self, blocks) -> None:
        """Drop one reference per block. A last-reference page parks in the
        cached tier when registered, else returns to the free list. Order of
        return is the order given — deterministic for replay."""
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            c = self._refcount.get(b)
            if c is None:
                raise ValueError(f"double free of block {b}")
            if c == 1:
                del self._refcount[b]
                if b in self._cache_keys:
                    self._cached[b] = self._cache_keys[b]   # newest LRU slot
                    self.cached_count += 1
                else:
                    self._free.append(b)
                    self.free_count += 1
            else:
                self._refcount[b] = c - 1

    def fork(self, blocks) -> list:
        """Share a table: +1 ref on every page, returns a copy of the list.
        The forked table reads the same pages until a write forces CoW."""
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            if b not in self._refcount:
                raise ValueError(f"fork of unallocated block {b}")
            self._refcount[b] += 1
            self.fork_count += 1
        return list(blocks)

    def ensure_exclusive(self, block: int):
        """Make ``block`` writable by exactly one owner. Returns
        ``(new_block, (src, dst))`` when the page was shared and had to be
        copied (the caller mirrors the copy on-device), or ``(block, None)``
        when it was already exclusive."""
        c = self._refcount.get(block)
        if c is None:
            raise ValueError(f"ensure_exclusive of unallocated block {block}")
        if c == 1:
            return block, None
        fresh = self.allocate(1)[0]
        self._refcount[block] = c - 1
        self.cow_copies += 1
        return fresh, (block, fresh)

    # ------------------------------------------------------- warm restart
    _COUNTERS = ("alloc_count", "free_count", "fork_count", "cow_copies",
                 "cached_count", "cache_evictions", "cache_revivals")

    def state_dict(self) -> dict:
        """Full allocator bookkeeping as plain host data (lists of pairs, not
        dicts keyed by int — JSON round-trips must not stringify block ids).
        Cache keys serialize as chains via prefix_cache.key_to_chain. Free-list
        and cached-tier ORDER is part of the state: allocation determinism
        (and therefore byte-identical schedule replay after a warm restart)
        depends on it."""
        from .prefix_cache import key_to_chain
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "free": list(self._free),
            "refcount": [[b, c] for b, c in self._refcount.items()],
            "cache_keys": [[b, key_to_chain(k)]
                           for b, k in self._cache_keys.items()],
            "cached": [[b, key_to_chain(k)] for b, k in self._cached.items()],
            "counters": {k: getattr(self, k) for k in self._COUNTERS},
        }

    def load_state_dict(self, state: dict) -> None:
        from .prefix_cache import chain_to_key
        if (state["num_blocks"] != self.num_blocks
                or state["block_size"] != self.block_size):
            raise ValueError(
                f"allocator geometry mismatch: checkpoint has "
                f"{state['num_blocks']}x{state['block_size']}-token pages, "
                f"this pool is {self.num_blocks}x{self.block_size}")
        self._free = deque(int(b) for b in state["free"])
        self._refcount = {int(b): int(c) for b, c in state["refcount"]}
        self._cache_keys = {int(b): chain_to_key(ch)
                            for b, ch in state["cache_keys"]}
        self._cached = OrderedDict((int(b), chain_to_key(ch))
                                   for b, ch in state["cached"])
        for k in self._COUNTERS:
            setattr(self, k, int(state["counters"][k]))

    # ------------------------------------------------------------ cache tier
    def set_evict_hook(self, fn) -> None:
        """``fn(block, key)`` fires when a parked cached page is reclaimed by
        :meth:`allocate` — its device bytes are about to be overwritten, so
        the prefix cache must forget the key."""
        self._evict_hook = fn

    def register_cached(self, block: int, key) -> None:
        """Mark a live page as prefix-cache backed under ``key``: its last
        free parks it in the cached tier instead of the free list. Idempotent
        re-registration under the same key is a no-op; re-keying is a bug."""
        if block not in self._refcount:
            raise ValueError(f"register_cached of unallocated block {block}")
        old = self._cache_keys.get(block)
        if old is not None and old != key:
            raise ValueError(f"block {block} already cached under another key")
        self._cache_keys[block] = key

    def is_parked(self, block: int) -> bool:
        return block in self._cached

    def add_ref(self, block: int) -> None:
        """One more reference on a live page — a prefix hit mapping a shared
        block into a new table (same bookkeeping as a single-block fork)."""
        if block not in self._refcount:
            raise ValueError(f"add_ref of unallocated block {block}")
        self._refcount[block] += 1
        self.fork_count += 1

    def revive(self, block: int) -> None:
        """A prefix hit on a parked page: leave the cached tier, refcount 1.
        The page keeps its registration, so it re-parks on its next last
        free — that re-park lands at the newest LRU slot (the touch)."""
        if block not in self._cached:
            raise ValueError(f"revive of non-parked block {block}")
        del self._cached[block]
        self._refcount[block] = 1
        self.cache_revivals += 1
