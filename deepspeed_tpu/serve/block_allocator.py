"""Host-side block allocator for the paged KV cache.

The device holds one fixed pool of ``num_blocks`` pages per layer
(``[n_layer, num_blocks, block_size, n_head, head_dim]``, serve/paged.py);
this allocator hands out page indices. Pure host bookkeeping — allocation
never touches the device, so admission control is a free-list length check,
not an OOM recovery path.

Design points (vLLM's PagedAttention memory model):

- **Block 0 is reserved** as the null page: padded/inactive lanes of the
  fixed-shape programs route their writes there, and unallocated block-table
  entries point at it. It is never handed out, so a stray write can never
  corrupt a live sequence.
- **Free list is FIFO** (appendleft/pop would be LIFO; we pop from the left
  of a deque seeded in index order) — allocation order is deterministic for
  the byte-identical schedule-replay tests.
- **Refcounts + copy-on-write**: beam search forks a parent sequence's table
  by incrementing refcounts; a writer that needs an exclusive page calls
  :meth:`ensure_exclusive`, which returns the ``(src, dst)`` page copy the
  caller must mirror on-device (paged.copy_blocks) when the page was shared.
"""

from collections import deque

NULL_BLOCK = 0


class AllocationError(RuntimeError):
    """Out of KV pages (or a request can never fit) — admission refusal, not
    a crash: callers catch this and keep the request waiting or reject it."""


class BlockAllocator:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved null "
                             f"page), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = deque(range(1, self.num_blocks))   # block 0 reserved
        self._refcount = {}                              # block -> int (>0)
        # cumulative free-list traffic counters for the serving request-trace
        # pool timeline (monotonic; never reset)
        self.alloc_count = 0        # pages handed out
        self.free_count = 0         # pages returned to the free list
        self.fork_count = 0         # page references added by table forks
        self.cow_copies = 0         # shared pages copied by ensure_exclusive

    # ------------------------------------------------------------- queries
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return -(-int(num_tokens) // self.block_size)  # ceil div

    def can_allocate(self, num_blocks: int) -> bool:
        return num_blocks <= len(self._free)

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    def stats(self) -> dict:
        return {"num_blocks": self.num_blocks, "block_size": self.block_size,
                "free": self.num_free, "used": self.num_used,
                "shared": sum(1 for c in self._refcount.values() if c > 1),
                "alloc_count": self.alloc_count, "free_count": self.free_count,
                "fork_count": self.fork_count, "cow_copies": self.cow_copies}

    # ------------------------------------------------------- alloc/free/fork
    def allocate(self, num_blocks: int) -> list:
        if num_blocks > len(self._free):
            raise AllocationError(
                f"requested {num_blocks} KV pages with {len(self._free)} free "
                f"(pool {self.num_blocks - 1} usable pages of "
                f"{self.block_size} tokens)")
        out = [self._free.popleft() for _ in range(num_blocks)]
        for b in out:
            self._refcount[b] = 1
        self.alloc_count += num_blocks
        return out

    def free(self, blocks) -> None:
        """Drop one reference per block; pages return to the free list when
        their last reference goes. Order of return is the order given —
        deterministic for replay."""
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            c = self._refcount.get(b)
            if c is None:
                raise ValueError(f"double free of block {b}")
            if c == 1:
                del self._refcount[b]
                self._free.append(b)
                self.free_count += 1
            else:
                self._refcount[b] = c - 1

    def fork(self, blocks) -> list:
        """Share a table: +1 ref on every page, returns a copy of the list.
        The forked table reads the same pages until a write forces CoW."""
        for b in blocks:
            if b == NULL_BLOCK:
                continue
            if b not in self._refcount:
                raise ValueError(f"fork of unallocated block {b}")
            self._refcount[b] += 1
            self.fork_count += 1
        return list(blocks)

    def ensure_exclusive(self, block: int):
        """Make ``block`` writable by exactly one owner. Returns
        ``(new_block, (src, dst))`` when the page was shared and had to be
        copied (the caller mirrors the copy on-device), or ``(block, None)``
        when it was already exclusive."""
        c = self._refcount.get(block)
        if c is None:
            raise ValueError(f"ensure_exclusive of unallocated block {block}")
        if c == 1:
            return block, None
        fresh = self.allocate(1)[0]
        self._refcount[block] = c - 1
        self.cow_copies += 1
        return fresh, (block, fresh)
