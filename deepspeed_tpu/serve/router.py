"""Serving fleet router: prefix-affinity scheduling, load shedding, and warm
failover across N paged-serving replicas.

The single-engine serving stack (serve/engine.py) ends at one replica; this
module is the fleet-level front-end that spends every substrate piece built
for it:

- **prefix-affinity routing** (the SGLang cache-aware-routing insight): the
  router peeks each replica's prefix cache through the read-only
  ``InferenceEngine.prefix_peek`` hook — the exact chained content keys of
  serve/prefix_cache.py, no stats touched, no blocks revived — and routes an
  arrival to the replica with the longest cached prefix, falling back to
  least-loaded (queue depth with pool-headroom tiebreak). The
  ``affinity_weight`` knob trades cache reuse against load balance; weight 0
  is pure least-loaded, ``round_robin`` ignores both (the lint.sh gate's
  baseline policy).
- **admission control / load shedding**: a replica is ineligible when its
  waiting queue exceeds ``max_queue_depth`` or its pool occupancy exceeds
  ``occupancy_cap``; an arrival with no eligible replica is SHED — a
  RequestOutput with status "shed" and an EV_SHED record in the router's
  front-door request trace. Refusal, not a crash: overload degrades p99
  gracefully instead of collapsing goodput.
- **warm failover**: a crash-sim-style kill schedule removes replicas
  mid-flight. The victim drains through resilience/serve_restart —
  snapshot (quiesce parks every prefill frontier in the prefix cache),
  rebuild, restore — so requeued in-flight requests REMAP their prompt pages
  on the successor instead of re-prefilling. ``cold_failover=True`` rebuilds
  without the snapshot (the strictly-worse baseline the lint gate compares
  against). Each failover bills ``failover_cost`` synthetic seconds of
  ``restart_replay`` badput to that slot's goodput ledger.
- **fleet observability**: per-replica request-trace sketches fold through
  ``utils/cluster.fleet_latency_summary`` into exact fleet p50/p95/p99 every
  iteration (the PR 14 mergeable-sketch contract — bitwise-equal the
  single-stream percentiles over the concatenated ledger), speculation
  counters fold through ``fleet_serving_totals``, and per-slot goodput
  ledgers merge into one ``goodput_fleet`` block.

Determinism: the router steps every replica in lockstep on one iteration
clock (idle steps are host-cheap — no device call without lanes), routes by
exact integer counters, and the run transcript (``run`` returns it) is a
pure function of (requests, config, kill schedule) — byte-stable under
json.dumps, golden-compared in scripts/lint.sh.

Compile economics: replicas share one model/params object, so the paged
program set is built ONCE and shared through the build memo in
serve/paged.py; only replica 0 carries the telemetry session (a second
replica registering the same program signature would read as a recompile to
the compile watchdog).
"""

from collections import deque

from ..runtime.constants import (SERVING_FLEET_POLICIES,
                                 SERVING_FLEET_POLICY_AFFINITY,
                                 SERVING_FLEET_POLICY_ROUND_ROBIN)
from ..utils import logger
from .request_trace import LATENCY_METRICS, RequestTracer
from .scheduler import RequestOutput, unpack_request

FLEET_TRANSCRIPT_VERSION = 1
FLEET_TRANSCRIPT_KIND = "serve_fleet_transcript"
SHED_REASON = "fleet_saturated"

_GUARD_ITERS = 200000


class FleetRouter:
    """Deterministic front-end owning N ``InferenceEngine`` replicas.

    ``engines``            the replica list (index = slot id; a failed-over
                           replacement takes its victim's slot).
    ``policy``             "affinity" | "least_loaded" | "round_robin".
    ``affinity_weight``    cached-prefix blocks are worth this many queue
                           slots in the routing score (affinity policy only).
    ``max_queue_depth``    per-replica waiting-queue bound (0 = unbounded).
    ``occupancy_cap``      per-replica pool-occupancy admission cap in
                           (0, 1]; 1.0 disables occupancy shedding.
    ``kill_schedule``      iterable of ``(it, slot)`` — kill that slot's
                           replica when the router clock reaches ``it``.
    ``build_replacement``  ``slot -> InferenceEngine`` factory for failover
                           (must share the fleet's model/params object and
                           pass ``telemetry=None`` — see module docstring).
    ``snapshot_dir``       where warm-failover snapshots commit.
    ``failover_cost``      synthetic restart_replay seconds billed per kill.
    ``cold_failover``      rebuild without the snapshot (baseline mode).
    ``telemetry``          optional TelemetrySession for Serving/Fleet/*
                           scalars (replica 0's session in serve-sim).
    ``tracer``             front-door RequestTracer for shed records; one is
                           created (host_id = fleet size) when omitted.
    """

    def __init__(self, engines, *, policy=SERVING_FLEET_POLICY_AFFINITY,
                 affinity_weight=1.0, max_queue_depth=0, occupancy_cap=1.0,
                 kill_schedule=None, build_replacement=None,
                 snapshot_dir=None, failover_cost=4.0, cold_failover=False,
                 telemetry=None, tracer=None, run_id="fleet"):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if policy not in SERVING_FLEET_POLICIES:
            raise ValueError(f"fleet policy must be one of "
                             f"{SERVING_FLEET_POLICIES}, got {policy!r}")
        self.engines = list(engines)
        self.policy = policy
        self.affinity_weight = float(affinity_weight)
        self.max_queue_depth = int(max_queue_depth)
        self.occupancy_cap = float(occupancy_cap)
        self.build_replacement = build_replacement
        self.snapshot_dir = snapshot_dir
        self.failover_cost = float(failover_cost)
        self.cold_failover = bool(cold_failover)
        self.telemetry = telemetry
        self.run_id = run_id
        self.tracer = tracer if tracer is not None else RequestTracer(
            capacity=1024, host_id=len(self.engines))
        # kill schedule: it -> [slots], applied once when the clock arrives
        self._kills = {}
        for it, slot in (kill_schedule or ()):
            self._kills.setdefault(int(it), []).append(int(slot))
        self.kills_applied = 0
        self._rr = 0                     # round-robin cursor
        self._it = 0
        self._order = []                 # req_id in routing order
        self.outputs = {}                # req_id -> RequestOutput
        self.shed_count = 0
        self.finished_count = 0
        self.refused_count = 0
        self.prefill_chunks = [0] * len(self.engines)   # per slot, survives
        self._retired = []               # full bundles of killed replicas
        self.last_fleet_latency = {}
        # per-slot goodput ledgers on a synthetic clock: 1.0s per stepped
        # iteration, failover_cost s per kill — pure function of the
        # schedule, so the merged fraction is golden-able
        from ..utils.goodput import RunLedger
        self._cells = [[0.0] for _ in self.engines]
        self._ledgers = [
            RunLedger(run_id=self.run_id, host=slot,
                      clock=(lambda c=cell: c[0]), wall=lambda: 0.0)
            for slot, cell in enumerate(self._cells)]

    # ------------------------------------------------------------- routing
    def _eligible(self, slot, view):
        if self.max_queue_depth and view["waiting"] >= self.max_queue_depth:
            return False
        if self.occupancy_cap < 1.0:
            used = 1.0 - view["free_blocks"] / max(view["num_blocks"], 1)
            if used >= self.occupancy_cap:
                return False
        return True

    def route(self, req):
        """Pick a replica slot for ``req`` (None = shed). Exact integer/
        rational scoring, deterministic tie-break toward the lowest slot."""
        views = [eng.load_view() for eng in self.engines]
        elig = [s for s in range(len(self.engines))
                if self._eligible(s, views[s])]
        if not elig:
            return None, 0
        if self.policy == SERVING_FLEET_POLICY_ROUND_ROBIN:
            slot = elig[self._rr % len(elig)]
            self._rr += 1
            return slot, 0
        w = (self.affinity_weight
             if self.policy == SERVING_FLEET_POLICY_AFFINITY else 0.0)
        hits = {s: self.engines[s].prefix_peek(req.prompt)[0] for s in elig}
        best, best_key = None, None
        for s in elig:
            v = views[s]
            load = (v["waiting"] + v["running"]
                    - v["free_blocks"] / max(v["num_blocks"], 1))
            key = (w * hits[s] - load, -s)
            if best_key is None or key > best_key:
                best, best_key = s, key
        return best, hits[best]

    def _submit(self, req, slot):
        self._order.append(req.req_id)
        out = self.engines[slot].submit(req)
        if out is not None:                     # engine-level refusal
            self.outputs[req.req_id] = out
            self.refused_count += 1

    def _shed(self, req):
        self._order.append(req.req_id)
        self.tracer.on_shed(req, SHED_REASON)
        self.outputs[req.req_id] = RequestOutput(req.req_id, "shed",
                                                 refusal=SHED_REASON)
        self.shed_count += 1

    # ------------------------------------------------------------ failover
    def _kill(self, slot):
        """Replace ``engines[slot]`` mid-flight. Warm: drain through the
        serve_restart snapshot (in-flight requests remap their prefix pages
        on the successor). Cold: rebuild and re-submit the quiesced waiting
        queue — every requeued prompt re-prefills from scratch."""
        if self.build_replacement is None:
            raise RuntimeError("kill schedule requires a build_replacement "
                               "factory")
        victim = self.engines[slot]
        if victim.tracer is not None:
            self._retired.append(victim.tracer.bundle())
        mode = "cold" if self.cold_failover else "warm"
        if self.cold_failover:
            state = victim.state_dict()          # quiesces the victim
            replacement = self.build_replacement(slot)
            replacement.fast_forward(self._it)
            for packed, _idx in state["scheduler"]["waiting"]:
                replacement.submit(unpack_request(packed))
        else:
            if self.snapshot_dir is None:
                raise RuntimeError("warm failover requires snapshot_dir")
            from ..resilience.serve_restart import failover_server
            replacement = failover_server(
                victim, lambda: self.build_replacement(slot),
                self.snapshot_dir, tag=f"fleet_r{slot}_it{self._it}")
        self.engines[slot] = replacement
        self._cells[slot][0] += self.failover_cost
        self._ledgers[slot].close("restart_replay")
        self.kills_applied += 1
        logger.info(f"[deepspeed_tpu] fleet: replica {slot} killed at "
                    f"it={self._it}, {mode} failover "
                    f"({len(replacement.scheduler.waiting)} requests "
                    f"requeued)")
        return mode

    # --------------------------------------------------------- observability
    def _live_sketch_bundles(self):
        out = []
        for eng in self.engines:
            tr = eng.tracer
            if tr is None:
                continue
            out.append({"latency_sketches": {
                m: tr.hist[m].to_dict() for m in LATENCY_METRICS
                if tr.hist[m].count}})
        out.extend(self._retired)
        out.append({"latency_sketches": {
            m: self.tracer.hist[m].to_dict() for m in LATENCY_METRICS
            if self.tracer.hist[m].count}})
        return out

    def bundles(self):
        """Every request-trace bundle the fleet produced: live replicas,
        retired (killed) replicas, and the router's front door — the operand
        of the fleet merge AND of the end-of-run exactness assertion."""
        live = [eng.tracer.bundle() for eng in self.engines
                if eng.tracer is not None]
        return live + list(self._retired) + [self.tracer.bundle()]

    def goodput_summaries(self):
        return {slot: led.finalize(persist=False)
                for slot, led in enumerate(self._ledgers)}

    def fleet_goodput(self):
        from ..utils.goodput import fleet_goodput
        return fleet_goodput(self.goodput_summaries())

    def fleet_summary(self, ps=(50, 95, 99)):
        """End-of-run fleet rollup: exact merged percentiles, summed serving
        totals (speculation counters included), merged goodput."""
        from ..utils.cluster import fleet_latency_summary, fleet_serving_totals
        bundles = self.bundles()
        return {
            "replicas": len(self.engines),
            "policy": self.policy,
            "latency": fleet_latency_summary(bundles, ps=ps),
            "serving": fleet_serving_totals(bundles),
            "goodput_fleet": self.fleet_goodput(),
            "prefill_chunks": list(self.prefill_chunks),
            "total_prefill_chunks": sum(self.prefill_chunks),
            "finished": self.finished_count,
            "refused": self.refused_count,
            "shed": self.shed_count,
            "kills": self.kills_applied,
        }

    def _fleet_scalar(self, name, value):
        if self.telemetry is not None:
            self.telemetry.monitor.add_scalar(f"Serving/Fleet/{name}",
                                              float(value), self._it)

    def _emit_fleet_scalars(self):
        from ..utils.cluster import fleet_latency_summary, fleet_serving_totals
        self.last_fleet_latency = fleet_latency_summary(
            self._live_sketch_bundles(), ps=(50, 95, 99))
        if self.telemetry is None:
            return
        for k, v in self.last_fleet_latency.items():
            self._fleet_scalar(f"Latency/{k}", v)
        views = [eng.load_view() for eng in self.engines]
        self._fleet_scalar("waiting", sum(v["waiting"] for v in views))
        self._fleet_scalar("running", sum(v["running"] for v in views))
        self._fleet_scalar("free_blocks",
                           sum(v["free_blocks"] for v in views))
        self._fleet_scalar("shed", self.shed_count)
        self._fleet_scalar("finished", self.finished_count)
        spec = fleet_serving_totals(
            [{"totals": dict(eng.tracer.totals)} for eng in self.engines
             if eng.tracer is not None] + self._retired)["totals"]
        for k in ("drafted_tokens", "accepted_draft_tokens",
                  "wasted_draft_tokens"):
            self._fleet_scalar(f"Spec/{k}", spec.get(k, 0))
        productive = sum(led.class_seconds["productive_step"]
                         for led in self._ledgers)
        accounted = sum(led.accounted_seconds() for led in self._ledgers)
        self._fleet_scalar("Goodput/fraction",
                           productive / accounted if accounted else 0.0)

    # ------------------------------------------------------------- the loop
    def run(self, requests):
        """Route and drive everything to completion in lockstep. Returns
        ``(outputs in arrival order, transcript)`` — the transcript is the
        byte-stable iteration-domain record lint.sh golden-compares."""
        pending = deque(sorted(enumerate(requests),
                               key=lambda e: (e[1].arrival, e[0])))
        iterations = []
        guard = 0
        while pending or any(not e.scheduler.idle for e in self.engines):
            it = self._it
            entry = {"it": it, "routed": [], "shed": [], "kills": []}
            for slot in self._kills.pop(it, ()):
                entry["kills"].append([slot, self._kill(slot)])
            while pending and pending[0][1].arrival <= it:
                _, req = pending.popleft()
                slot, hit_blocks = self.route(req)
                if slot is None:
                    self._shed(req)
                    entry["shed"].append([req.req_id, SHED_REASON])
                else:
                    self._submit(req, slot)
                    entry["routed"].append([req.req_id, slot,
                                            int(hit_blocks)])
            for slot, eng in enumerate(self.engines):
                log = eng.step()
                if log["prefill"] is not None:
                    self.prefill_chunks[slot] += 1
                for rid in log["finished"]:
                    self.outputs[rid] = eng.outputs[rid]
                    self.finished_count += 1
                self._cells[slot][0] += 1.0
                self._ledgers[slot].close_step(it)
            self._emit_fleet_scalars()
            if entry["routed"] or entry["shed"] or entry["kills"]:
                iterations.append(entry)
            self._it += 1
            # fast-forward a fully idle fleet to the next event (arrival or
            # scheduled kill) — the synthetic goodput clock only advances on
            # stepped iterations, so skipped idle gaps bill nothing
            if (pending and all(e.scheduler.idle for e in self.engines)
                    and not any(k >= self._it for k in self._kills)):
                nxt = max(int(pending[0][1].arrival), self._it)
                if nxt > self._it:
                    self._it = nxt
                    for eng in self.engines:
                        eng.fast_forward(nxt)
            guard += 1
            if guard > _GUARD_ITERS:
                raise RuntimeError("fleet loop failed to drain (bug)")
        missing = [rid for rid in self._order if rid not in self.outputs]
        if missing:
            raise RuntimeError(
                f"fleet conservation violated: {len(missing)} requests "
                f"lost (neither finished, refused, nor shed): "
                f"{missing[:8]}")
        transcript = self._transcript(iterations)
        return [self.outputs[rid] for rid in self._order], transcript

    def _transcript(self, iterations):
        return {
            "version": FLEET_TRANSCRIPT_VERSION,
            "kind": FLEET_TRANSCRIPT_KIND,
            "fleet": {
                "replicas": len(self.engines),
                "policy": self.policy,
                "affinity_weight": self.affinity_weight,
                "max_queue_depth": self.max_queue_depth,
                "occupancy_cap": self.occupancy_cap,
            },
            "iterations": iterations,
            "totals": {
                "prefill_chunks": list(self.prefill_chunks),
                "finished": self.finished_count,
                "refused": self.refused_count,
                "shed": self.shed_count,
                "kills": self.kills_applied,
                "goodput_fleet_fraction":
                    self.fleet_goodput()["goodput_fraction"],
            },
        }
