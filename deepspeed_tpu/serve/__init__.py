"""TPU-native serving engine: block-paged KV cache + continuous batching.

The reference DeepSpeed 0.3.0 ships no inference engine; this package is the
serving layer the ROADMAP's "millions of users" north star needs. Three parts:

- :mod:`block_allocator` — host-side free-list allocator over a fixed HBM pool
  of KV pages, with per-sequence block tables and refcounted copy-on-write
  forks for beam search (vLLM's PagedAttention memory model, SOSP '23);
- :mod:`paged` + :mod:`scheduler` — fixed-shape paged decode/prefill programs
  (one compile each, ever) and an iteration-granular continuous-batching
  scheduler with chunked prefill interleaved into in-flight decodes (Orca,
  OSDI '22);
- :mod:`engine` — the ``deepspeed_tpu.init_inference``-shaped facade wrapping
  models/gpt2.py, config block ``"serving"``, telemetry Serving/* scalars.

``serve/oracle.py`` holds the dense-cache mirror programs the equivalence
tests and ``ds-tpu serve-sim`` bit-compare the paged path against.
"""

from .block_allocator import AllocationError, BlockAllocator
from .engine import InferenceEngine
from .scheduler import Request, RequestOutput, Scheduler

__all__ = ["AllocationError", "BlockAllocator", "InferenceEngine", "Request",
           "RequestOutput", "Scheduler"]
