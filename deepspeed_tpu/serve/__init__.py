"""TPU-native serving engine: block-paged KV cache + continuous batching.

The reference DeepSpeed 0.3.0 ships no inference engine; this package is the
serving layer the ROADMAP's "millions of users" north star needs. Three parts:

- :mod:`block_allocator` — host-side free-list allocator over a fixed HBM pool
  of KV pages, with per-sequence block tables and refcounted copy-on-write
  forks for beam search (vLLM's PagedAttention memory model, SOSP '23);
- :mod:`paged` + :mod:`scheduler` — fixed-shape paged decode/prefill programs
  (one compile each, ever) and an iteration-granular continuous-batching
  scheduler with chunked prefill interleaved into in-flight decodes (Orca,
  OSDI '22);
- :mod:`engine` — the ``deepspeed_tpu.init_inference``-shaped facade wrapping
  models/gpt2.py, config block ``"serving"``, telemetry Serving/* scalars;
- :mod:`request_trace` — the serving observatory: per-request lifecycle
  ledger, latency percentiles, preemption-waste accounting, SLO
  classification, ``ds-tpu serve-timeline`` Perfetto export (config block
  ``"serving": {"request_trace": ...}``).

``serve/oracle.py`` holds the dense-cache mirror programs the equivalence
tests and ``ds-tpu serve-sim`` bit-compare the paged path against.
"""

# Lazy exports (PEP 562): `ds-tpu serve-timeline` dispatches into
# serve/request_trace.py on machines with no accelerator runtime (post-mortem
# boxes), so importing this package must not pull in the engine's jax stack.
_EXPORTS = {
    "AllocationError": ".block_allocator",
    "BlockAllocator": ".block_allocator",
    "FleetRouter": ".router",
    "InferenceEngine": ".engine",
    "Request": ".scheduler",
    "RequestOutput": ".scheduler",
    "RequestTracer": ".request_trace",
    "Scheduler": ".scheduler",
    "StreamingHistogram": ".request_trace",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        from importlib import import_module
        val = getattr(import_module(_EXPORTS[name], __name__), name)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
