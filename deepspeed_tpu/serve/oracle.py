"""Dense-cache mirror of the paged serving programs — the bit-exactness oracle.

The paged programs (serve/paged.py) claim to be the dense cached-forward math
with only the *memory layout* changed. This module is the referee: the same
slot-shaped programs over plain contiguous per-slot caches
``[n_layer, num_slots + 1, n_head, max_len, head_dim]`` (the +1 row is the
null slot padded lanes write to — the dense analog of the null page), with no
block tables, no pools, no paging. ``ds-tpu serve-sim`` and the equivalence
tests run it in lockstep with the engine and assert the logits are **bitwise
identical** every iteration; any divergence means the paging machinery
(allocator, tables, scatter/gather, copy-on-write) changed the numbers.

Why a mirror rather than ``model.generate`` directly: XLA's CPU gemm is not
batch-size independent in the last ulp, so the oracle must issue dots at the
SAME shapes as the engine ([num_slots, 1, H] decode rows, [1, chunk, H]
prefill rows). The aligned-batch test in tests/unit/test_paged_attention.py
closes the remaining gap by driving ``_build_cached_forward`` itself at
matching shapes.
"""

import jax
import jax.numpy as jnp


def build_oracle_programs(model, *, num_slots, max_len, prefill_chunk):
    """``decode_step(p, toks, pos, active, kcs, vcs)`` and
    ``prefill_chunk(p, toks, pos, n_valid, slot, kcs, vcs)`` over dense
    per-slot caches, plus ``reorder(kcs, vcs, perm)`` (the beam-search cache
    shuffle the paged path does with table forks)."""
    c = model.config
    nh, hd = c.n_head, c.head_dim
    S, ML, C = int(num_slots), int(max_len), int(prefill_chunk)
    cd = c.compute_dtype
    eps = c.layer_norm_epsilon
    import math as _math

    def _qkv(x, bp):
        B_, Tn, _ = x.shape
        qkv = jnp.dot(x, bp["c_attn_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) \
            + bp["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        return q, k, v

    def _proj(y, bp, x_dtype):
        return (jnp.dot(y, bp["c_proj_w"].astype(x_dtype),
                        preferred_element_type=jnp.float32).astype(x_dtype)
                + bp["c_proj_b"].astype(x_dtype))

    def _attend(q, kg, vg, mask, x_dtype):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kg,
                       preferred_element_type=jnp.float32) / _math.sqrt(hd)
        s = jnp.where(mask, s, jnp.float32(-1e9))
        p = jax.nn.softmax(s, axis=-1).astype(x_dtype)
        y = jnp.einsum("bhqk,bhkd->bhqd", p, vg,
                       preferred_element_type=jnp.float32).astype(x_dtype)
        B_, _, Tn, _ = y.shape
        return y.transpose(0, 2, 1, 3).reshape(B_, Tn, nh * hd)

    def _blocks_forward(p, x, attn_fn):
        for li, bp in enumerate(p["blocks"]):
            a = attn_fn(model._layer_norm(x, bp["ln_1"], eps), bp["attn"], li)
            x = x + a
            h = model._layer_norm(x, bp["ln_2"], eps)
            x = x + model._mlp(h, bp["mlp"])
        return model._layer_norm(x, p["ln_f"], eps)

    def _logits(row, p):
        return jnp.einsum("bh,vh->bv", row, p["wte"].astype(row.dtype),
                          preferred_element_type=jnp.float32)

    hh = jnp.arange(nh)

    def decode_step(p, toks, pos, active, kcs, vcs):
        caches = {"k": kcs, "v": vcs}
        x = p["wte"][toks[:, None]].astype(cd) \
            + p["wpe"][pos[:, None]].astype(cd)
        wslot = jnp.where(active, jnp.arange(S), S)      # pads -> null slot
        pc = jnp.minimum(pos, ML - 1)

        def attn(xin, bp, li):
            q, k, v = _qkv(xin, bp)                      # [S, nh, 1, hd]
            caches["k"] = caches["k"].at[
                li, wslot[:, None], hh[None, :], pc[:, None]].set(
                k[:, :, 0, :].astype(caches["k"].dtype))
            caches["v"] = caches["v"].at[
                li, wslot[:, None], hh[None, :], pc[:, None]].set(
                v[:, :, 0, :].astype(caches["v"].dtype))
            kg = caches["k"][li, :S]                     # [S, nh, ML, hd]
            vg = caches["v"][li, :S]
            mask = (jnp.arange(ML)[None, :] <= pos[:, None])[:, None, None, :]
            return _proj(_attend(q, kg, vg, mask, xin.dtype), bp, xin.dtype)

        x = _blocks_forward(p, x, attn)
        return _logits(x[:, -1], p), caches["k"], caches["v"]

    def prefill_chunk_fn(p, toks, pos, n_valid, slot, kcs, vcs):
        caches = {"k": kcs, "v": vcs}
        wpe_cap = p["wpe"].shape[0] - 1
        tp = pos + jnp.arange(C)
        positions = jnp.minimum(tp, wpe_cap)
        x = p["wte"][toks].astype(cd) + p["wpe"][positions][None].astype(cd)
        valid = jnp.arange(C) < n_valid
        wslot = jnp.where(valid, slot, S)
        pc = jnp.minimum(tp, ML - 1)

        def attn(xin, bp, li):
            q, k, v = _qkv(xin, bp)                      # [1, nh, C, hd]
            caches["k"] = caches["k"].at[
                li, wslot[:, None], hh[None, :], pc[:, None]].set(
                k[0].transpose(1, 0, 2).astype(caches["k"].dtype))
            caches["v"] = caches["v"].at[
                li, wslot[:, None], hh[None, :], pc[:, None]].set(
                v[0].transpose(1, 0, 2).astype(caches["v"].dtype))
            kg = jax.lax.dynamic_slice_in_dim(caches["k"][li], slot, 1, axis=0)
            vg = jax.lax.dynamic_slice_in_dim(caches["v"][li], slot, 1, axis=0)
            mask = jnp.arange(ML)[None, :] <= tp[:, None]
            return _proj(_attend(q, kg, vg, mask, xin.dtype), bp, xin.dtype)

        x = _blocks_forward(p, x, attn)
        last = jax.lax.dynamic_slice(x, (0, n_valid - 1, 0),
                                     (1, 1, x.shape[-1]))[:, 0]
        return _logits(last, p), caches["k"], caches["v"]

    def reorder(kcs, vcs, perm):
        """Slot permutation/duplication [S] — the dense analog of beam-search
        block-table forking: new slot s takes old slot perm[s]'s cache.
        Identity entries keep non-beam slots untouched."""
        return kcs.at[:, :S].set(kcs[:, perm]), vcs.at[:, :S].set(vcs[:, perm])

    def fresh_caches():
        shape = (c.n_layer, S + 1, nh, ML, hd)
        return jnp.zeros(shape, cd), jnp.zeros(shape, cd)

    return {
        "decode_step": jax.jit(decode_step, donate_argnums=(4, 5)),
        "prefill_chunk": jax.jit(prefill_chunk_fn, donate_argnums=(5, 6)),
        "reorder": jax.jit(reorder, donate_argnums=(0, 1)),
        "fresh_caches": fresh_caches,
    }
