"""Serving request observatory: per-request lifecycle ledger, latency
percentiles, preemption-waste accounting, Perfetto timelines, SLO gate.

The serving engine's iteration loop crosses every interesting request-lifecycle
boundary on the host anyway — admission, each prefill chunk, each decode
iteration's batch membership, preemption, beam fork, first token, completion.
``RequestTracer`` records exactly those boundaries (plus one ``perf_counter``
read each) into a bounded per-host ring, mirroring the pipeline schedule
observatory's design (utils/pipeline_trace.py): no device fetch, no barrier,
no added HLO — with ``serving.request_trace`` disabled the engine holds
``None`` instead of a tracer, and even enabled the module contains zero
blocking primitives (pinned by the lint HostSyncPass through
tests/unit/test_no_sync_guard.py and ``ds-tpu lint``).

Four consumers sit on the ledger:

* **Latency percentiles** — streaming log-bucketed histograms for TTFT, TPOT,
  queue delay and end-to-end latency; ``percentiles()`` reads p50/p90/p99 (or
  any requested set) and ``latency_summary()`` flows through
  ``TelemetrySession.end_step`` as ``Serving/Latency/*`` scalars.
* **Waste accounting** — every scheduled token is classified useful vs
  replayed-after-preemption (the scheduler knows exactly which prefill
  positions and decode steps recompute work a preempted attempt already did);
  the split sums to total scheduled tokens exactly, plus a per-iteration
  block-pool occupancy / fragmentation / free-list timeline.
* **SLO accounting** — finished requests are classified met/violated against
  ``serving.request_trace.slo`` (``ttft_ms`` / ``tpot_ms``) and ``ds-tpu
  serve-sim`` gates on attainment.
* **Perfetto export** — ``to_serve_trace_events`` / ``serve_timeline_main``
  convert a ledger bundle (or a flight-recorder dump embedding one) into
  deterministic Chrome ``trace_event`` JSON: one track per request, queue /
  prefill / decode / replay slices on the iteration timebase, counter tracks
  for pool occupancy, waiting queue and waste fraction. ``bin/ds-tpu
  serve-timeline`` dispatches here (docs/serving.md).
"""

import argparse
import atexit
import json
import math
import os
import time
from collections import deque

from ..utils.trace_event import (complete_slice, counter_event, instant_event,
                                 load_bundle, process_name_event,
                                 serialize_trace, thread_meta_events,
                                 trace_envelope)

REQUEST_TRACE_VERSION = 1
SERVE_TRACE_KIND = "serving_request_trace"

# the wall-clock latency metrics the tracer keeps streaming histograms for
LATENCY_METRICS = ("ttft_ms", "tpot_ms", "queue_delay_ms", "e2e_ms")
# SLO-gateable subset (serving.request_trace.slo config keys)
SLO_METRICS = ("ttft_ms", "tpot_ms")

# lifecycle event names; every event is a compact list
# [name, iteration, rel_us, *args] (iteration -1 = outside the step loop)
EV_SUBMIT = "submit"
EV_REFUSED = "refused"          # args: reason
EV_SHED = "shed"                # args: reason (fleet router load shedding)
EV_ADMIT = "admit"              # args: lanes, queue_delay_iters
EV_CACHE_HIT = "cache_hit"      # args: cached_prefix_tokens (prefix reuse)
EV_PREFILL = "prefill"          # args: pos, n, replayed
EV_DECODE = "decode"            # args: lanes, replayed
EV_SPEC_ACCEPT = "spec_accept"  # args: drafted, accepted, committed
EV_SPEC_REJECT = "spec_reject"  # args: drafted, accepted, committed (a == 0)
EV_FORK = "fork"                # args: lanes (beam CoW table fork)
EV_PREEMPT = "preempt"          # args: evicted_blocks
EV_FIRST_TOKEN = "first_token"
EV_FINISH = "finish"            # args: n_tokens


class HistogramSketch:
    """Log-bucketed streaming histogram sketch: O(1) add, bounded memory,
    percentile read-out at ``growth``-factor relative resolution (default
    ~3%). Quantiles report the upper bound of the covering bucket, so they
    never understate a tail — the conservative direction for an SLO read-out.

    The bin edges are a pure function of ``(growth, min_value)``, identical
    on every replica, so sketches are EXACTLY mergeable: ``merge_from`` adds
    integer bin counts, and the merged percentiles equal the percentiles of
    the concatenated value stream (same covering-bucket read-out over the
    same total bin counts). ``utils/cluster.fleet_latency_summary`` builds
    fleet-level rollups on this property."""

    def __init__(self, growth=1.03, min_value=1e-3):
        self._min = float(min_value)
        self._lg = math.log(float(growth))
        self._growth = float(growth)
        self._buckets = {}
        self.count = 0
        self.total = 0.0

    def add(self, value):
        if value is None:
            return
        v = max(float(value), self._min)
        idx = int(math.log(v / self._min) / self._lg)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1
        self.count += 1
        self.total += float(value)

    def percentile(self, p):
        """Value at percentile ``p`` (0..100], or None on an empty histogram."""
        if not self.count:
            return None
        target = max(float(p) / 100.0 * self.count, 1.0)
        seen = 0
        last = None
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            last = idx
            if seen >= target:
                break
        return self._min * self._growth ** (last + 1)

    def percentiles(self, ps=(50, 90, 99)):
        return {f"p{p:g}": self.percentile(p) for p in ps}

    @property
    def mean(self):
        return (self.total / self.count) if self.count else None

    # -- merge / serialization (fleet rollups) ------------------------------
    def merge_from(self, other):
        """Fold another sketch into this one. Exact: bin geometry must match
        (raises ValueError otherwise), then merging is bin-count addition."""
        if (other._min, other._growth) != (self._min, self._growth):
            raise ValueError(
                "histogram sketch geometry mismatch: "
                f"(min={other._min}, growth={other._growth}) vs "
                f"(min={self._min}, growth={self._growth})")
        for idx, n in other._buckets.items():
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        return self

    def to_dict(self):
        return {
            "kind": "histogram_sketch",
            "growth": self._growth,
            "min_value": self._min,
            "buckets": {str(i): self._buckets[i]
                        for i in sorted(self._buckets)},
            "count": self.count,
            "total": self.total,
        }

    @classmethod
    def from_dict(cls, d):
        sk = cls(growth=d.get("growth", 1.03),
                 min_value=d.get("min_value", 1e-3))
        for i, n in (d.get("buckets") or {}).items():
            sk._buckets[int(i)] = int(n)
        sk.count = int(d.get("count", sum(sk._buckets.values())))
        sk.total = float(d.get("total", 0.0))
        return sk

    @classmethod
    def merged(cls, sketches):
        out = None
        for sk in sketches:
            if out is None:
                out = cls(growth=sk._growth, min_value=sk._min)
            out.merge_from(sk)
        return out


# Historical name — the sketch started life as a per-host-only histogram.
StreamingHistogram = HistogramSketch


class RequestTracer:
    """Bounded per-host ledger of per-request lifecycle events plus the
    per-iteration goodput/pool timeline. Only stdlib calls on the hot path:
    one ``perf_counter`` read and a list append per recorded boundary."""

    def __init__(self, capacity=256, iteration_capacity=4096, dump_dir=None,
                 slo=None, host_id=0):
        self.capacity = int(capacity)
        self.iteration_capacity = int(iteration_capacity)
        self.dump_dir = dump_dir or None
        self.host_id = int(host_id)
        # configured SLO thresholds; 0 / missing = that metric is not gated
        slo = slo or {}
        self.slo = {m: float(slo[m]) for m in SLO_METRICS
                    if slo.get(m) and float(slo[m]) > 0.0}
        self.requests = deque(maxlen=self.capacity)   # finished/refused records
        self.live = {}                                # req_id -> open record
        self.iterations = deque(maxlen=self.iteration_capacity)
        self.hist = {m: StreamingHistogram() for m in LATENCY_METRICS}
        self.totals = {"prefill_tokens": 0, "prefill_replayed": 0,
                       "decode_tokens": 0, "decode_replayed": 0,
                       "cached_prefix_tokens": 0, "drafted_tokens": 0,
                       "accepted_draft_tokens": 0, "wasted_draft_tokens": 0}
        self.slo_met = 0
        self.slo_violated = 0
        self.refused = 0
        self.shed = 0
        self.finished = 0
        self.preemptions = 0
        self._epoch = time.perf_counter()
        self._cur = None                              # open iteration record
        if self.dump_dir:
            atexit.register(self._atexit_dump)

    # -- plumbing ----------------------------------------------------------
    def _now_us(self):
        return int((time.perf_counter() - self._epoch) * 1e6)

    def _event(self, rec, name, it, *args):
        rec["events"].append([name, int(it), self._now_us()] + list(args))

    # -- request lifecycle -------------------------------------------------
    def on_submit(self, req):
        rec = {
            "req_id": req.req_id,
            "arrival": int(req.arrival),
            "lanes": int(req.num_beams),
            "prompt_len": len(req.prompt),
            "max_new_tokens": int(req.max_new_tokens),
            "status": "live",
            "preemptions": 0,
            "events": [],
        }
        self.live[req.req_id] = rec
        self._event(rec, EV_SUBMIT, -1)
        return rec

    def on_refused(self, req, reason):
        rec = self.live.pop(req.req_id, None) or self.on_submit(req)
        self.live.pop(req.req_id, None)
        self._event(rec, EV_REFUSED, -1, reason)
        rec["status"] = "refused"
        self.refused += 1
        self.requests.append(rec)
        return rec

    def on_shed(self, req, reason):
        # fleet-router admission control: same refusal-not-crash ledger shape
        # as on_refused, but counted separately — shedding is a routing-policy
        # outcome (fleet saturated), not an engine capacity error
        rec = self.live.pop(req.req_id, None) or self.on_submit(req)
        self.live.pop(req.req_id, None)
        self._event(rec, EV_SHED, -1, reason)
        rec["status"] = "shed"
        self.shed += 1
        self.requests.append(rec)
        return rec

    def on_admit(self, g, it):
        rec = self.live.get(g.req.req_id)
        if rec is None:
            return
        self._event(rec, EV_ADMIT, it, g.lanes, int(it) - rec["arrival"])
        cached = int(getattr(g, "cached_prefix_tokens", 0))
        if cached:
            # prefix-cache reuse: these prompt tokens are never scheduled, so
            # they enter neither the useful nor the replayed side of the
            # waste split — a preempt-restart's remapped prefix must not be
            # billed as recomputation (that is the whole point of the remap)
            self._event(rec, EV_CACHE_HIT, it, cached)
            rec["cached_prefix_tokens"] = (
                rec.get("cached_prefix_tokens", 0) + cached)
            self.totals["cached_prefix_tokens"] += cached

    def on_prefill(self, g, it, pos, n, replayed):
        rec = self.live.get(g.req.req_id)
        if rec is None:
            return
        self._event(rec, EV_PREFILL, it, int(pos), int(n), int(replayed))
        if self._cur is not None:
            self._cur["prefill"][0] += int(n) - int(replayed)
            self._cur["prefill"][1] += int(replayed)
        self.totals["prefill_tokens"] += int(n)
        self.totals["prefill_replayed"] += int(replayed)

    def on_decode(self, g, it, lanes, replayed):
        rec = self.live.get(g.req.req_id)
        if rec is not None:
            self._event(rec, EV_DECODE, it, int(lanes), int(replayed))
        if self._cur is not None:
            self._cur["decode"][0] += int(lanes) - int(replayed)
            self._cur["decode"][1] += int(replayed)
        self.totals["decode_tokens"] += int(lanes)
        self.totals["decode_replayed"] += int(replayed)

    def on_spec(self, g, it, drafted, accepted, committed, replayed):
        """One speculative round for one request. The ``committed`` tokens
        enter the decode side of the useful+replayed == scheduled identity
        (they ARE the tokens plain decode would have scheduled); the draft
        economics — drafted / accepted / wasted — live OUTSIDE the identity,
        like ``cached_prefix_tokens``: draft-model work is not target-model
        schedule, and billing it there would misread speculation as waste."""
        rec = self.live.get(g.req.req_id)
        if rec is not None:
            name = EV_SPEC_ACCEPT if accepted else EV_SPEC_REJECT
            self._event(rec, name, it, int(drafted), int(accepted),
                        int(committed))
            rec["drafted_tokens"] = (
                rec.get("drafted_tokens", 0) + int(drafted))
            rec["accepted_tokens"] = (
                rec.get("accepted_tokens", 0) + int(accepted))
            rec["wasted_draft_tokens"] = (
                rec.get("wasted_draft_tokens", 0) + int(drafted)
                - int(accepted))
        if self._cur is not None:
            self._cur["decode"][0] += int(committed) - int(replayed)
            self._cur["decode"][1] += int(replayed)
        self.totals["decode_tokens"] += int(committed)
        self.totals["decode_replayed"] += int(replayed)
        self.totals["drafted_tokens"] += int(drafted)
        self.totals["accepted_draft_tokens"] += int(accepted)
        self.totals["wasted_draft_tokens"] += int(drafted) - int(accepted)

    def on_fork(self, g, it):
        rec = self.live.get(g.req.req_id)
        if rec is not None and g.lanes > 1:
            self._event(rec, EV_FORK, it, g.lanes)

    def on_preempt(self, g, it, evicted_blocks):
        rec = self.live.get(g.req.req_id)
        if rec is None:
            return
        self._event(rec, EV_PREEMPT, it, int(evicted_blocks))
        rec["preemptions"] += 1
        self.preemptions += 1

    def on_first_token(self, g, it):
        """Record the first-token boundary and return ``(ttft_ms,
        ttft_iters)`` — the single source both the engine's scalar emission
        and the RequestOutput fields derive from (they cannot drift)."""
        rec = self.live.get(g.req.req_id)
        if rec is None:
            return None, None
        self._event(rec, EV_FIRST_TOKEN, it)
        ttft_ms = (rec["events"][-1][2] - rec["events"][0][2]) / 1000.0
        ttft_iters = int(it) - rec["arrival"]
        rec["ttft_ms"] = ttft_ms
        rec["ttft_iters"] = ttft_iters
        return ttft_ms, ttft_iters

    def on_finish(self, g, it, n_tokens):
        rec = self.live.pop(g.req.req_id, None)
        if rec is None:
            return None
        self._event(rec, EV_FINISH, it, int(n_tokens))
        rec["status"] = "finished"
        rec["finished_it"] = int(it)
        rec["n_tokens"] = int(n_tokens)
        t_submit = rec["events"][0][2]
        t_finish = rec["events"][-1][2]
        rec["e2e_ms"] = (t_finish - t_submit) / 1000.0
        rec["e2e_iters"] = int(it) - rec["arrival"]
        admits = [e for e in rec["events"] if e[0] == EV_ADMIT]
        if admits:  # queue delay of the admission that completed (the last)
            rec["queue_delay_ms"] = (admits[-1][2] - t_submit) / 1000.0
            rec["queue_delay_iters"] = admits[-1][4]
        first = [e for e in rec["events"] if e[0] == EV_FIRST_TOKEN]
        if first and n_tokens > 1:
            rec["tpot_ms"] = (t_finish - first[-1][2]) / 1000.0 / (n_tokens - 1)
        for m in LATENCY_METRICS:
            self.hist[m].add(rec.get(m))
        rec["slo_violations"] = sorted(
            m for m, lim in self.slo.items()
            if rec.get(m) is not None and rec[m] > lim)
        if self.slo:
            if rec["slo_violations"]:
                self.slo_violated += 1
            else:
                self.slo_met += 1
        self.finished += 1
        self.requests.append(rec)
        return rec

    # -- iteration timeline ------------------------------------------------
    def begin_iteration(self, it):
        self._cur = {"it": int(it), "t_us": self._now_us(),
                     "prefill": [0, 0],     # [useful, replayed] tokens
                     "decode": [0, 0]}

    def end_iteration(self, waiting, running, pool):
        """Close the iteration record with the scheduler's queue depths and
        the allocator's pool timeline point (``Scheduler.pool_stats``)."""
        cur, self._cur = self._cur, None
        if cur is None:
            return None
        cur["waiting"] = int(waiting)
        cur["running"] = int(running)
        cur["pool"] = pool
        self.iterations.append(cur)
        return cur

    # -- read-outs ---------------------------------------------------------
    def percentiles(self, metric=None, ps=(50, 90, 99)):
        """p50/p90/p99 (or any ``ps``) of one latency metric, or of all of
        them when ``metric`` is None — only metrics with data appear."""
        if metric is not None:
            return self.hist[metric].percentiles(ps)
        return {m: self.hist[m].percentiles(ps)
                for m in LATENCY_METRICS if self.hist[m].count}

    def latency_summary(self, ps=(50, 90, 99)):
        """Flat ``{metric_pNN: value}`` dict for TelemetrySession.end_step
        (emitted as ``Serving/Latency/*`` scalars)."""
        out = {}
        for m in LATENCY_METRICS:
            h = self.hist[m]
            if not h.count:
                continue
            for p in ps:
                out[f"{m}_p{p:g}"] = h.percentile(p)
        return out

    def waste_summary(self):
        t = self.totals
        scheduled = t["prefill_tokens"] + t["decode_tokens"]
        replayed = t["prefill_replayed"] + t["decode_replayed"]
        return {
            "scheduled_tokens": scheduled,
            "useful_tokens": scheduled - replayed,
            "replayed_tokens": replayed,
            "prefill_tokens": t["prefill_tokens"],
            "prefill_replayed": t["prefill_replayed"],
            "decode_tokens": t["decode_tokens"],
            "decode_replayed": t["decode_replayed"],
            "waste_fraction": (replayed / scheduled) if scheduled else 0.0,
            # prefix-cache reuse: prompt tokens whose KV was remapped rather
            # than scheduled — by construction OUTSIDE the useful+replayed ==
            # scheduled identity, so reuse is never misread as recomputation
            "cached_prefix_tokens": t["cached_prefix_tokens"],
            # speculation economics: draft-model work, likewise OUTSIDE the
            # identity (the committed tokens themselves are counted above)
            "drafted_tokens": t["drafted_tokens"],
            "accepted_draft_tokens": t["accepted_draft_tokens"],
            "wasted_draft_tokens": t["wasted_draft_tokens"],
        }

    def slo_summary(self):
        classified = self.slo_met + self.slo_violated
        return {
            "configured": dict(self.slo),
            "met": self.slo_met,
            "violated": self.slo_violated,
            "attainment": (self.slo_met / classified) if classified else None,
        }

    # -- bundle / dump -----------------------------------------------------
    def bundle(self):
        return {
            "version": REQUEST_TRACE_VERSION,
            "kind": SERVE_TRACE_KIND,
            "host": self.host_id,
            "slo": dict(self.slo),
            "requests": list(self.requests),
            "live": [self.live[k] for k in sorted(self.live)],
            "iterations": list(self.iterations),
            "totals": dict(self.totals),
            "counts": {"finished": self.finished, "refused": self.refused,
                       "shed": self.shed, "preemptions": self.preemptions},
            # mergeable latency sketches: N replica bundles combine exactly
            # into fleet percentiles (utils/cluster.fleet_latency_summary)
            "latency_sketches": {m: self.hist[m].to_dict()
                                 for m in LATENCY_METRICS
                                 if self.hist[m].count},
        }

    def dump(self, path=None):
        if path is None:
            if not self.dump_dir:
                return None
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir,
                                f"request_trace_host{self.host_id}.json")
        with open(path, "w") as f:
            json.dump(self.bundle(), f)
        return path

    def _atexit_dump(self):
        if self.dump_dir and (self.requests or self.live):
            try:
                self.dump()
            except OSError:
                pass  # trace dump failure must never mask the real exit


# ------------------------------------------------------------- Perfetto export

# Chrome trace_event reserved color names (same convention as the pipeline
# exporter): useful work vs replayed-after-preemption work must be visually
# distinct at a glance
_CAT_COLORS = {
    "prefill": "thread_state_running",
    "decode": "thread_state_runnable",
    "prefill_replay": "cq_build_failed",
    "decode_replay": "cq_build_failed",
    "queued": "rail_idle",
}


def _slice(tid, ts, dur, name, cat, args):
    return complete_slice(0, tid, ts, dur, name, cat, args,
                          cname=_CAT_COLORS.get(cat))


def to_serve_trace_events(bundle, us_per_iter=1000):
    """Convert a request-trace bundle into Chrome/Perfetto ``trace_event``
    JSON: one thread (track) per request in arrival order, queue / prefill /
    decode slices (replayed work color-flagged), instant markers for preempt /
    first-token / finish, and counter tracks for pool occupancy, waiting queue
    and cumulative waste fraction.

    Timestamps live on the ITERATION timebase (``it * us_per_iter``), which is
    a pure function of the schedule — the export is byte-deterministic for a
    deterministic trace (the golden-file contract), unlike the wall-clock
    ``*_us`` fields the bundle also carries for human inspection."""
    U = int(us_per_iter)
    events = [process_name_event(0, f"serving host {bundle.get('host', 0)}")]
    records = sorted(list(bundle.get("requests", []))
                     + list(bundle.get("live", [])),
                     key=lambda r: (r["arrival"], r["req_id"]))

    def ts_of(it, fallback):
        return (int(it) if it >= 0 else int(fallback)) * U

    for i, rec in enumerate(records):
        tid = i + 1
        events += thread_meta_events(0, tid, rec["req_id"], sort_index=tid)
        queued_since = rec["arrival"]
        run = None          # open decode run: [start_it, end_it, toks, replay]

        def flush_run():
            nonlocal run
            if run is None:
                return
            start, end, toks, replayed = run
            cat = "decode_replay" if replayed else "decode"
            events.append(_slice(
                tid, start * U, (end - start + 1) * U,
                f"decode x{end - start + 1}", cat,
                {"iters": end - start + 1, "tokens": toks,
                 "replayed": replayed}))
            run = None

        for ev in rec["events"]:
            name, it = ev[0], ev[1]
            if name != EV_DECODE:
                flush_run()
            if name == EV_ADMIT:
                if it > queued_since:
                    events.append(_slice(
                        tid, queued_since * U, (it - queued_since) * U,
                        "queued", "queued",
                        {"iters": it - queued_since}))
            elif name == EV_PREFILL:
                pos, n, replayed = ev[3], ev[4], ev[5]
                cat = "prefill_replay" if replayed == n else "prefill"
                events.append(_slice(
                    tid, it * U, U, f"prefill[{pos}:{pos + n}]", cat,
                    {"pos": pos, "tokens": n, "replayed": replayed}))
            elif name == EV_DECODE:
                lanes, replayed = ev[3], ev[4]
                if run is not None and (run[1] + 1 != it
                                        or bool(run[3]) != bool(replayed)):
                    flush_run()
                if run is None:
                    run = [it, it, 0, 0]
                run[1] = it
                run[2] += lanes
                run[3] += replayed
            elif name in (EV_SPEC_ACCEPT, EV_SPEC_REJECT):
                # only ever present with speculation on, so speculation-off
                # exports (the golden-file contract) are unchanged
                events.append(instant_event(
                    0, tid, it * U,
                    "spec accept" if name == EV_SPEC_ACCEPT else "spec reject",
                    {"drafted": ev[3], "accepted": ev[4],
                     "committed": ev[5]}))
            elif name == EV_CACHE_HIT:
                # only ever present with the prefix cache on and hitting, so
                # cache-off exports (the golden-file contract) are unchanged
                events.append(instant_event(0, tid, it * U, "prefix cache hit",
                                            {"cached_tokens": ev[3]}))
            elif name == EV_PREEMPT:
                events.append(instant_event(0, tid, it * U, "preempt",
                                            {"evicted_blocks": ev[3]}))
                queued_since = it
            elif name == EV_FIRST_TOKEN:
                events.append(instant_event(
                    0, tid, it * U, "first_token",
                    {"ttft_iters": rec.get("ttft_iters")}))
            elif name == EV_FINISH:
                events.append(instant_event(0, tid, it * U, "finish",
                                            {"n_tokens": ev[3]}))
            elif name == EV_REFUSED:
                events.append(instant_event(0, tid, ts_of(it, rec["arrival"]),
                                            "refused", {"reason": ev[3]}))
            elif name == EV_SHED:
                # only ever present in fleet-router front-door ledgers, so
                # single-engine exports (the golden-file contract) are unchanged
                events.append(instant_event(0, tid, ts_of(it, rec["arrival"]),
                                            "shed", {"reason": ev[3]}))
        flush_run()

    sched_tokens = 0
    replayed_tokens = 0
    for itrec in bundle.get("iterations", []):
        ts = itrec["it"] * U
        pool = itrec.get("pool") or {}
        used, free = pool.get("used", 0), pool.get("free", 0)
        occ = used / (used + free) if (used + free) else 0.0
        events.append(counter_event(0, 0, ts, "pool occupancy",
                                    {"occupancy": round(occ, 6)}))
        if "frag" in pool:
            events.append(counter_event(0, 0, ts, "pool fragmentation",
                                        {"fragmentation": round(pool["frag"], 6)}))
        events.append(counter_event(0, 0, ts, "waiting queue",
                                    {"waiting": itrec.get("waiting", 0)}))
        events.append(counter_event(0, 0, ts, "free blocks", {"free": free}))
        sched_tokens += sum(itrec["prefill"]) + sum(itrec["decode"])
        replayed_tokens += itrec["prefill"][1] + itrec["decode"][1]
        waste = replayed_tokens / sched_tokens if sched_tokens else 0.0
        events.append(counter_event(0, 0, ts, "waste fraction",
                                    {"waste": round(waste, 6)}))
    return trace_envelope(events, "ds-tpu serve-timeline",
                          requests=len(records), us_per_iter=U,
                          trace_version=bundle.get("version"))


# --------------------------------------------------------------------- the CLI


def _load_bundle(path):
    # flight-recorder dumps embed the request-trace bundle under its kind key
    return load_bundle(path, SERVE_TRACE_KIND)


def serve_timeline_main(argv=None):
    """``ds-tpu serve-timeline`` entry point: request-trace ledger bundle (or
    a flight-recorder dump embedding one) -> Perfetto/Chrome trace_event JSON."""
    parser = argparse.ArgumentParser(
        prog="ds-tpu serve-timeline",
        description="Convert a serving request_trace ledger bundle (or a "
                    "flight-recorder dump that embeds one) into Perfetto/"
                    "Chrome trace_event JSON viewable at ui.perfetto.dev or "
                    "chrome://tracing.")
    parser.add_argument("bundle", help="path to the ledger bundle / dump JSON")
    parser.add_argument("-o", "--output", default=None,
                        help="output path (default: <bundle>.trace.json)")
    parser.add_argument("--us-per-iter", type=int, default=1000,
                        help="microseconds per scheduler iteration on the "
                             "deterministic timebase (default 1000)")
    args = parser.parse_args(argv)

    try:
        bundle = _load_bundle(args.bundle)
    except (OSError, ValueError) as e:
        print(f"ds-tpu serve-timeline: cannot read {args.bundle}: {e}")
        return 2
    if bundle is None:
        print(f"ds-tpu serve-timeline: {args.bundle} holds no "
              f"{SERVE_TRACE_KIND} bundle (enable serving.request_trace and "
              "re-dump)")
        return 2

    trace = to_serve_trace_events(bundle, us_per_iter=args.us_per_iter)
    out = args.output
    if out is None:
        stem = args.bundle[:-5] if args.bundle.endswith(".json") else args.bundle
        out = stem + ".trace.json"
    with open(out, "w") as f:
        f.write(serialize_trace(trace))
    n_req = len(bundle.get("requests", [])) + len(bundle.get("live", []))
    print(f"wrote {len(trace['traceEvents'])} trace events "
          f"({n_req} requests, {len(bundle.get('iterations', []))} "
          f"iterations) -> {out}")
    return 0
