"""Fixed-shape paged KV-cache programs over models/gpt2.py.

Every program here has ONE abstract signature for the engine's lifetime — slot
count, chunk length, block table width and pool geometry are baked in at build
time, and per-iteration variation (which sequences are live, where they write)
rides in as array *values* (positions, tables, active masks). That is the whole
recompile story: ``ds-tpu serve-sim`` asserts zero decode-program recompiles
after warmup via the compile watchdog.

The pool is ``[n_layer, num_blocks, block_size, n_head, head_dim]`` per k/v in
the model's compute dtype; block 0 is the reserved null page (block_allocator).
The paged attention gathers each slot's pages by table and reshapes them into
the same ``[slots, n_head, max_blocks * block_size, head_dim]`` dense view the
model's cached forward contracts over, so with ``max_blocks * block_size ==
max_len`` the paged programs are **bitwise** the dense cached-forward math:
identical dot shapes, identical mask (``-1e9`` scores underflow to exact-zero
softmax weights, so garbage in never-written or masked page slots contributes
exact zeros), identical reduction orders. tests/unit/test_paged_attention.py
pins this against ``_build_cached_forward`` directly; serve/oracle.py carries
the per-slot-position dense mirror for mixed traces.

All cache/pool arguments are donated (the lesson of the relay-kill crashes,
models/gpt2.py): XLA aliases one pool buffer through every program, so serving
HBM is params + pool + activations — never 2x pool.

**Model-axis sharding** (``mesh=`` a Mesh carrying a ``model`` axis of size
``tp``): the KV pool is sharded by attention head — each chip holds
``[n_layer, num_blocks, block_size, n_head/tp, head_dim]`` — and decode /
prefill lower as one pjit program over that axis via ``shard_map``. Per
shard: slice the local head columns of ``c_attn_w`` (rows of ``c_proj_w``)
by ``axis_index``, run attention against the *local* pool shard (the block
table is replicated, pages are local — the same table steers every shard's
gather, including the Pallas kernel's BlockSpec index maps, which are
shape-generic over the head count), then one f32 ``psum`` per layer rebuilds
the proj contraction. Everything outside attention (LN, MLP, residual,
logits) is replicated compute on replicated activations, so all shards hold
bit-identical activations; the psum splits each proj dot's reduction into
``tp`` ordered partials, which moves float rounding by ulps — the sharded
engine is **token-identical** to the single-chip one (asserted by ``ds-tpu
serve-sim --sharding``), while the *bitwise* dense-mirror contract stays on
the unsharded path. Per-iteration variation still rides as array values and
the collective set is static (``n_layer`` all-reduces per program — the lint
registry's collective-budget manifest pins exactly that), so the
zero-recompile contract is unchanged.
"""

import math
import weakref

import jax
import jax.numpy as jnp

from .block_allocator import NULL_BLOCK

# One program set per (model instance, build geometry), shared by every engine
# built over it. The jitted programs close over only the model's pure config
# math and the baked geometry — params and pools arrive as call arguments — so
# two engines with the same model and geometry would lower byte-identical HLO;
# rebuilding per engine just recompiles it. Sharing makes engine construction
# (warm restarts, test fleets, the lint registry's capture engines) pay XLA
# once per process instead of once per engine. Weak-keyed so a model's
# programs die with it. Telemetry compile accounting is unaffected: the
# session's _WatchedJit AOT-compiles per (session, signature) on top of the
# raw jit, so watched engines still observe their own compiles.
_BUILD_CACHE = weakref.WeakKeyDictionary()


def _mesh_cache_key(mesh):
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


def build_paged_programs(model, *, num_slots, block_size, max_blocks,
                         prefill_chunk, copy_width=None, use_pallas=False,
                         mesh=None, verify_width=0):
    """Jitted program dict for one engine: ``decode_step``, ``prefill_chunk``,
    ``copy_blocks`` plus ``beam_init(K, eos)`` / ``beam_select(K, eos)``
    factories (per-(K, eos) program caches — K is a shape, eos a baked
    constant, so each variant is its own fixed-signature program). With
    ``mesh`` (carrying a ``model`` axis), the pool-touching programs lower
    as head-sharded pjit programs instead; the dict also carries the
    ``pool_sharding`` / ``replicated_sharding`` placements the engine puts
    its buffers with.

    ``verify_width = D > 0`` additionally builds ``spec_verify`` — the
    speculative-decoding verification program: a batched, D-token-wide
    generalization of ``decode_step`` (one chunked-prefill-shaped pass per
    slot, per-position logits out) that scores a drafted continuation for
    every slot in ONE target-model execution. Single-chip only: the engine
    refuses speculation + sharding, so the sharded build never asks for it."""
    cache_key = (int(num_slots), int(block_size), int(max_blocks),
                 int(prefill_chunk), int(copy_width or num_slots),
                 bool(use_pallas), _mesh_cache_key(mesh), int(verify_width))
    try:
        per_model = _BUILD_CACHE.setdefault(model, {})
    except TypeError:               # model not weak-referenceable: no sharing
        per_model = None
    if per_model is not None and cache_key in per_model:
        return per_model[cache_key]
    out = _build_paged_programs(
        model, num_slots=num_slots, block_size=block_size,
        max_blocks=max_blocks, prefill_chunk=prefill_chunk,
        copy_width=copy_width, use_pallas=use_pallas, mesh=mesh,
        verify_width=verify_width)
    if per_model is not None:
        per_model[cache_key] = out
    return out


def _build_paged_programs(model, *, num_slots, block_size, max_blocks,
                          prefill_chunk, copy_width=None, use_pallas=False,
                          mesh=None, verify_width=0):
    c = model.config
    nh, hd = c.n_head, c.head_dim
    S, BS, MB, C = int(num_slots), int(block_size), int(max_blocks), int(prefill_chunk)
    ML = MB * BS                      # the dense view length the gather rebuilds
    P = int(copy_width or num_slots)  # CoW copies per batched copy_blocks call
    cd = c.compute_dtype
    eps = c.layer_norm_epsilon
    V = c.vocab_size

    if use_pallas:
        from ..ops.pallas.paged_attention import paged_decode_attention
    else:
        paged_decode_attention = None

    def _qkv(x, bp):
        # verbatim models/gpt2.py attn_cached projection — bit-for-bit
        B_, Tn, _ = x.shape
        qkv = jnp.dot(x, bp["c_attn_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) \
            + bp["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B_, Tn, nh, hd).transpose(0, 2, 1, 3)
        return q, k, v

    def _proj(y, bp, x_dtype):
        return (jnp.dot(y, bp["c_proj_w"].astype(x_dtype),
                        preferred_element_type=jnp.float32).astype(x_dtype)
                + bp["c_proj_b"].astype(x_dtype))

    def _gather(pool, li, tables):
        """[S_, heads, ML, hd] dense view of one layer's pages by table — the
        exact layout ``kcs[li]`` has in the model's cached forward. Shape-
        generic over the pool's head dim, so a shard_map-local pool shard
        gathers its local heads with the same code."""
        g = pool[li][tables]                       # [S_, MB, BS, heads, hd]
        S_ = tables.shape[0]
        return g.reshape(S_, ML, pool.shape[3], hd).transpose(0, 2, 1, 3)

    def _attend(q, kg, vg, mask, x_dtype):
        # verbatim attn_cached score/softmax/value path
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kg,
                       preferred_element_type=jnp.float32) / math.sqrt(hd)
        s = jnp.where(mask, s, jnp.float32(-1e9))
        p = jax.nn.softmax(s, axis=-1).astype(x_dtype)
        y = jnp.einsum("bhqk,bhkd->bhqd", p, vg,
                       preferred_element_type=jnp.float32).astype(x_dtype)
        B_, heads, Tn, _ = y.shape
        return y.transpose(0, 2, 1, 3).reshape(B_, Tn, heads * hd)

    def _blocks_forward(p, x, attn_fn):
        for li, bp in enumerate(p["blocks"]):
            a = attn_fn(model._layer_norm(x, bp["ln_1"], eps), bp["attn"], li)
            x = x + a
            h = model._layer_norm(x, bp["ln_2"], eps)
            x = x + model._mlp(h, bp["mlp"])
        return model._layer_norm(x, p["ln_f"], eps)

    def _logits(row, p):
        # row [B_, H] — same einsum the cached forward applies to x[:, -1]
        return jnp.einsum("bh,vh->bv", row, p["wte"].astype(row.dtype),
                          preferred_element_type=jnp.float32)

    # ---------------------------------------------------------------- decode
    def decode_step(p, toks, pos, tables, active, k_pool, v_pool):
        """One token for every slot: toks/pos/tables/active are [S]-shaped
        ([S, MB] for tables); inactive lanes compute garbage and write to the
        null page. Returns (logits [S, V] f32, k_pool, v_pool)."""
        pools = {"k": k_pool, "v": v_pool}
        x = p["wte"][toks[:, None]].astype(cd) \
            + p["wpe"][pos[:, None]].astype(cd)             # [S, 1, H]
        wblk = jnp.where(active, tables[jnp.arange(S), pos // BS],
                         NULL_BLOCK)
        off = pos % BS

        def attn(xin, bp, li):
            q, k, v = _qkv(xin, bp)
            pools["k"] = pools["k"].at[li, wblk, off].set(
                k[:, :, 0, :].astype(pools["k"].dtype))
            pools["v"] = pools["v"].at[li, wblk, off].set(
                v[:, :, 0, :].astype(pools["v"].dtype))
            if paged_decode_attention is not None:
                y = paged_decode_attention(q, pools["k"], pools["v"], li,
                                           tables, pos + 1, block_size=BS)
                return _proj(y.transpose(0, 2, 1, 3).reshape(S, 1, nh * hd),
                             bp, xin.dtype)
            kg = _gather(pools["k"], li, tables)
            vg = _gather(pools["v"], li, tables)
            mask = (jnp.arange(ML)[None, :] <= pos[:, None])[:, None, None, :]
            return _proj(_attend(q, kg, vg, mask, xin.dtype), bp, xin.dtype)

        x = _blocks_forward(p, x, attn)
        return _logits(x[:, -1], p), pools["k"], pools["v"]

    # --------------------------------------------------------------- prefill
    def prefill_chunk_fn(p, toks, pos, n_valid, table, k_pool, v_pool):
        """One chunk of ONE sequence's prompt: toks [1, C] padded past
        ``n_valid``; writes positions [pos, pos + n_valid) through ``table``
        (pads go to the null page) and returns the logits of the last valid
        row — only meaningful on the chunk that completes the prompt."""
        pools = {"k": k_pool, "v": v_pool}
        wpe_cap = p["wpe"].shape[0] - 1
        tp = pos + jnp.arange(C)                              # [C] positions
        positions = jnp.minimum(tp, wpe_cap)  # pads only; valid rows untouched
        x = p["wte"][toks].astype(cd) \
            + p["wpe"][positions][None].astype(cd)            # [1, C, H]
        valid = jnp.arange(C) < n_valid
        wblk = jnp.where(valid, table[jnp.minimum(tp // BS, MB - 1)],
                         NULL_BLOCK)
        off = tp % BS
        tbl1 = table[None]                                    # [1, MB]

        def attn(xin, bp, li):
            q, k, v = _qkv(xin, bp)                           # [1, nh, C, hd]
            pools["k"] = pools["k"].at[li, wblk, off].set(
                k[0].transpose(1, 0, 2).astype(pools["k"].dtype))
            pools["v"] = pools["v"].at[li, wblk, off].set(
                v[0].transpose(1, 0, 2).astype(pools["v"].dtype))
            kg = _gather(pools["k"], li, tbl1)
            vg = _gather(pools["v"], li, tbl1)
            # same [Tn, ML] causal frontier the cached forward masks with
            mask = jnp.arange(ML)[None, :] <= tp[:, None]     # [C, ML]
            return _proj(_attend(q, kg, vg, mask, xin.dtype), bp, xin.dtype)

        x = _blocks_forward(p, x, attn)
        last = jax.lax.dynamic_slice(x, (0, n_valid - 1, 0),
                                     (1, 1, x.shape[-1]))[:, 0]
        return _logits(last, p), pools["k"], pools["v"]

    # ---------------------------------------------------- speculative verify
    D = int(verify_width)

    def spec_verify(p, toks, pos0, n_valid, tables, active, k_pool, v_pool):
        """Score a drafted continuation for every slot in one step: ``toks``
        is [S, D] — row 0 each slot's last committed token, rows 1.. the
        draft's proposals — at positions ``pos0 + [0, D)``; rows past
        ``n_valid[s]`` (and all rows of inactive slots) write to the null
        page and produce garbage logits the host ignores. Returns
        (logits [S, D, V] f32, k_pool, v_pool): row i's logits are the
        target's next-token distribution AFTER consuming toks[:, :i+1] —
        exactly what ``decode_step`` would have produced i steps later, so
        greedy acceptance against these rows is token-identical to plain
        decode. Rejected rows leave garbage KV past the accepted frontier;
        the causal mask (keys <= query position) means it is never attended,
        and the next round's writes cover the same extent — rollback is a
        host-side table truncation, no device work."""
        pools = {"k": k_pool, "v": v_pool}
        wpe_cap = p["wpe"].shape[0] - 1
        tp = pos0[:, None] + jnp.arange(D)[None, :]           # [S, D] positions
        positions = jnp.minimum(tp, wpe_cap)  # pads only; valid rows untouched
        x = p["wte"][toks].astype(cd) + p["wpe"][positions].astype(cd)
        valid = (jnp.arange(D)[None, :] < n_valid[:, None]) & active[:, None]
        wblk = jnp.where(
            valid,
            tables[jnp.arange(S)[:, None], jnp.minimum(tp // BS, MB - 1)],
            NULL_BLOCK)
        off = tp % BS

        def attn(xin, bp, li):
            q, k, v = _qkv(xin, bp)                           # [S, nh, D, hd]
            pools["k"] = pools["k"].at[li, wblk, off].set(
                k.transpose(0, 2, 1, 3).astype(pools["k"].dtype))
            pools["v"] = pools["v"].at[li, wblk, off].set(
                v.transpose(0, 2, 1, 3).astype(pools["v"].dtype))
            kg = _gather(pools["k"], li, tables)
            vg = _gather(pools["v"], li, tables)
            # per-row causal frontier: row i attends keys <= pos0 + i — the
            # same mask decode_step applies one position at a time
            mask = (jnp.arange(ML)[None, None, :]
                    <= tp[:, :, None])[:, None, :, :]
            return _proj(_attend(q, kg, vg, mask, xin.dtype), bp, xin.dtype)

        x = _blocks_forward(p, x, attn)
        logits = _logits(x.reshape(S * D, -1), p)
        return logits.reshape(S, D, V), pools["k"], pools["v"]

    # ------------------------------------------------------------ block copy
    def copy_blocks(k_pool, v_pool, src, dst):
        """Copy-on-write page copies, batched to a fixed width ``P`` (pads are
        0 -> 0 null self-copies). Gathers before scattering, so overlapping
        pairs are safe; the engine never generates them anyway."""
        k_pool = k_pool.at[:, dst].set(k_pool[:, src])
        v_pool = v_pool.at[:, dst].set(v_pool[:, src])
        return k_pool, v_pool

    # ----------------------------------------------------------- beam heads
    NEG = jnp.float32(-1e9)
    beam_cache = {}

    def beam_init(K, eos):
        """(prefill logits [1, V]) -> (scores, tok0, live) [K each] — the
        top-K first-token expansion from the chunk that completed the prompt.
        Verbatim beam_search init math."""
        key = ("init", K, eos)
        if key not in beam_cache:
            def f(logits):
                logp0 = jax.nn.log_softmax(logits, axis=-1)
                scores, tok0 = jax.lax.top_k(logp0, K)        # [1, K]
                live = (tok0 != eos) if eos >= 0 else jnp.ones((1, K), bool)
                return scores[0], tok0[0].astype(jnp.int32), live[0]
            beam_cache[key] = jax.jit(f)
        return beam_cache[key]

    def beam_select(K, eos):
        """(logits [S, V], slot_idx [K], scores [K], live [K]) ->
        (scores, parent, tok, live) [K each] — one beam step, verbatim
        beam_search step_scores + top-K reorder math at B=1."""
        key = ("select", K, eos)
        if key not in beam_cache:
            def f(logits, slot_idx, scores, live):
                logp = jax.nn.log_softmax(
                    logits[slot_idx].reshape(1, K, V), axis=-1)
                cand = scores[None, :, None] + logp
                if eos >= 0:
                    frozen = jnp.full((1, K, V), NEG).at[:, :, eos].set(
                        scores[None])
                    cand = jnp.where(live[None, :, None], cand, frozen)
                flat = cand.reshape(1, K * V)
                new_scores, idx = jax.lax.top_k(flat, K)      # [1, K]
                parent = idx // V
                tok = (idx % V).astype(jnp.int32)
                new_live = jnp.take_along_axis(live[None], parent, axis=1)
                if eos >= 0:
                    new_live = new_live & (tok != eos)
                return (new_scores[0], parent[0].astype(jnp.int32), tok[0],
                        new_live[0])
            beam_cache[key] = jax.jit(f)
        return beam_cache[key]

    if mesh is None:
        out = {
            "decode_step": jax.jit(decode_step, donate_argnums=(5, 6)),
            "prefill_chunk": jax.jit(prefill_chunk_fn, donate_argnums=(5, 6)),
            "copy_blocks": jax.jit(copy_blocks, donate_argnums=(0, 1)),
            "beam_init": beam_init,
            "beam_select": beam_select,
            "copy_width": P,
        }
        if D > 0:
            out["spec_verify"] = jax.jit(spec_verify, donate_argnums=(6, 7))
        return out

    if D > 0:
        raise ValueError("speculative verify is single-chip only (the engine "
                         "refuses speculation + sharding)")

    # ------------------------------------------------- model-axis sharding
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from ..parallel.mesh import MODEL_AXIS, shard_map

    tp = mesh.shape[MODEL_AXIS]
    if nh % tp:
        raise ValueError(f"n_head {nh} not divisible by model-axis size {tp}")
    nh_l = nh // tp
    H = nh * hd
    POOL = PS(None, None, None, MODEL_AXIS, None)   # pool sharded by head
    REP = PS()                                      # everything else replicated
    pool_sharding = NamedSharding(mesh, POOL)
    rep_sharding = NamedSharding(mesh, REP)

    def _qkv_local(x, bp):
        """Local-head slice of the attn projection: column block
        ``[part*H + h0, +nh_l*hd)`` of ``c_attn_w`` for part in (q, k, v).
        Same dot/bias/reshape structure as ``_qkv``, nh_l heads wide."""
        B_, Tn, _ = x.shape
        h0 = jax.lax.axis_index(MODEL_AXIS) * (nh_l * hd)
        w = bp["c_attn_w"].astype(x.dtype)
        b = bp["c_attn_b"].astype(x.dtype)

        def part(i):
            wc = jax.lax.dynamic_slice_in_dim(w, i * H + h0, nh_l * hd, 1)
            bc = jax.lax.dynamic_slice_in_dim(b, i * H + h0, nh_l * hd, 0)
            out = jnp.dot(x, wc,
                          preferred_element_type=jnp.float32).astype(x.dtype) \
                + bc
            return out.reshape(B_, Tn, nh_l, hd).transpose(0, 2, 1, 3)

        return part(0), part(1), part(2)

    def _proj_local(y, bp, x_dtype):
        """Row block of ``c_proj_w`` for the local heads; the f32 ``psum``
        over the model axis rebuilds the full contraction (the ONE collective
        per layer the budget manifest admits), bias added once after."""
        h0 = jax.lax.axis_index(MODEL_AXIS) * (nh_l * hd)
        wr = jax.lax.dynamic_slice_in_dim(
            bp["c_proj_w"].astype(x_dtype), h0, nh_l * hd, 0)
        part = jnp.dot(y, wr, preferred_element_type=jnp.float32)
        return (jax.lax.psum(part, MODEL_AXIS).astype(x_dtype)
                + bp["c_proj_b"].astype(x_dtype))

    def sharded_decode_step(p, toks, pos, tables, active, k_pool, v_pool):
        def body(p, toks, pos, tables, active, k_pool, v_pool):
            pools = {"k": k_pool, "v": v_pool}
            x = p["wte"][toks[:, None]].astype(cd) \
                + p["wpe"][pos[:, None]].astype(cd)
            wblk = jnp.where(active, tables[jnp.arange(S), pos // BS],
                             NULL_BLOCK)
            off = pos % BS

            def attn(xin, bp, li):
                q, k, v = _qkv_local(xin, bp)        # [S, nh_l, 1, hd]
                pools["k"] = pools["k"].at[li, wblk, off].set(
                    k[:, :, 0, :].astype(pools["k"].dtype))
                pools["v"] = pools["v"].at[li, wblk, off].set(
                    v[:, :, 0, :].astype(pools["v"].dtype))
                if paged_decode_attention is not None:
                    y = paged_decode_attention(q, pools["k"], pools["v"], li,
                                               tables, pos + 1, block_size=BS)
                    y = y.transpose(0, 2, 1, 3).reshape(S, 1, nh_l * hd)
                else:
                    kg = _gather(pools["k"], li, tables)
                    vg = _gather(pools["v"], li, tables)
                    mask = (jnp.arange(ML)[None, :]
                            <= pos[:, None])[:, None, None, :]
                    y = _attend(q, kg, vg, mask, xin.dtype)
                return _proj_local(y, bp, xin.dtype)

            x = _blocks_forward(p, x, attn)
            return _logits(x[:, -1], p), pools["k"], pools["v"]

        return shard_map(body, mesh=mesh,
                         in_specs=(REP, REP, REP, REP, REP, POOL, POOL),
                         out_specs=(REP, POOL, POOL))(
            p, toks, pos, tables, active, k_pool, v_pool)

    def sharded_prefill_chunk(p, toks, pos, n_valid, table, k_pool, v_pool):
        def body(p, toks, pos, n_valid, table, k_pool, v_pool):
            pools = {"k": k_pool, "v": v_pool}
            wpe_cap = p["wpe"].shape[0] - 1
            tp_ = pos + jnp.arange(C)
            positions = jnp.minimum(tp_, wpe_cap)
            x = p["wte"][toks].astype(cd) \
                + p["wpe"][positions][None].astype(cd)
            valid = jnp.arange(C) < n_valid
            wblk = jnp.where(valid, table[jnp.minimum(tp_ // BS, MB - 1)],
                             NULL_BLOCK)
            off = tp_ % BS
            tbl1 = table[None]

            def attn(xin, bp, li):
                q, k, v = _qkv_local(xin, bp)        # [1, nh_l, C, hd]
                pools["k"] = pools["k"].at[li, wblk, off].set(
                    k[0].transpose(1, 0, 2).astype(pools["k"].dtype))
                pools["v"] = pools["v"].at[li, wblk, off].set(
                    v[0].transpose(1, 0, 2).astype(pools["v"].dtype))
                kg = _gather(pools["k"], li, tbl1)
                vg = _gather(pools["v"], li, tbl1)
                mask = jnp.arange(ML)[None, :] <= tp_[:, None]
                return _proj_local(_attend(q, kg, vg, mask, xin.dtype),
                                   bp, xin.dtype)

            x = _blocks_forward(p, x, attn)
            last = jax.lax.dynamic_slice(x, (0, n_valid - 1, 0),
                                         (1, 1, x.shape[-1]))[:, 0]
            return _logits(last, p), pools["k"], pools["v"]

        return shard_map(body, mesh=mesh,
                         in_specs=(REP, REP, REP, REP, REP, POOL, POOL),
                         out_specs=(REP, POOL, POOL))(
            p, toks, pos, n_valid, table, k_pool, v_pool)

    # copy_blocks scatters along the (unsharded) block axis only — GSPMD
    # partitions it per shard with zero collectives; no shard_map needed
    return {
        "decode_step": jax.jit(
            sharded_decode_step, donate_argnums=(5, 6),
            in_shardings=(rep_sharding,) * 5 + (pool_sharding,) * 2,
            out_shardings=(rep_sharding, pool_sharding, pool_sharding)),
        "prefill_chunk": jax.jit(
            sharded_prefill_chunk, donate_argnums=(5, 6),
            in_shardings=(rep_sharding,) * 5 + (pool_sharding,) * 2,
            out_shardings=(rep_sharding, pool_sharding, pool_sharding)),
        "copy_blocks": jax.jit(
            copy_blocks, donate_argnums=(0, 1),
            in_shardings=(pool_sharding, pool_sharding,
                          rep_sharding, rep_sharding),
            out_shardings=(pool_sharding, pool_sharding)),
        "beam_init": beam_init,
        "beam_select": beam_select,
        "copy_width": P,
        "pool_sharding": pool_sharding,
        "replicated_sharding": rep_sharding,
        "model_parallel": tp,
    }
