"""InferenceEngine: continuous-batching serving over the paged KV cache.

The engine owns the device state (params + one paged KV pool, aliased through
every program by donation) and executes the scheduler's host decisions in a
fixed per-iteration order:

    admit -> ensure write blocks (CoW page copies) -> one prefill chunk
          -> one decode step for every live lane -> sampling heads

Every device program has one fixed abstract signature (serve/paged.py), so the
whole serving loop compiles each program exactly once — ``ds-tpu serve-sim``
asserts this through the compile watchdog. Sampling is host-side for the
single-lane path — exact greedy (np.argmax over the fetched f32 logits row,
same first-max tie-break as the in-graph jnp.argmax) when ``temperature <= 0``,
else temperature/top-k/top-p sampling with a counter-based RNG keyed on
``(request seed, token position)`` so replays and preempt-restarts regenerate
identical tokens — and a tiny fixed-shape device program per beam step.

``mirror=True`` runs the dense-cache oracle (serve/oracle.py) in lockstep and
asserts the paged logits are **bitwise identical** to the dense ones every
prefill chunk and every decode step — the standing proof that paging is a
memory-layout change, not a numerics change.
"""

import time

import numpy as np

import jax.numpy as jnp

from .block_allocator import NULL_BLOCK
from .paged import build_paged_programs
from .request_trace import RequestTracer
from .scheduler import RequestOutput, Scheduler

_MAX_IDLE_SKIP = 1 << 30


class InferenceEngine:
    def __init__(self, model, params, *, num_slots=8, block_size=16,
                 num_blocks=257, max_model_len=256, prefill_chunk=32,
                 use_pallas=False, telemetry=None, mirror=False,
                 request_trace=None, prefix_cache=False, sharding=None,
                 speculation=None):
        c = model.config
        spec_cfg = speculation if (speculation or {}).get("enabled") else None
        if max_model_len % block_size != 0:
            raise ValueError(f"max_model_len {max_model_len} not a multiple "
                             f"of block_size {block_size}")
        if max_model_len > c.n_positions:
            raise ValueError(f"max_model_len {max_model_len} exceeds the "
                             f"model's n_positions {c.n_positions}")
        if getattr(c, "moe_experts", 0):
            raise ValueError("serving supports dense models only (no MoE)")
        if getattr(c, "sparse_attention", None):
            raise ValueError("serving supports dense attention only")
        if isinstance(sharding, dict):
            tp = int(sharding.get("model", 1))
        else:
            tp = int(sharding or 1)
        if tp < 1:
            raise ValueError(f"serving.sharding.model must be >= 1, got {tp}")
        if tp > 1:
            import jax
            if c.n_head % tp:
                raise ValueError(f"n_head {c.n_head} not divisible by "
                                 f"serving.sharding.model {tp}")
            if tp > len(jax.devices()):
                raise ValueError(f"serving.sharding.model {tp} exceeds "
                                 f"{len(jax.devices())} devices")
            if mirror:
                raise ValueError(
                    "mirror asserts bitwise identity against the dense "
                    "oracle; the sharded proj psum reorders each reduction "
                    "(token-identical, not bitwise) — run the mirror on an "
                    "unsharded engine")
        if mirror and prefix_cache:
            raise ValueError(
                "mirror cannot run with prefix_cache: a warm start skips "
                "prefill chunks whose KV the dense per-slot oracle no longer "
                "holds (its cache is overwritten on slot reuse) — prove "
                "bitwise identity on a cache-off engine instead")
        if spec_cfg is not None and mirror:
            raise ValueError(
                "mirror asserts bitwise identity against the dense oracle; "
                "the K+1-wide spec_verify program fuses the batch differently "
                "than the 1-wide decode_step (token-identical, not bitwise — "
                "the sharded-psum precedent) and commits multiple tokens per "
                "step the per-step oracle cannot follow — run the mirror on a "
                "speculation-off engine, and pin speculative token identity "
                "with `ds-tpu serve-sim --compare-speculate` instead")
        if spec_cfg is not None and tp > 1:
            raise ValueError(
                "speculation + serving.sharding.model > 1 is not supported: "
                "the spec_verify program is single-chip only (shard the "
                "target OR speculate, not both)")
        self.tp = tp
        self.model = model
        self.params = params
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_model_len = int(max_model_len)
        self.max_blocks = self.max_model_len // self.block_size
        self.prefill_chunk = int(prefill_chunk)
        self.telemetry = telemetry
        # the non-perturbing gate: with serving.request_trace disabled the
        # tracer is None — no attribute exists for compiled code to close
        # over, and every hook below is a `is not None` host branch
        # (tests/unit/test_request_trace.py pins HLO-identity on/off)
        rt = request_trace or {}
        self.tracer = None
        if rt.get("enabled"):
            self.tracer = RequestTracer(
                capacity=rt.get("capacity", 256),
                iteration_capacity=rt.get("iteration_capacity", 4096),
                dump_dir=rt.get("dump_dir") or None,
                slo=rt.get("slo"),
                host_id=rt.get("host_id", 0))

        self._mesh = None
        if tp > 1:
            import jax
            from ..comm.topology import CommTopology
            from ..parallel.mesh import build_mesh
            self._mesh = build_mesh(data=1, model=tp, pipe=1,
                                    devices=jax.devices()[:tp])
            if self.telemetry is not None:
                # classify the decode/prefill psums' links for the anatomy
                # ledger: the model axis of one serving replica rides a
                # single slice, so its collectives are all-ICI wire
                topo = CommTopology(tp, 1)
                self.telemetry.set_comm_topology(
                    topo.slice_device_sets(self._mesh))
        self._spec = None
        self._verify = None
        self.spec_k = 0
        if spec_cfg is not None:
            from .speculative import SpeculativeDecoder
            draft_model = spec_cfg.get("draft_model")
            draft_params = spec_cfg.get("draft_params")
            if draft_model is None or draft_params is None:
                raise ValueError(
                    "serving.speculation.enabled needs a live draft model: "
                    "pass draft_model= and draft_parameters= to "
                    "deepspeed.init_inference (the config's draft_model key "
                    "is a label, not a loader)")
            self.spec_k = int(spec_cfg.get("max_draft_tokens", 4))
            self._spec = SpeculativeDecoder(
                draft_model, draft_params, num_slots=self.num_slots,
                block_size=self.block_size, max_blocks=self.max_blocks,
                prefill_chunk=self.prefill_chunk,
                draft_pool_blocks=(int(spec_cfg.get("draft_pool_blocks") or 0)
                                   or self.num_blocks),
                max_draft_tokens=self.spec_k, target_config=c,
                watch=self._watch)
        self._raw = build_paged_programs(
            model, num_slots=self.num_slots, block_size=self.block_size,
            max_blocks=self.max_blocks, prefill_chunk=self.prefill_chunk,
            use_pallas=use_pallas, mesh=self._mesh,
            verify_width=self.spec_k + 1 if self._spec is not None else 0)
        if self._spec is not None:
            self._verify = self._watch("serve:spec_verify",
                                       self._raw["spec_verify"])
        self._decode = self._watch("serve:decode_step", self._raw["decode_step"])
        self._prefill = self._watch("serve:prefill_chunk",
                                    self._raw["prefill_chunk"])
        self._copy = self._watch("serve:copy_blocks", self._raw["copy_blocks"])
        self._beam_watched = {}
        self.copy_width = self._raw["copy_width"]

        pool_shape = (c.n_layer, self.num_blocks, self.block_size,
                      c.n_head, c.head_dim)
        self.k_pool = jnp.zeros(pool_shape, c.compute_dtype)
        self.v_pool = jnp.zeros(pool_shape, c.compute_dtype)
        if self._mesh is not None:
            import jax
            self.k_pool = jax.device_put(self.k_pool,
                                         self._raw["pool_sharding"])
            self.v_pool = jax.device_put(self.v_pool,
                                         self._raw["pool_sharding"])

        self.scheduler = Scheduler(
            num_slots=self.num_slots, num_blocks=self.num_blocks,
            block_size=self.block_size, max_model_len=self.max_model_len,
            prefill_chunk=self.prefill_chunk, prefix_cache=prefix_cache)
        self.prefix_cache = self.scheduler.prefix_cache

        self._mirror = None
        self.mirror_checks = 0
        if mirror:
            from .oracle import build_oracle_programs
            self._mirror = build_oracle_programs(
                model, num_slots=self.num_slots, max_len=self.max_model_len,
                prefill_chunk=self.prefill_chunk)
            self._okcs, self._ovcs = self._mirror["fresh_caches"]()

        self._it = 0
        self._order = []                    # req_id submission order
        self.outputs = {}                   # req_id -> RequestOutput
        self._submit_ms = {}
        self._start_wall = None
        self._tokens_sampled = 0            # every appended token
        self._tokens_finished = 0           # tokens of finished requests only
        # target-model step accounting (speculation's headline number):
        # _target_steps counts target program executions (prefill chunks,
        # decode steps, spec verifies); _advance_steps counts per-GROUP
        # participations in a token-advancing step, so advance/token reads
        # ~1.0 for plain greedy and ~1/(1+E[accepted]) with speculation
        self._target_steps = 0
        self._advance_steps = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_rounds = 0

    # ------------------------------------------------------------- plumbing
    def _watch(self, name, fn):
        return self.telemetry.watch(name, fn) if self.telemetry else fn

    def _beam_head(self, kind, g):
        K, eos = g.lanes, g.req.eos_token_id
        key = (kind, K, eos)
        if key not in self._beam_watched:
            fn = self._raw[f"beam_{kind}"](K, eos)
            self._beam_watched[key] = self._watch(
                f"serve:beam_{kind}_k{K}_e{eos}", fn)
        return self._beam_watched[key]

    def _scalar(self, name, value):
        if self.telemetry is not None:
            self.telemetry.monitor.add_scalar(f"Serving/{name}",
                                              float(value), self._it)

    # ----------------------------------------------------------- submission
    def submit(self, req):
        """Queue a request; infeasible ones are refused (a RequestOutput with
        status "refused"), never crash the engine."""
        self._order.append(req.req_id)
        self._submit_ms[req.req_id] = time.perf_counter()
        if self.tracer is not None:
            self.tracer.on_submit(req)
        reason = self.scheduler.submit(req)
        if reason is not None:
            if self.tracer is not None:
                self.tracer.on_refused(req, reason)
            out = RequestOutput(req.req_id, "refused", refusal=reason)
            self.outputs[req.req_id] = out
            return out
        return None

    # ---------------------------------------------------------- the big loop
    def step(self):
        """One serving iteration. Returns the schedule-log dict — pure host
        decisions only, so a trace replay is byte-identical (json.dumps)."""
        if self._start_wall is None:
            self._start_wall = time.perf_counter()
        sched, it, tr = self.scheduler, self._it, self.tracer
        log = {"it": it}
        if tr is not None:
            tr.begin_iteration(it)

        admitted = sched.admit(it)
        preempted, copies = sched.ensure_decode_room()
        log["admitted"] = [g.req.req_id for g in admitted]
        log["preempted"] = [g.req.req_id for g in preempted]
        log["copies"] = [list(c) for c in copies]
        if tr is not None:
            for g in admitted:
                tr.on_admit(g, it)
            for g in preempted:
                tr.on_preempt(g, it, g.evicted_blocks)
        self._run_copies(copies)

        log["prefill"] = self._prefill_one(it)
        spec_res = self._speculate_all(it) if self._spec is not None else None
        if spec_res is not None:
            log["spec"], log["decode"], log["finished"] = spec_res
        else:
            if self._spec is not None:
                log["spec"] = []
            log["decode"], log["finished"] = self._decode_all(it)

        self._scalar("occupancy", sched.occupancy())
        self._scalar("waiting", len(sched.waiting))
        self._scalar("free_blocks", sched.allocator.num_free)
        if self.prefix_cache is not None:
            pc = self.prefix_cache.stats()
            self._scalar("PrefixCache/hit_rate", pc["hit_rate"])
            self._scalar("PrefixCache/hit_tokens", pc["hit_tokens"])
            self._scalar("PrefixCache/parked_blocks", pc["parked_blocks"])
            self._scalar("PrefixCache/evictions", pc["evictions"])
        if self._spec is not None:
            s = self.spec_summary()
            self._scalar("Spec/acceptance_rate", s["spec_acceptance_rate"])
            self._scalar("Spec/drafted_tokens", s["drafted_tokens"])
            self._scalar("Spec/accepted_tokens", s["accepted_tokens"])
            self._scalar("Spec/wasted_draft_tokens",
                         s["wasted_draft_tokens"])
            self._scalar("Spec/target_steps_per_token",
                         s["target_steps_per_token"])
        elapsed = max(time.perf_counter() - self._start_wall, 1e-9)
        self._scalar("tok_s", self._tokens_sampled / elapsed)
        self._scalar("goodput_tok_s", self._tokens_finished / elapsed)
        if tr is not None:
            itrec = tr.end_iteration(len(sched.waiting), len(sched.running),
                                     sched.pool_stats())
            ws = tr.waste_summary()
            self._scalar("Waste/replayed_tokens", ws["replayed_tokens"])
            self._scalar("Waste/fraction", ws["waste_fraction"])
            self._scalar("Pool/fragmentation", itrec["pool"]["frag"])
            if self.telemetry is not None:
                self.telemetry.end_step(it, 1, serving=tr.latency_summary())

        self._it += 1
        return log

    def run(self, requests):
        """Submit everything, drive steps until drained. Returns (outputs in
        submission order, per-iteration schedule log)."""
        for r in requests:
            self.submit(r)
        logs = []
        guard = 0
        while not self.scheduler.idle:
            if not self.scheduler.running:
                na = self.scheduler.next_arrival()
                if na is not None and na > self._it:
                    self._it = na           # fast-forward idle iterations
            logs.append(self.step())
            guard += 1
            if guard > 200000:
                raise RuntimeError("serving loop failed to drain (bug)")
        return [self.outputs[rid] for rid in self._order], logs

    # -------------------------------------------------------------- internals
    def _run_copies(self, copies):
        P = self.copy_width
        for i in range(0, len(copies), P):
            batch = copies[i:i + P]
            src = np.zeros(P, np.int32)     # pads: null 0 -> 0 self-copy
            dst = np.zeros(P, np.int32)
            for j, (s, d) in enumerate(batch):
                src[j], dst[j] = s, d
            self.k_pool, self.v_pool = self._copy(
                self.k_pool, self.v_pool, jnp.asarray(src), jnp.asarray(dst))

    def _pad_table(self, table):
        out = np.full(self.max_blocks, NULL_BLOCK, np.int32)
        out[:len(table)] = table
        return out

    def _prefill_one(self, it):
        pf = self.scheduler.next_prefill(it)
        if pf is None:
            return None
        g, pos, n, chunk = pf
        toks = jnp.asarray([chunk], jnp.int32)
        table = jnp.asarray(self._pad_table(g.tables[0]))
        logits, self.k_pool, self.v_pool = self._prefill(
            self.params, toks, jnp.int32(pos), jnp.int32(n), table,
            self.k_pool, self.v_pool)
        self._target_steps += 1
        if self._mirror is not None:
            ol, self._okcs, self._ovcs = self._mirror["prefill_chunk"](
                self.params, toks, jnp.int32(pos), jnp.int32(n),
                jnp.int32(g.slots[0]), self._okcs, self._ovcs)
            self._assert_bitwise(logits, ol, f"prefill it={it} "
                                 f"req={g.req.req_id} pos={pos}")
        if self.tracer is not None:
            self.tracer.on_prefill(g, it, pos, n, g.prefill_replay_tokens(pos, n))
        done = self.scheduler.finish_prefill_chunk(g, n, it)
        if done:
            self._first_tokens(g, logits, it)
        return [g.req.req_id, pos, n, bool(done)]

    def _first_tokens(self, g, logits, it):
        if g.lanes == 1:
            tok = self._sample_token(g, np.asarray(logits[0]), 0)
            self.scheduler.begin_decode(g, [tok], it)
        else:
            scores, tok0, live = self._beam_head("init", g)(logits)
            self.scheduler.begin_decode(
                g, [int(t) for t in np.asarray(tok0)], it,
                scores=np.asarray(scores), live=np.asarray(live))
            if self._mirror is not None and g.lanes > 1:
                perm = np.arange(self.num_slots, dtype=np.int32)
                perm[np.asarray(g.slots[1:], np.int32)] = g.slots[0]
                self._okcs, self._ovcs = self._mirror["reorder"](
                    self._okcs, self._ovcs, jnp.asarray(perm))
        self._tokens_sampled += g.lanes
        if self.tracer is not None:
            # single-source TTFT: the ledger record feeds the Group field,
            # the Serving/* scalars AND the RequestOutput fields (they read
            # the same numbers, so they cannot drift)
            self.tracer.on_fork(g, it)
            ttft_ms, ttft_iters = self.tracer.on_first_token(g, it)
        else:
            ttft_ms = (time.perf_counter()
                       - self._submit_ms[g.req.req_id]) * 1000.0
            ttft_iters = it - g.req.arrival
        g.first_token_ms = ttft_ms
        self._scalar("ttft_ms", ttft_ms)
        self._scalar("ttft_iters", ttft_iters)

    # -------------------------------------------------------- speculation
    def _extend_target_table(self, g, m, copies):
        """Cover write positions ``next_pos .. next_pos+m`` in the group's
        target block table before a verify step: fresh pages past the end,
        ``ensure_exclusive`` (CoW) for existing shared ones — the same
        discipline as Scheduler._ensure_group_blocks, widened to the verify
        window. On pool exhaustion the appended pages go back and the table
        shrinks to its original length (the group plain-decodes this
        iteration); CoW swaps that already happened keep their device copy,
        the pages are genuinely exclusive now (the scheduler precedent)."""
        from .block_allocator import AllocationError
        alloc = self.scheduler.allocator
        BS = self.block_size
        table = g.tables[0]
        orig_len = len(table)
        p0 = g.next_pos(0)
        try:
            for bi in range(p0 // BS, (p0 + m) // BS + 1):
                if bi == len(table):
                    table.append(alloc.allocate(1)[0])
                else:
                    blk, copy = alloc.ensure_exclusive(table[bi])
                    if copy is not None:
                        table[bi] = blk
                        copies.append(copy)
        except AllocationError:
            if len(table) > orig_len:
                alloc.free(table[orig_len:])
                del table[orig_len:]
            return False
        return True

    def _speculate_all(self, it):
        """One speculative decode round, replacing ``_decode_all`` for the
        whole iteration: eligible single-lane greedy groups get up to K draft
        proposals verified at K+1 positions, and EVERY other decode lane
        (beam lanes, sampled lanes, groups that lost a draft-page race) rides
        the same ``spec_verify`` execution as a plain ``n_valid=1`` row — so
        a speculative iteration still executes exactly ONE target
        decode-domain program, and "strictly fewer target steps" holds at
        the program-execution level, not just per token.

        Accepted prefixes (plus the target's own next token) commit; the
        first rejection truncates the block table to the accepted frontier
        and refcount-releases the tail (free rollback — the kept partial
        page's garbage tail is never attended and is overwritten next
        round). Returns ``(spec_log, decode_log, finished)``, or None when
        no group can draft this iteration (the caller falls back to the
        cheaper 1-wide ``decode_step``)."""
        spec, sched, alloc = self._spec, self.scheduler, self.scheduler.allocator
        spec.sync(sched.running)
        lanes = [(g, lane, slot) for g, lane, slot in
                 sched.decode_lanes() if g.entered_decode_it != it]
        plan, copies = [], []
        for g, lane, slot in lanes:
            if lane != 0 or g.lanes != 1 or g.req.temperature > 0.0:
                continue
            # never draft past the request budget: m proposals commit at most
            # m+1 tokens, and the final token must come from a verify row so
            # the emitted stream matches plain decode's finish check exactly
            m = min(self.spec_k,
                    g.req.max_new_tokens - len(g.generated[0]) - 1)
            if m < 1:
                continue
            if not spec.prepare(g, m):
                continue
            if not self._extend_target_table(g, m, copies):
                continue
            plan.append((g, m))
        self._run_copies(copies)
        if not plan:
            return None

        drafts = spec.propose(plan)
        plan_groups = {id(g) for g, _ in plan}
        plain = [(g, lane, slot) for g, lane, slot in lanes
                 if id(g) not in plan_groups]
        decode_log = [[g.req.req_id, lane, slot] for g, lane, slot in plain]
        if self.tracer is not None:
            traced = set()
            for g, _, _ in plain:
                if id(g) in traced:
                    continue
                traced.add(id(g))
                self.tracer.on_decode(
                    g, it, g.lanes, g.lanes if g.decode_is_replay() else 0)

        S, D = self.num_slots, self.spec_k + 1
        toks = np.zeros((S, D), np.int32)
        pos0 = np.zeros(S, np.int32)
        n_valid = np.zeros(S, np.int32)
        tables = np.full((S, self.max_blocks), NULL_BLOCK, np.int32)
        active = np.zeros(S, bool)
        for g, m in plan:
            slot = g.slots[0]
            toks[slot, 0] = g.generated[0][-1]
            toks[slot, 1:1 + m] = drafts[spec._key(g)]
            pos0[slot] = g.next_pos(0)
            n_valid[slot] = m + 1
            tables[slot] = self._pad_table(g.tables[0])
            active[slot] = True
        for g, lane, slot in plain:
            toks[slot, 0] = g.generated[lane][-1]
            pos0[slot] = g.next_pos(lane)
            n_valid[slot] = 1
            tables[slot] = self._pad_table(g.tables[lane])
            active[slot] = True
        logits, self.k_pool, self.v_pool = self._verify(
            self.params, jnp.asarray(toks), jnp.asarray(pos0),
            jnp.asarray(n_valid), jnp.asarray(tables), jnp.asarray(active),
            self.k_pool, self.v_pool)
        self._target_steps += 1
        self._advance_steps += len(plan) + len({id(g) for g, _, _ in plain})
        self._spec_rounds += 1
        logits_np = np.asarray(logits)

        spec_log, finished = [], []
        for g, m in plan:
            slot = g.slots[0]
            p0 = g.next_pos(0)
            ds = drafts[spec._key(g)]
            len_before = len(g.generated[0])
            eos, L = g.req.eos_token_id, g.req.max_new_tokens
            committed, a, fin = [], 0, False
            for i in range(m + 1):
                t = int(np.argmax(logits_np[slot, i]))
                committed.append(t)
                matched = i < m and ds[i] == t
                if matched:
                    a += 1
                # the exact _sample_greedy finish check, applied per token
                if (len_before + len(committed) >= L
                        or (eos >= 0 and t == eos)):
                    fin = True
                    break
                if not matched:
                    break
            g.generated[0].extend(committed)
            self._tokens_sampled += len(committed)
            self._spec_drafted += m
            self._spec_accepted += a
            r = min(max(g.replay_decode_hwm - len_before, 0), len(committed))
            if self.tracer is not None:
                self.tracer.on_spec(g, it, drafted=m, accepted=a,
                                    committed=len(committed), replayed=r)
            spec_log.append([g.req.req_id, m, a, len(committed)])
            if fin:
                self._finish(g, g.generated[0], None, finished, it)
                continue
            # rollback: the table only needs to cover the committed frontier
            # (positions <= p0 + a hold valid KV)
            keep = alloc.blocks_for_tokens(p0 + a + 1)
            table = g.tables[0]
            if keep < len(table):
                alloc.free(table[keep:])
                del table[keep:]
            spec.observe(g, p0, a, m)

        # the ride-along lanes sample from verify row 0 — greedy argmax is
        # token-identical to decode_step's row (the --compare-speculate
        # contract); beam heads consume the device row like the sharded
        # engine's psum'd logits (token-identical precedent)
        logits0_np = logits_np[:, 0]
        logits0 = None
        for g in list(sched.running):
            if (g.phase != "decode" or g.entered_decode_it == it
                    or id(g) in plan_groups):
                continue
            if g.lanes == 1:
                self._sample_greedy(g, logits0_np, finished, it)
            else:
                if logits0 is None:
                    logits0 = logits[:, 0]
                self._sample_beam(g, logits0, finished, it)
        return spec_log, decode_log, finished

    def _decode_all(self, it):
        # a group that completed prefill THIS iteration sits out one decode:
        # its first write block is ensured at the NEXT iteration's start
        lanes = [(g, lane, slot) for g, lane, slot in
                 self.scheduler.decode_lanes() if g.entered_decode_it != it]
        decode_log = [[g.req.req_id, lane, slot] for g, lane, slot in lanes]
        if not lanes:
            return decode_log, []
        if self.tracer is not None:
            # classify BEFORE sampling appends: a step whose pre-append token
            # count sits below the group's replay high-water mark regenerates
            # work a preempted attempt already did (all K lanes of it)
            traced = set()
            for g, _, _ in lanes:
                if id(g) in traced:
                    continue
                traced.add(id(g))
                self.tracer.on_decode(
                    g, it, g.lanes, g.lanes if g.decode_is_replay() else 0)
        S = self.num_slots
        toks = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        tables = np.full((S, self.max_blocks), NULL_BLOCK, np.int32)
        active = np.zeros(S, bool)
        for g, lane, slot in lanes:
            toks[slot] = g.generated[lane][-1]
            pos[slot] = g.next_pos(lane)
            tables[slot] = self._pad_table(g.tables[lane])
            active[slot] = True
        logits, self.k_pool, self.v_pool = self._decode(
            self.params, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(tables), jnp.asarray(active),
            self.k_pool, self.v_pool)
        self._target_steps += 1
        self._advance_steps += len({id(g) for g, _, _ in lanes})
        if self._mirror is not None:
            ol, self._okcs, self._ovcs = self._mirror["decode_step"](
                self.params, jnp.asarray(toks), jnp.asarray(pos),
                jnp.asarray(active), self._okcs, self._ovcs)
            self._assert_bitwise(logits, ol, f"decode it={it}", rows=active)
        logits_np = np.asarray(logits)

        finished = []
        for g in list(self.scheduler.running):
            if g.phase != "decode" or g.entered_decode_it == it:
                continue                    # groups that just prefilled wait
            if g.lanes == 1:
                self._sample_greedy(g, logits_np, finished, it)
            else:
                self._sample_beam(g, logits, finished, it)
        return decode_log, finished

    def _sample_token(self, g, logits_row, position):
        """Next token for a single-lane group from its f32 logits row.

        ``temperature <= 0`` is the exact historical greedy path. Otherwise:
        scale by temperature, apply top-k then nucleus truncation, softmax in
        f64 (host math — bit-stable across platforms), and invert the CDF at a
        uniform drawn from ``default_rng([seed, position])``. The counter-based
        keying makes every draw a pure function of (request, position): replays
        and preempt-restarts (bit-identical logits) resample identical tokens,
        and no RNG state needs checkpointing or preemption care."""
        req = g.req
        if req.temperature <= 0.0:
            return int(np.argmax(logits_row))
        logits = np.asarray(logits_row, np.float64) / req.temperature
        if 0 < req.top_k < logits.size:
            kth = np.partition(logits, -req.top_k)[-req.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        probs = np.exp(logits - np.max(logits))
        probs /= probs.sum()
        if req.top_p < 1.0:
            order = np.argsort(-logits, kind="stable")
            csum = np.cumsum(probs[order])
            # smallest prefix reaching top_p, always keeping the crossing token
            cut = int(np.searchsorted(csum, req.top_p, side="left")) + 1
            mask = np.zeros(probs.size, bool)
            mask[order[:cut]] = True
            probs = np.where(mask, probs, 0.0)
            probs /= probs.sum()
        u = np.random.default_rng([req.seed, position]).random()
        tok = int(np.searchsorted(np.cumsum(probs), u, side="right"))
        tok = min(tok, probs.size - 1)
        while tok > 0 and probs[tok] == 0.0:   # float-edge guard: never emit a
            tok -= 1                           # truncated (zero-mass) token
        return tok

    def _sample_greedy(self, g, logits_np, finished, it):
        tok = self._sample_token(g, logits_np[g.slots[0]], len(g.generated[0]))
        g.generated[0].append(tok)
        self._tokens_sampled += 1
        eos = g.req.eos_token_id
        if (len(g.generated[0]) >= g.req.max_new_tokens
                or (eos >= 0 and tok == eos)):
            self._finish(g, g.generated[0], None, finished, it)

    def _sample_beam(self, g, logits, finished, it):
        scores, parents, toks, live = self._beam_head("select", g)(
            logits, jnp.asarray(g.slots, jnp.int32),
            jnp.asarray(g.scores, jnp.float32), jnp.asarray(g.live, bool))
        parents = [int(p) for p in np.asarray(parents)]
        old_slot_of = [g.slots[p] for p in parents]
        self.scheduler.reorder_beams(g, parents)
        if self._mirror is not None:
            perm = np.arange(self.num_slots, dtype=np.int32)
            perm[np.asarray(g.slots, np.int32)] = old_slot_of
            self._okcs, self._ovcs = self._mirror["reorder"](
                self._okcs, self._ovcs, jnp.asarray(perm))
        for k, t in enumerate(np.asarray(toks)):
            g.generated[k].append(int(t))
        self._tokens_sampled += g.lanes
        g.scores = np.asarray(scores)
        g.live = np.asarray(live)
        if len(g.generated[0]) >= g.req.max_new_tokens:
            best, score = self._rank_beams(g)
            self._finish(g, best, score, finished, it)

    def _rank_beams(self, g):
        """Host replay of beam_search's GNMT final ranking: finished beams
        count tokens through EOS (clamped to L), unfinished count exactly L.
        Bitwise-identical to the dense path for length_penalty == 1.0."""
        L = float(g.req.max_new_tokens)
        eos = g.req.eos_token_id
        scores = np.asarray(g.scores, np.float32)
        if eos >= 0:
            lengths = []
            for toks in g.generated:
                n = 0
                for t in toks:
                    if t == eos:
                        break
                    n += 1
                lengths.append(min(n + 1.0, L))
        else:
            lengths = [L] * g.lanes
        lengths = np.asarray(lengths, np.float32)
        final = scores / np.power(lengths, np.float32(g.req.length_penalty))
        best = int(np.argmax(final))
        return g.generated[best], float(final[best])

    def _finish(self, g, tokens, score, finished, it):
        if self._spec is not None:
            self._spec.release(g)   # draft pages die with the request
        self.scheduler.finish_group(g)
        n = len(tokens)
        self._tokens_finished += n
        rec = (self.tracer.on_finish(g, it, n)
               if self.tracer is not None else None)
        if rec is not None:
            # ledger-derived bookkeeping (same record the timeline exports)
            out = RequestOutput(
                g.req.req_id, "finished", tokens=list(tokens), score=score,
                ttft_iters=rec.get("ttft_iters"), ttft_ms=rec.get("ttft_ms"),
                finished_it=rec["finished_it"],
                preemptions=rec["preemptions"])
        else:
            out = RequestOutput(
                g.req.req_id, "finished", tokens=list(tokens), score=score,
                ttft_iters=(g.first_token_it - g.req.arrival),
                ttft_ms=g.first_token_ms, finished_it=it,
                preemptions=getattr(g.req, "_preemptions_carry",
                                    g.preemptions))
        self.outputs[g.req.req_id] = out
        finished.append(g.req.req_id)

    def _assert_bitwise(self, paged, dense, what, rows=None):
        a, b = np.asarray(paged), np.asarray(dense)
        if rows is not None:
            a, b = a[rows], b[rows]
        if not np.array_equal(a, b):
            bad = int(np.sum(a != b))
            raise AssertionError(
                f"paged/dense logits diverged ({what}): {bad} of {a.size} "
                f"entries differ; max abs diff "
                f"{float(np.max(np.abs(a - b)))!r}")
        self.mirror_checks += 1

    # ------------------------------------------------------------- metrics
    @property
    def target_steps(self):
        """Target-model program executions so far (prefill chunks + decode
        steps + spec verifies) — speculation's strict-improvement number."""
        return self._target_steps

    def spec_summary(self):
        """Speculation efficiency counters (PERF.md 'target steps per
        token'): ``target_steps_per_token`` divides per-group participations
        in token-advancing steps by tokens sampled, so plain greedy reads
        ~1.0 and speculation ~1/(1 + E[accepted]) — the number the serve-sim
        ``--spec-steps-budget`` gate thresholds."""
        drafted, accepted = self._spec_drafted, self._spec_accepted
        return {
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "wasted_draft_tokens": drafted - accepted,
            "spec_rounds": self._spec_rounds,
            "spec_acceptance_rate": accepted / max(drafted, 1),
            "target_steps": self._target_steps,
            "advance_steps": self._advance_steps,
            "target_steps_per_token":
                self._advance_steps / max(self._tokens_sampled, 1),
        }

    # ------------------------------------------------------- fleet hooks
    def prefix_peek(self, prompt):
        """Read-only fleet-router probe: ``(hit_blocks, hit_tokens)`` this
        replica's prefix cache would serve for ``prompt`` — no stats are
        touched, no blocks are revived, so peeking every replica per arrival
        is free. ``(0, 0)`` when the cache is disabled."""
        if self.prefix_cache is None:
            return (0, 0)
        blocks, hit_tokens = self.prefix_cache.peek(prompt)
        return (len(blocks), hit_tokens)

    def load_view(self) -> dict:
        """Host-side load snapshot for fleet admission/balance decisions:
        queue depth, lane usage, and pool headroom, all exact counters the
        scheduler already maintains (no device sync)."""
        return {"waiting": len(self.scheduler.waiting),
                "running": len(self.scheduler.running),
                "free_slots": len(self.scheduler.free_slots),
                "free_blocks": self.scheduler.allocator.num_free,
                "num_blocks": self.num_blocks,
                "it": self._it}

    def fast_forward(self, it: int):
        """Advance the iteration clock without stepping — the fleet router
        keeps all replicas on one timebase, so a cold replacement joining at
        router iteration ``it`` must not restart from 0 (its arrivals and
        latency iteration-counts would otherwise be skewed)."""
        self._it = max(self._it, int(it))

    # ------------------------------------------------------- warm restart
    _OUT_FIELDS = ("req_id", "status", "tokens", "score", "refusal",
                   "ttft_iters", "ttft_ms", "finished_it", "preemptions")

    def geometry(self) -> dict:
        """Everything the paged programs' shapes (and therefore the KV pool
        bytes) depend on — a warm restart into a different geometry would
        read pages laid out for another engine, so restore validates this."""
        c = self.model.config
        return {"num_slots": self.num_slots, "block_size": self.block_size,
                "num_blocks": self.num_blocks,
                "max_model_len": self.max_model_len,
                "prefill_chunk": self.prefill_chunk, "tp": self.tp,
                "n_layer": int(c.n_layer), "n_head": int(c.n_head),
                "head_dim": int(c.head_dim),
                "compute_dtype": str(jnp.dtype(c.compute_dtype).name)}

    def state_dict(self) -> dict:
        """Warm-restart snapshot: quiesces the scheduler (preempting every
        running group so its prefill frontier parks in the prefix cache),
        then captures the KV pools, the allocator/cache/scheduler ledgers,
        and the request bookkeeping as host data. The restored replica remaps
        parked prompt pages through the prefix machinery instead of
        re-prefilling (docs/resilience.md)."""
        from .scheduler import pack_request  # noqa: F401  (re-export site)
        self.scheduler.quiesce()
        if self._spec is not None:
            # draft state is best-effort: the restored replica re-drafts from
            # each request's committed context (token-identity is unaffected)
            self._spec.drop_all()
        return {
            "geometry": self.geometry(),
            "scheduler": self.scheduler.state_dict(),
            "it": self._it,
            "order": list(self._order),
            "outputs": [{k: getattr(o, k) for k in self._OUT_FIELDS}
                        for o in self.outputs.values()],
            "tokens_sampled": self._tokens_sampled,
            "tokens_finished": self._tokens_finished,
            "k_pool": np.asarray(self.k_pool),
            "v_pool": np.asarray(self.v_pool),
        }

    def load_state_dict(self, state: dict) -> None:
        """Rejoin warm from a ``state_dict`` snapshot. Refuses (ValueError) a
        snapshot whose geometry does not match this engine — page indices and
        pool bytes are only meaningful under the exact same layout."""
        mine, theirs = self.geometry(), state["geometry"]
        if mine != theirs:
            diff = {k: (theirs.get(k), mine.get(k))
                    for k in sorted(set(mine) | set(theirs))
                    if theirs.get(k) != mine.get(k)}
            raise ValueError(f"serving warm restart refused: checkpoint "
                             f"geometry does not match this engine "
                             f"(checkpoint vs live): {diff}")
        self.scheduler.load_state_dict(state["scheduler"])
        self._it = int(state["it"])
        self._order = list(state["order"])
        self.outputs = {d["req_id"]: RequestOutput(**d)
                        for d in state["outputs"]}
        self._tokens_sampled = int(state["tokens_sampled"])
        self._tokens_finished = int(state["tokens_finished"])
        c = self.model.config
        self.k_pool = jnp.asarray(state["k_pool"], c.compute_dtype)
        self.v_pool = jnp.asarray(state["v_pool"], c.compute_dtype)
        if self._mesh is not None:
            import jax
            self.k_pool = jax.device_put(self.k_pool,
                                         self._raw["pool_sharding"])
            self.v_pool = jax.device_put(self.v_pool,
                                         self._raw["pool_sharding"])
        # wall-clock bookkeeping restarts: TTFT-ms of still-pending requests
        # is measured from the rejoin (iteration-time TTFT is exact)
        now = time.perf_counter()
        self._submit_ms = {r.req_id: now
                           for r, _ in self.scheduler.waiting}
        if self.tracer is not None:
            # requeued requests re-enter this replica's ledger fresh — their
            # pre-kill history died with the old process, and TTFT after a
            # warm restart is TTFT as experienced from the rejoin
            for r, _ in self.scheduler.waiting:
                self.tracer.on_submit(r)
        self._start_wall = None

    # ------------------------------------------------------------------ lint
    def lint_programs(self, sample_batch=None):
        """(name, jitted, example_args, manifest) for the lint registry —
        same contract as runtime engine.lint_programs. Fresh example pools so
        capture never lowers against donated-dead buffers."""
        c = self.model.config
        compute = {"bfloat16": "bf16", "float16": "f16"}.get(
            jnp.dtype(c.compute_dtype).name, "f32")
        manifest = {
            "compute_dtype": compute,
            "donation": {"check_unusable": True, "min_undonated_bytes": 1024},
            "strict": True,
            "any_reduction": {"max": 0},
        }
        copy_manifest = manifest
        if self.tp > 1:
            # head-sharded programs: exactly one f32 proj psum per layer and
            # nothing else on the wire — threshold 0 so even a tiny stray
            # resharding collective fails the budget, not just a large one
            manifest = {
                "compute_dtype": compute,
                "donation": {"check_unusable": True,
                             "min_undonated_bytes": 1024},
                "strict": True,
                "small_element_threshold": 0,
                "collectives": {"all-reduce": {"min": c.n_layer,
                                               "max": c.n_layer,
                                               "dtypes": ["f32"]}},
            }
            copy_manifest = {
                "compute_dtype": compute,
                "donation": {"check_unusable": True,
                             "min_undonated_bytes": 1024},
                "strict": True,
                "small_element_threshold": 0,
                "any_reduction": {"max": 0},
            }
        S, MB, C, P = (self.num_slots, self.max_blocks, self.prefill_chunk,
                       self.copy_width)
        pool_shape = (c.n_layer, self.num_blocks, self.block_size,
                      c.n_head, c.head_dim)
        kp = jnp.zeros(pool_shape, c.compute_dtype)
        vp = jnp.zeros(pool_shape, c.compute_dtype)
        zs = jnp.zeros(S, jnp.int32)
        entries = [
            ("serve_decode_step", self._raw["decode_step"],
             (self.params, zs, zs, jnp.zeros((S, MB), jnp.int32),
              jnp.zeros(S, bool), kp, vp), manifest),
            ("serve_prefill_chunk", self._raw["prefill_chunk"],
             (self.params, jnp.zeros((1, C), jnp.int32), jnp.int32(0),
              jnp.int32(1), jnp.zeros(MB, jnp.int32), kp, vp), manifest),
            ("serve_copy_blocks", self._raw["copy_blocks"],
             (kp, vp, jnp.zeros(P, jnp.int32), jnp.zeros(P, jnp.int32)),
             copy_manifest),
        ]
        if self._spec is not None:
            D = self.spec_k + 1
            entries.append(
                ("serve_spec_verify", self._raw["spec_verify"],
                 (self.params, jnp.zeros((S, D), jnp.int32), zs, zs,
                  jnp.zeros((S, MB), jnp.int32), jnp.zeros(S, bool),
                  kp, vp), manifest))
            entries.extend(self._spec.lint_programs(manifest))
        return entries

    def memory_manifest(self):
        """The memory analogue of ``lint_programs`` (utils/hbm, docs/hbm.md):
        the serving engine's persistent device residents — compute-dtype
        params (head-sharded under tp) and the paged KV pools, plus the draft
        model's own params/pool when speculation is live. Geometry carries the
        closed-form pool arithmetic (2 x L x blocks x block_size x H x Hd x
        itemsize, head-sharded over tp) the modeled view predicts from."""
        import jax
        from ..utils.hbm import leaf_signature
        c = self.model.config
        itemsize = int(jnp.dtype(c.compute_dtype).itemsize)
        leaves = jax.tree_util.tree_leaves(self.params)
        psi = sum(int(np.prod(l.shape)) if l.shape else 1 for l in leaves)
        per_device = sum(leaf_signature(l)[2] for l in leaves)
        classes = {"params": self.params,
                   "kv_pool": [self.k_pool, self.v_pool]}
        geometry = {
            "kind": "serving",
            "psi": psi,
            "param_itemsize": itemsize,
            "tp": int(self.tp),
            "param_per_device_fraction": per_device / max(psi * itemsize, 1),
            "pool": {"n_layer": int(c.n_layer),
                     "num_blocks": int(self.num_blocks),
                     "block_size": int(self.block_size),
                     "n_head": int(c.n_head), "head_dim": int(c.head_dim),
                     "itemsize": itemsize,
                     "shard_factor": int(self.tp) if self.tp > 1 else 1},
        }
        if self._spec is not None:
            dc = self._spec.model.config
            d_item = int(jnp.dtype(dc.compute_dtype).itemsize)
            d_leaves = jax.tree_util.tree_leaves(self._spec.params)
            classes["draft_params"] = self._spec.params
            classes["draft_pool"] = [self._spec.k_pool, self._spec.v_pool]
            geometry["draft"] = {
                "psi": sum(int(np.prod(l.shape)) if l.shape else 1
                           for l in d_leaves),
                "param_itemsize": d_item,
                "pool": {"n_layer": int(dc.n_layer),
                         "num_blocks": int(self._spec.k_pool.shape[1]),
                         "block_size": int(self._spec.block_size),
                         "n_head": int(dc.n_head),
                         "head_dim": int(dc.head_dim),
                         "itemsize": d_item},
            }
        return {"classes": classes, "geometry": geometry}
