"""``ds-tpu serve-sim`` — deterministic request-replay driver for the engine.

Replays a seeded synthetic trace (mixed prompt/generation lengths, staggered
arrivals, a sprinkle of beam-search requests) through InferenceEngine on the
CPU mesh and asserts the three serving invariants:

1. **Zero recompiles after warmup** — every serve:* program compiles exactly
   once for the whole trace (compile watchdog through TelemetrySession).
2. **Bit-exact paging** — the engine runs with ``mirror=True``, so every
   prefill chunk and decode step is compared bitwise against the dense-cache
   oracle (serve/oracle.py); one diverging ulp fails the run.
3. **Deterministic schedule** — with ``--replay``, the whole trace is run
   twice on fresh engines and the per-iteration schedule logs must be
   byte-identical (json.dumps) and the outputs token-identical.
4. **Exact waste decomposition** — the request-trace ledger (on by default
   here) must classify every scheduled token as useful or replayed, summing
   to the schedule log's own token count exactly.
5. **SLO attainment** (with ``--slo-ttft-ms`` / ``--slo-tpot-ms``) — any
   finished request violating a configured SLO fails the run nonzero.

Serving/* scalars (occupancy, TTFT, goodput) land in the TelemetrySession's
scalars.jsonl. ``--json`` writes a machine-readable report whose
``deterministic`` subtree is byte-stable across runs (CI diffs it, mirroring
``ds-tpu lint --json``); ``--dump-ledger`` writes the raw ledger bundle for
``ds-tpu serve-timeline``. Exit 0 = all invariants held.
"""

import argparse
import json
import sys


def synth_trace(n, *, vocab_size, max_model_len, seed, beam_every=7,
                include_infeasible=False, shared_prefix_len=0,
                arrival_scale=1.0, arrival_process=None):
    """Seeded mixed trace: prompts 1..~ML/2, generations 1..~ML/4, arrivals
    staggered 0-2 iterations apart, every ``beam_every``-th request beam-4.

    With ``shared_prefix_len > 0`` every prompt starts with the SAME seeded
    ``shared_prefix_len``-token system prompt followed by a per-request tail —
    the canonical prefix-cache workload. ``arrival_scale`` scales the seeded
    inter-arrival gaps (0.0 = every request arrives at once, the
    past-saturation fleet workload) without perturbing the RNG stream. The
    default path draws nothing extra, so existing seeded traces (and their
    goldens) are untouched.

    ``arrival_process=("poisson", rate)`` replaces the staggered gaps with a
    seeded Poisson process of intensity ``rate`` requests/iteration
    (exponential inter-arrival gaps on a float clock, floored to the
    iteration domain). Arrivals bunch, so a rate past the fleet's service
    capacity drives the waiting queues through any --max-queue-depth bound —
    the load-shedding workload. Deterministic per seed like everything else
    here; it is a DIFFERENT mode (the extra draw shifts the RNG stream), so
    default-mode traces are still byte-identical to older releases."""
    import numpy as np
    from .scheduler import Request

    rng = np.random.RandomState(seed)
    P = int(shared_prefix_len)
    if P >= max_model_len:
        raise ValueError("shared_prefix_len must leave room for a tail and "
                         f"generation (got {P} >= {max_model_len})")
    system_prompt = rng.randint(0, vocab_size, size=P).tolist() if P else []
    reqs, arrival, clock = [], 0, 0.0
    for i in range(n):
        if arrival_process is not None:
            kind, rate = arrival_process
            if kind != "poisson":
                raise ValueError(f"unknown arrival process {kind!r}")
            clock += float(rng.exponential(1.0 / rate))
            arrival = int(clock)
        else:
            arrival += int(int(rng.randint(0, 3)) * arrival_scale)
        T0 = P + int(rng.randint(1, max(2, (max_model_len - P) // 2)))
        L = int(rng.randint(1, max(2, max_model_len // 4)))
        if T0 + L > max_model_len:          # keep the trace feasible
            L = max_model_len - T0
        K = 4 if (beam_every and i % beam_every == beam_every - 1) else 1
        prompt = system_prompt + rng.randint(0, vocab_size,
                                             size=T0 - P).tolist()
        reqs.append(Request(f"req{i:03d}", prompt, L, arrival=arrival,
                            num_beams=K))
    if include_infeasible:
        prompt = rng.randint(0, vocab_size, size=max_model_len).tolist()
        reqs.append(Request("req-too-long", prompt, max_model_len,
                            arrival=0))
    return reqs


def _p50(values):
    """Deterministic iteration-domain median: upper median of sorted ints."""
    vals = sorted(v for v in values if v is not None)
    return vals[len(vals) // 2] if vals else None


def _model_params(args):
    """Build the sim model + params once — fleet replicas must SHARE the
    model object so the paged program set builds (and compiles) once for the
    whole fleet (the serve/paged.py build memo keys on it)."""
    import jax
    import jax.numpy as jnp

    from ..models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=args.vocab_size, n_positions=args.max_model_len,
                     n_embd=args.n_embd, n_layer=args.n_layer,
                     n_head=args.n_head, compute_dtype=jnp.float32,
                     loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))
    return model, params


def _build(args, telemetry, prefix_cache=None, sharding=None, speculate=None,
           model_params=None, host_id=0):
    from .engine import InferenceEngine

    pc = args.prefix_cache if prefix_cache is None else prefix_cache
    tp = args.sharding if sharding is None else sharding
    spec_k = args.speculate if speculate is None else speculate
    # the dense-cache oracle cannot mirror any of these modes (skipped
    # prefills / reduction-order drift / multi-token commits), and the
    # engine constructor enforces that
    mirror = not args.no_mirror and not pc and tp <= 1 and not spec_k
    model, params = (model_params if model_params is not None
                     else _model_params(args))
    speculation = None
    if spec_k:
        import jax

        # self-draft by default (same model + params -> near-total acceptance,
        # the deterministic upper bound the strict-step gate relies on); a
        # non-negative --spec-draft-seed re-draws the draft params so the
        # rejection/rollback path gets exercised too
        dparams = (model.init(jax.random.PRNGKey(args.spec_draft_seed))
                   if args.spec_draft_seed >= 0 else params)
        speculation = {"enabled": True, "draft_model": model,
                       "draft_params": dparams, "max_draft_tokens": spec_k}
    engine = InferenceEngine(
        model, params, num_slots=args.slots, block_size=args.block_size,
        num_blocks=args.num_blocks, max_model_len=args.max_model_len,
        prefill_chunk=args.prefill_chunk, use_pallas=args.pallas,
        telemetry=telemetry, mirror=mirror, prefix_cache=pc,
        sharding={"model": tp} if tp > 1 else None,
        speculation=speculation,
        request_trace=None if args.no_trace else {
            "enabled": True,
            "capacity": max(args.requests + 1, 256),
            "slo": {"ttft_ms": args.slo_ttft_ms, "tpot_ms": args.slo_tpot_ms},
            "host_id": host_id,
        })
    return engine


def _trace(args):
    return synth_trace(args.requests, vocab_size=args.vocab_size,
                       max_model_len=args.max_model_len, seed=args.seed,
                       include_infeasible=args.include_infeasible,
                       shared_prefix_len=args.shared_prefix,
                       arrival_scale=args.arrival_scale,
                       arrival_process=args.arrival_process)


def _report(args, trace, outputs, logs, tracer, waste, slo, failures,
            cache_stats=None, ttft_compare=None, fleet_merge_exact=None,
            spec_summary=None, steps_compare=None):
    """Machine-readable serve-sim report. The ``deterministic`` subtree is a
    pure function of the seeded trace (iteration-domain latencies, token
    counts, waste split — byte-stable across runs on one platform); ``wall``
    carries the ms-domain percentiles and SLO attainment, which vary run to
    run. CI diffs the deterministic part."""
    recs = {}
    if tracer is not None:
        recs = {r["req_id"]: r for r in tracer.requests}
    table = []
    for o in sorted(outputs, key=lambda o: o.req_id):
        r = recs.get(o.req_id, {})
        table.append({
            "req_id": o.req_id,
            "status": o.status,
            "n_tokens": len(o.tokens),
            "ttft_iters": o.ttft_iters,
            "queue_delay_iters": r.get("queue_delay_iters"),
            "e2e_iters": r.get("e2e_iters"),
            "preemptions": o.preemptions,
            "slo_violations": r.get("slo_violations", []),
        })
    det = {
        "args": {"requests": args.requests, "seed": args.seed,
                 "slots": args.slots, "block_size": args.block_size,
                 "num_blocks": args.num_blocks,
                 "max_model_len": args.max_model_len,
                 "prefill_chunk": args.prefill_chunk,
                 "shared_prefix": args.shared_prefix,
                 "sharding": args.sharding,
                 "prefix_cache": bool(args.prefix_cache),
                 "speculate": args.speculate,
                 "spec_draft_seed": args.spec_draft_seed},
        "n_finished": sum(1 for o in outputs if o.status == "finished"),
        "n_refused": sum(1 for o in outputs if o.status == "refused"),
        "iterations": len(logs),
        "preemptions": sum(len(l["preempted"]) for l in logs),
        "requests": table,
        "waste": waste,
    }
    if cache_stats is not None:
        # pure functions of the seeded schedule -> deterministic subtree
        det["prefix_cache"] = cache_stats
    if spec_summary is not None:
        # acceptance counters and step ratios are pure functions of the
        # seeded schedule (host argmax over deterministic logits)
        det["speculation"] = spec_summary
    if steps_compare is not None:
        det["target_steps"] = steps_compare
    if ttft_compare is not None:
        det["ttft_p50_iters"] = ttft_compare
    if fleet_merge_exact is not None:
        # exact-by-construction boolean (sketch merge == single stream), so
        # it belongs in the byte-stable subtree despite wall-derived inputs
        det["fleet_merge_exact"] = bool(fleet_merge_exact)
    wall = {}
    if tracer is not None:
        wall["percentiles"] = tracer.percentiles()
        wall["slo"] = slo
    return {"version": 1, "kind": "serve_sim_report",
            "deterministic": det, "wall": wall,
            "failures": list(failures)}


def _parse_kill(ap, spec, fleet):
    try:
        it_s, slot_s = spec.split(":")
        it, slot = int(it_s), int(slot_s)
    except ValueError:
        ap.error(f"--kill wants IT:REPLICA, got {spec!r}")
    if not fleet:
        ap.error("--kill needs --fleet N")
    if not 0 <= slot < fleet:
        ap.error(f"--kill replica {slot} out of range for --fleet {fleet}")
    if it < 0:
        ap.error(f"--kill iteration must be >= 0, got {it}")
    return (it, slot)


def _run_fleet(args, session, model_params, *, policy, cold_failover,
               snapshot_dir):
    """One fleet pass over the seeded trace: N fresh replicas sharing one
    model/params (one program build for the whole fleet), replica 0 carrying
    the telemetry session (a second replica registering the same program
    signature would read as a recompile to the watchdog)."""
    from .request_trace import RequestTracer
    from .router import FleetRouter

    engines = [_build(args, session if slot == 0 else None,
                      prefix_cache=True, model_params=model_params,
                      host_id=slot)
               for slot in range(args.fleet)]

    def build_replacement(slot):
        return _build(args, None, prefix_cache=True,
                      model_params=model_params, host_id=slot)

    front = RequestTracer(capacity=max(args.requests + 1, 256),
                          host_id=args.fleet)
    router = FleetRouter(
        engines, policy=policy, affinity_weight=args.affinity_weight,
        max_queue_depth=args.max_queue_depth,
        occupancy_cap=args.occupancy_cap, kill_schedule=args.kill,
        build_replacement=build_replacement, snapshot_dir=snapshot_dir,
        cold_failover=cold_failover, telemetry=session, tracer=front,
        run_id=f"fleet_seed{args.seed}")
    outputs, transcript = router.run(_trace(args))
    return router, outputs, transcript


def _fleet_single_stream(bundles, ps=(50, 95, 99)):
    """Percentiles of ONE sketch stream over every finished record in every
    bundle — the ground truth the merged fleet sketches must bitwise equal
    (the HistogramSketch mergeability contract, asserted every fleet run)."""
    from .request_trace import HistogramSketch, LATENCY_METRICS
    singles = {m: HistogramSketch() for m in LATENCY_METRICS}
    for b in bundles:
        for rec in (b or {}).get("requests") or []:
            if rec.get("status") == "finished":
                for m in LATENCY_METRICS:
                    singles[m].add(rec.get(m))
    out = {}
    for m in sorted(singles):
        if not singles[m].count:
            continue
        for p in ps:
            out[f"{m}_p{p:g}"] = singles[m].percentile(p)
    return out


def _fleet_main(args):
    import tempfile

    from ..utils.cluster import fleet_latency_summary, fleet_serving_totals
    from ..utils.telemetry import TelemetrySession

    if args.compare_cold_failover and not args.kill:
        print("serve-sim: --compare-cold-failover needs --kill",
              file=sys.stderr)
        return 2

    trace = _trace(args)
    session = TelemetrySession(output_path=args.output, job_name="serve_sim")
    model_params = _model_params(args)
    snapshot_dir = args.snapshot_dir or tempfile.mkdtemp(
        prefix="ds_tpu_fleet_snap_")

    router, outputs, transcript = _run_fleet(
        args, session, model_params, policy=args.fleet_policy,
        cold_failover=False, snapshot_dir=snapshot_dir)

    failures = []
    finished = [o for o in outputs if o.status == "finished"]
    refused = [o for o in outputs if o.status == "refused"]
    shed = [o for o in outputs if o.status == "shed"]

    # fleet invariant 1: one compile per program for the WHOLE fleet — the
    # replicas share the program build, so N replicas cost one compile set
    serve_names = sorted(n for n in session.watchdog.records
                         if n.startswith("serve:"))
    for name in serve_names:
        n_r = session.watchdog.recompiles(name)
        if n_r:
            failures.append(f"{name}: {n_r} recompile(s) after warmup")
    if not serve_names:
        failures.append("no serve:* programs reached the compile watchdog")

    # fleet invariant 2: conservation — every submitted request comes back
    # exactly once, finished or EXPLICITLY refused/shed; kills lose nothing
    want = sorted(r.req_id for r in trace)
    got = sorted(o.req_id for o in outputs)
    if want != got:
        lost = sorted(set(want) - set(got))
        dups = len(got) - len(set(got))
        failures.append(f"request conservation violated: {len(lost)} "
                        f"lost / {dups} duplicated "
                        f"({', '.join(lost[:8])})")
    bad = [o.req_id for o in outputs
           if o.status not in ("finished", "refused", "shed")]
    if bad:
        failures.append(f"unexpected terminal status on {len(bad)} "
                        f"request(s): {', '.join(bad[:8])}")

    # fleet invariant 3: EXACT fleet percentiles — the merged per-replica
    # sketches must bitwise-equal the single-stream sketch over the
    # concatenated ledger (retired replicas and the front door included)
    bundles = router.bundles()
    fleet_lat = fleet_latency_summary(bundles, ps=(50, 95, 99))
    single_lat = _fleet_single_stream(bundles, ps=(50, 95, 99))
    fleet_merge_exact = fleet_lat == single_lat
    if not fleet_merge_exact:
        failures.append("fleet percentile merge diverged from the "
                        "single-stream sketch over the concatenated ledger")

    # fleet invariant 4: merged goodput floor (kills bill restart_replay
    # badput on a synthetic per-iteration clock — pure schedule function)
    gp = router.fleet_goodput()
    if args.fleet_goodput_floor and not (
            gp["goodput_fraction"] >= args.fleet_goodput_floor):
        failures.append(
            f"goodput_fleet fraction {gp['goodput_fraction']:.4f} under the "
            f"--fleet-goodput-floor {args.fleet_goodput_floor}")

    # fleet invariant 5: the SLO gate over FLEET-MERGED percentiles
    if args.slo_ttft_ms and fleet_lat.get("ttft_ms_p99", 0.0) > args.slo_ttft_ms:
        failures.append(f"fleet ttft_ms_p99 {fleet_lat['ttft_ms_p99']:.2f} "
                        f"over the {args.slo_ttft_ms} ms SLO")
    if args.slo_tpot_ms and fleet_lat.get("tpot_ms_p99", 0.0) > args.slo_tpot_ms:
        failures.append(f"fleet tpot_ms_p99 {fleet_lat['tpot_ms_p99']:.2f} "
                        f"over the {args.slo_tpot_ms} ms SLO")

    # fleet invariant 6 (optional): affinity must BUY something over
    # round-robin on this trace — identical tokens, strictly fewer total
    # prefill chunks (the fleet-wide cache-reuse win), strictly better
    # fleet p50 TTFT in the deterministic iteration domain
    affinity_compare = None
    if args.compare_affinity:
        router_rr, outs_rr, _ = _run_fleet(
            args, None, model_params, policy="round_robin",
            cold_failover=False, snapshot_dir=snapshot_dir)
        t_aff = {o.req_id: (o.status, o.tokens) for o in outputs}
        t_rr = {o.req_id: (o.status, o.tokens) for o in outs_rr}
        if t_aff != t_rr:
            diff = sorted(r for r in t_aff if t_aff[r] != t_rr.get(r))
            failures.append(
                f"routing policy changed tokens on {len(diff)} request(s): "
                f"{', '.join(diff[:8])}")
        chunks_aff = sum(router.prefill_chunks)
        chunks_rr = sum(router_rr.prefill_chunks)
        p50_aff = _p50(o.ttft_iters for o in outputs
                       if o.status == "finished")
        p50_rr = _p50(o.ttft_iters for o in outs_rr
                      if o.status == "finished")
        affinity_compare = {
            "prefill_chunks": {"affinity": chunks_aff,
                               "round_robin": chunks_rr},
            "ttft_p50_iters": {"affinity": p50_aff, "round_robin": p50_rr},
        }
        if not chunks_aff < chunks_rr:
            failures.append(
                f"affinity routing did not strictly reduce prefill chunks: "
                f"{chunks_aff} vs round-robin {chunks_rr}")
        if p50_aff is None or p50_rr is None or not p50_aff < p50_rr:
            failures.append(
                f"affinity routing did not strictly improve fleet p50 TTFT: "
                f"{p50_aff} vs round-robin {p50_rr} iters")

    # fleet invariant 7 (optional): warm failover must strictly beat a cold
    # successor on the same kill schedule — identical tokens, fewer chunks
    failover_compare = None
    if args.compare_cold_failover:
        router_cold, outs_cold, _ = _run_fleet(
            args, None, model_params, policy=args.fleet_policy,
            cold_failover=True, snapshot_dir=snapshot_dir)
        t_warm = {o.req_id: (o.status, o.tokens) for o in outputs}
        t_cold = {o.req_id: (o.status, o.tokens) for o in outs_cold}
        if t_warm != t_cold:
            diff = sorted(r for r in t_warm if t_warm[r] != t_cold.get(r))
            failures.append(
                f"failover mode changed tokens on {len(diff)} request(s): "
                f"{', '.join(diff[:8])}")
        chunks_warm = sum(router.prefill_chunks)
        chunks_cold = sum(router_cold.prefill_chunks)
        failover_compare = {"prefill_chunks": {"warm": chunks_warm,
                                               "cold": chunks_cold}}
        if not chunks_warm < chunks_cold:
            failures.append(
                f"warm failover did not strictly reduce prefill chunks: "
                f"{chunks_warm} vs cold {chunks_cold}")

    # fleet invariant 8 (poisson arrivals): shed determinism — the shed set
    # (and so the shed RATE) must be a pure function of the seeded trace and
    # the admission bounds. Re-route the identical trace through a fresh
    # router (shared model/params, so no recompiles) and require the same
    # terminal status on every request.
    shed_rate = len(shed) / max(len(trace), 1)
    if args.arrival_process is not None:
        _, outs_re, _ = _run_fleet(
            args, None, model_params, policy=args.fleet_policy,
            cold_failover=False, snapshot_dir=snapshot_dir)
        st = {o.req_id: o.status for o in outputs}
        st_re = {o.req_id: o.status for o in outs_re}
        if st != st_re:
            diff = sorted(r for r in st if st[r] != st_re.get(r))
            failures.append(
                f"shed determinism violated: terminal status changed on "
                f"{len(diff)} request(s) across identical replays "
                f"({', '.join(diff[:8])})")
        shed_re = sum(1 for s in st_re.values() if s == "shed")
        if shed_re != len(shed):
            failures.append(f"shed rate not deterministic: {len(shed)} vs "
                            f"{shed_re} shed across identical replays")

    spec_totals = fleet_serving_totals(bundles)

    if args.transcript:
        with open(args.transcript, "w") as f:
            f.write(json.dumps(transcript, sort_keys=True,
                               separators=(",", ":")))

    if args.dump_ledger:
        router.tracer.dump(args.dump_ledger)

    if args.json_out:
        det = {
            "args": {"requests": args.requests, "seed": args.seed,
                     "fleet": args.fleet, "fleet_policy": args.fleet_policy,
                     "affinity_weight": args.affinity_weight,
                     "max_queue_depth": args.max_queue_depth,
                     "occupancy_cap": args.occupancy_cap,
                     "arrival_scale": args.arrival_scale,
                     "arrival": args.arrival,
                     "shared_prefix": args.shared_prefix,
                     "kill": [list(k) for k in args.kill],
                     "speculate": args.speculate},
            "n_finished": len(finished),
            "n_refused": len(refused),
            "n_shed": len(shed),
            "shed_rate": round(shed_rate, 6),
            "kills": router.kills_applied,
            "prefill_chunks": list(router.prefill_chunks),
            "total_prefill_chunks": sum(router.prefill_chunks),
            "goodput_fleet_fraction": gp["goodput_fraction"],
            "fleet_merge_exact": bool(fleet_merge_exact),
            "serving_totals": spec_totals,
        }
        if affinity_compare is not None:
            det["affinity_compare"] = affinity_compare
        if failover_compare is not None:
            det["failover_compare"] = failover_compare
        report = {"version": 1, "kind": "serve_fleet_report",
                  "deterministic": det,
                  "wall": {"fleet_latency": fleet_lat,
                           "goodput_fleet": gp},
                  "failures": list(failures)}
        blob = json.dumps(report, sort_keys=True, separators=(",", ":"))
        if args.json_out == "-":
            print(blob)
        else:
            with open(args.json_out, "w") as f:
                f.write(blob)

    session.close()

    print(f"serve-sim: fleet={args.fleet} policy={args.fleet_policy}: "
          f"{len(finished)} finished / {len(refused)} refused / "
          f"{len(shed)} shed of {len(trace)} requests, "
          f"{router.kills_applied} replica kill(s)")
    print(f"  prefill chunks   : {sum(router.prefill_chunks)} total "
          f"{list(router.prefill_chunks)} per slot")
    print(f"  fleet merge      : "
          f"{'exact' if fleet_merge_exact else 'DIVERGED'} over "
          f"{len(bundles)} bundles")
    print(f"  goodput_fleet    : {gp['goodput_fraction']:.4f} "
          f"({gp['class_seconds']['restart_replay']:.1f}s restart_replay "
          f"across {gp['n_hosts']} slots)")
    tot = spec_totals["totals"]
    if tot.get("drafted_tokens"):
        print(f"  fleet speculation: {tot['accepted_draft_tokens']} of "
              f"{tot['drafted_tokens']} drafts accepted, "
              f"{tot['wasted_draft_tokens']} wasted")
    if affinity_compare is not None:
        pc, tp = (affinity_compare["prefill_chunks"],
                  affinity_compare["ttft_p50_iters"])
        print(f"  affinity compare : chunks {pc['affinity']} vs "
              f"round-robin {pc['round_robin']}, p50 TTFT "
              f"{tp['affinity']} vs {tp['round_robin']} iters")
    if failover_compare is not None:
        fc = failover_compare["prefill_chunks"]
        print(f"  failover compare : warm {fc['warm']} vs cold "
              f"{fc['cold']} prefill chunks")
    if args.transcript:
        print(f"  transcript       : {args.transcript}")
    print(f"  scalars          : {session.monitor.log_dir}/scalars.jsonl")

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("serve-sim: OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds-tpu serve-sim",
        description="deterministic serving-engine replay with bitwise oracle "
                    "+ zero-recompile assertions")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=257)
    ap.add_argument("--max-model-len", type=int, default=256)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--vocab-size", type=int, default=128)
    ap.add_argument("--n-embd", type=int, default=32)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=2)
    ap.add_argument("--no-mirror", action="store_true",
                    help="skip the dense-oracle bitwise lockstep (faster)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the cross-request prefix cache (disables the "
                         "mirror oracle: remapped prefixes skip the prefill "
                         "the oracle would need to reproduce)")
    ap.add_argument("--shared-prefix", type=int, default=0, metavar="P",
                    help="give every request the same seeded P-token system "
                         "prompt (the prefix-cache workload); 0 = off")
    ap.add_argument("--compare-prefix-cache", action="store_true",
                    help="run the trace cache-off AND cache-on, assert token "
                         "identity and a STRICT cache-on p50 TTFT (iters) "
                         "improvement (implies --prefix-cache)")
    ap.add_argument("--speculate", type=int, nargs="?", const=4, default=0,
                    metavar="K",
                    help="speculative decoding with a K-token self-draft "
                         "(disables the mirror oracle: the K+1-wide verify is "
                         "token-identical, not bitwise); bare flag = K=4")
    ap.add_argument("--compare-speculate", action="store_true",
                    help="run the trace speculation-off AND speculation-on, "
                         "assert byte-identical tokens and STRICTLY fewer "
                         "target-model steps (implies --speculate)")
    ap.add_argument("--spec-draft-seed", type=int, default=-1, metavar="S",
                    help="re-draw the draft params from seed S instead of "
                         "self-drafting, to exercise rejection/rollback "
                         "(-1 = self-draft)")
    ap.add_argument("--spec-steps-budget", type=float, default=0.0,
                    metavar="R",
                    help="with --speculate: fail unless target_steps_per_"
                         "token < R (0 = not gated; PERF.md defines the "
                         "metric)")
    ap.add_argument("--sharding", type=int, default=1, metavar="TP",
                    help="shard the KV pool + decode programs over TP model-"
                         "axis devices by attention head (disables the "
                         "mirror oracle: per-layer psum is token-identical, "
                         "not bitwise)")
    ap.add_argument("--verify-unsharded", action="store_true",
                    help="with --sharding > 1: also run the trace on a "
                         "single-chip engine and assert token-identical "
                         "outputs (greedy and beam)")
    ap.add_argument("--pallas", action="store_true",
                    help="use the Pallas paged-decode kernel (interpret mode "
                         "on CPU)")
    ap.add_argument("--replay", action="store_true",
                    help="run the trace twice and assert byte-identical "
                         "schedules")
    ap.add_argument("--include-infeasible", action="store_true",
                    help="append a request that can never fit (exercises "
                         "admission refusal)")
    ap.add_argument("--output", default="serve_sim_telemetry",
                    help="TelemetrySession output dir for Serving/* scalars")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable the request-trace ledger (the engine's "
                         "tracer gate is None — the HLO-identity mode)")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="TTFT SLO in ms (0 = not gated); any finished "
                         "request over the limit fails the run")
    ap.add_argument("--slo-tpot-ms", type=float, default=0.0,
                    help="per-output-token SLO in ms (0 = not gated)")
    ap.add_argument("--json", dest="json_out", default=None, metavar="PATH",
                    help="write the machine-readable report here ('-' = "
                         "stdout); its 'deterministic' subtree is byte-"
                         "stable across runs")
    ap.add_argument("--dump-ledger", default=None, metavar="PATH",
                    help="write the raw request-trace ledger bundle here "
                         "(input for `ds-tpu serve-timeline`)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="route the trace across N engine replicas through "
                         "the FleetRouter (serve/router.py) instead of one "
                         "engine; implies --prefix-cache (affinity routing "
                         "peeks it)")
    ap.add_argument("--fleet-policy", default=None,
                    choices=["affinity", "least_loaded", "round_robin"],
                    help="fleet routing policy (default: affinity)")
    ap.add_argument("--affinity-weight", type=float, default=1.0,
                    help="cached-prefix blocks are worth this many queue "
                         "slots in the affinity routing score")
    ap.add_argument("--max-queue-depth", type=int, default=0,
                    help="per-replica waiting-queue admission bound; an "
                         "arrival with every replica at the bound is SHED "
                         "(0 = unbounded)")
    ap.add_argument("--occupancy-cap", type=float, default=1.0,
                    help="per-replica pool-occupancy admission cap in "
                         "(0, 1]; 1.0 = occupancy shedding off")
    ap.add_argument("--compare-affinity", action="store_true",
                    help="run the fleet trace affinity AND round_robin, "
                         "assert token identity, STRICTLY fewer total "
                         "prefill chunks and STRICTLY better fleet p50 TTFT "
                         "(iters) with affinity on")
    ap.add_argument("--kill", action="append", default=None,
                    metavar="IT:REPLICA",
                    help="kill replica REPLICA when the router clock reaches "
                         "IT and fail it over (repeatable)")
    ap.add_argument("--compare-cold-failover", action="store_true",
                    help="with --kill: rerun the kill schedule with COLD "
                         "replacements (no snapshot), assert token identity "
                         "and STRICTLY fewer warm prefill chunks")
    ap.add_argument("--fleet-goodput-floor", type=float, default=0.0,
                    help="fail unless the merged goodput_fleet fraction is "
                         ">= this floor (0 = not gated)")
    ap.add_argument("--transcript", default=None, metavar="PATH",
                    help="write the byte-stable fleet routing transcript "
                         "here (lint.sh golden-compares it)")
    ap.add_argument("--arrival-scale", type=float, default=1.0,
                    help="scale the seeded inter-arrival gaps (0.0 = all "
                         "requests arrive at once, past saturation)")
    ap.add_argument("--arrival", default="default", metavar="PROCESS",
                    help="arrival process: 'default' (seeded 0-2 iteration "
                         "stagger) or 'poisson:RATE' (seeded Poisson process "
                         "at RATE requests/iteration — arrivals bunch, so a "
                         "rate past service capacity crosses any "
                         "--max-queue-depth bound and sheds; with --fleet "
                         "the run re-routes the trace a second time and "
                         "asserts the shed set is deterministic)")
    ap.add_argument("--snapshot-dir", default=None, metavar="DIR",
                    help="warm-failover snapshot directory (default: a "
                         "fresh temp dir)")
    args = ap.parse_args(argv)
    if args.no_trace and (args.slo_ttft_ms or args.slo_tpot_ms
                          or args.dump_ledger):
        ap.error("--no-trace is incompatible with --slo-*/--dump-ledger "
                 "(they need the ledger)")
    args.arrival_process = None
    if args.arrival != "default":
        kind, sep, rate_s = args.arrival.partition(":")
        try:
            rate = float(rate_s)
        except ValueError:
            rate = 0.0
        if kind != "poisson" or not sep or not rate > 0.0:
            ap.error("--arrival must be 'default' or 'poisson:RATE' with "
                     f"RATE > 0, got {args.arrival!r}")
        args.arrival_process = (kind, rate)
    args.kill = [_parse_kill(ap, s, args.fleet) for s in (args.kill or [])]
    if args.fleet:
        if args.fleet < 1:
            ap.error("--fleet must be >= 1")
        if args.no_trace:
            ap.error("--fleet needs the request-trace ledger (the fleet "
                     "percentile merge reads it)")
        if args.sharding > 1 or args.verify_unsharded:
            ap.error("--fleet replicas are single-chip in the sim")
        if args.compare_prefix_cache or args.compare_speculate or args.replay:
            ap.error("--fleet has its own compare modes "
                     "(--compare-affinity / --compare-cold-failover)")
        args.prefix_cache = True
        if args.fleet_policy is None:
            args.fleet_policy = "affinity"
        return _fleet_main(args)
    if (args.fleet_policy or args.compare_affinity or args.kill
            or args.compare_cold_failover or args.transcript
            or args.fleet_goodput_floor):
        ap.error("fleet options need --fleet N")
    if args.compare_prefix_cache:
        args.prefix_cache = True
    if args.compare_speculate and not args.speculate:
        args.speculate = 4
    if args.speculate < 0:
        ap.error("--speculate must be >= 1 (or omitted)")
    if args.spec_steps_budget and not args.speculate:
        ap.error("--spec-steps-budget needs --speculate")
    if args.speculate and args.sharding > 1:
        ap.error("--speculate is single-chip only (the spec_verify program "
                 "does not shard)")
    if args.verify_unsharded and args.sharding <= 1:
        ap.error("--verify-unsharded needs --sharding > 1")
    if args.sharding < 1:
        ap.error("--sharding must be >= 1")
    mirror_on = not args.no_mirror and not args.prefix_cache \
        and args.sharding <= 1 and not args.speculate
    if not args.no_mirror and not mirror_on:
        print("serve-sim: note: mirror oracle disabled "
              "(incompatible with --prefix-cache / --sharding / --speculate)")

    from ..utils.telemetry import TelemetrySession

    trace = _trace(args)

    session = TelemetrySession(output_path=args.output, job_name="serve_sim")
    engine = _build(args, session)
    outputs, logs = engine.run(trace)

    finished = [o for o in outputs if o.status == "finished"]
    refused = [o for o in outputs if o.status == "refused"]
    tokens = sum(len(o.tokens) for o in finished)
    preempts = sum(len(l["preempted"]) for l in logs)
    ttfts = [o.ttft_iters for o in finished if o.ttft_iters is not None]

    failures = []

    # invariant 1: one compile per program, zero recompiles, whole trace
    serve_names = sorted(n for n in session.watchdog.records
                         if n.startswith("serve:"))
    total_recompiles = 0
    for name in serve_names:
        n_c = session.watchdog.compiles(name)
        n_r = session.watchdog.recompiles(name)
        total_recompiles += n_r
        if n_r:
            failures.append(f"{name}: {n_r} recompile(s) after warmup")
    if not serve_names:
        failures.append("no serve:* programs reached the compile watchdog")

    # invariant 2: the oracle lockstep actually ran
    if mirror_on and engine.mirror_checks == 0:
        failures.append("mirror enabled but no bitwise checks executed")

    # invariant 3 (optional): byte-identical replay on a fresh engine
    if args.replay:
        engine2 = _build(args, None)
        outputs2, logs2 = engine2.run(_trace(args))
        if json.dumps(logs) != json.dumps(logs2):
            failures.append("replay schedule log diverged")
        toks1 = [(o.req_id, o.status, o.tokens) for o in outputs]
        toks2 = [(o.req_id, o.status, o.tokens) for o in outputs2]
        if toks1 != toks2:
            failures.append("replay outputs diverged")

    # invariant 6 (optional): the model-axis sharded engine is a memory-layout
    # + compute-placement change, not a sampling change — token-identical to
    # the single-chip engine on the same trace (greedy and beam lanes alike)
    ttft_compare = None
    if args.verify_unsharded:
        eng1 = _build(args, None, sharding=1)
        outs1, _ = eng1.run(_trace(args))
        sharded = {(o.req_id): (o.status, o.tokens) for o in outputs}
        single = {(o.req_id): (o.status, o.tokens) for o in outs1}
        if sharded != single:
            bad = sorted(r for r in sharded if sharded[r] != single.get(r))
            failures.append(
                f"sharded (model={args.sharding}) outputs diverge from "
                f"single-chip on {len(bad)} request(s): {', '.join(bad[:8])}")

    # invariant 7 (optional): the prefix cache must actually BUY something on
    # this trace — token-identical outputs AND a strictly better p50 TTFT in
    # the deterministic iteration domain than the same engine cache-off
    if args.compare_prefix_cache:
        eng_off = _build(args, None, prefix_cache=False)
        outs_off, _ = eng_off.run(_trace(args))
        t_on = {o.req_id: (o.status, o.tokens) for o in outputs}
        t_off = {o.req_id: (o.status, o.tokens) for o in outs_off}
        if t_on != t_off:
            bad = sorted(r for r in t_on if t_on[r] != t_off.get(r))
            failures.append(
                f"prefix cache changed tokens on {len(bad)} request(s): "
                f"{', '.join(bad[:8])}")
        p50_on = _p50(o.ttft_iters for o in outputs
                      if o.status == "finished")
        p50_off = _p50(o.ttft_iters for o in outs_off
                       if o.status == "finished")
        ttft_compare = {"cache_on": p50_on, "cache_off": p50_off}
        if p50_on is None or p50_off is None or not p50_on < p50_off:
            failures.append(
                f"prefix cache did not strictly improve p50 TTFT: "
                f"cache-on {p50_on} vs cache-off {p50_off} iters")

    # invariant 8 (optional): speculation is a schedule optimization, not a
    # sampling change — byte-identical emitted tokens on the same trace with
    # STRICTLY fewer target-model program executions (the headline number)
    steps_compare = None
    if args.compare_speculate:
        eng_plain = _build(args, None, speculate=0)
        outs_plain, _ = eng_plain.run(_trace(args))
        t_on = {o.req_id: (o.status, o.tokens) for o in outputs}
        t_off = {o.req_id: (o.status, o.tokens) for o in outs_plain}
        if t_on != t_off:
            bad = sorted(r for r in t_on if t_on[r] != t_off.get(r))
            failures.append(
                f"speculation changed tokens on {len(bad)} request(s): "
                f"{', '.join(bad[:8])}")
        steps_compare = {"speculative": engine.target_steps,
                         "plain": eng_plain.target_steps}
        if not engine.target_steps < eng_plain.target_steps:
            failures.append(
                f"speculation did not strictly reduce target-model steps: "
                f"{engine.target_steps} vs plain {eng_plain.target_steps}")
    spec_summary = engine.spec_summary() if args.speculate else None
    if args.spec_steps_budget:
        ratio = spec_summary["target_steps_per_token"]
        if not ratio < args.spec_steps_budget:
            failures.append(
                f"target_steps_per_token {ratio:.4f} is not under the "
                f"--spec-steps-budget {args.spec_steps_budget}")

    tracer = engine.tracer
    waste = slo = None
    fleet_merge_exact = None
    if tracer is not None:
        # invariant 4: the ledger's useful/replayed split covers every token
        # the schedule log says was scheduled — exactly, no residue
        waste = tracer.waste_summary()
        sched_prefill = sum(l["prefill"][2] for l in logs if l["prefill"])
        # speculative rounds commit tokens outside the per-lane decode list;
        # their log entries carry the committed count in slot 3 (the "spec"
        # key only exists with speculation on, so spec-off logs are unchanged)
        sched_decode = (sum(len(l["decode"]) for l in logs)
                        + sum(e[3] for l in logs for e in l.get("spec", [])))
        if (waste["prefill_tokens"] != sched_prefill
                or waste["decode_tokens"] != sched_decode):
            failures.append(
                f"waste decomposition does not sum to scheduled tokens: "
                f"ledger prefill {waste['prefill_tokens']} vs schedule "
                f"{sched_prefill}, ledger decode {waste['decode_tokens']} "
                f"vs schedule {sched_decode}")
        if (waste["useful_tokens"] + waste["replayed_tokens"]
                != waste["scheduled_tokens"]):
            failures.append("waste decomposition: useful + replayed != "
                            "scheduled")
        # invariant 5: configured SLOs hold for every finished request
        slo = tracer.slo_summary()
        if slo["configured"] and slo["violated"]:
            worst = [r["req_id"] for r in tracer.requests
                     if r.get("slo_violations")]
            failures.append(
                f"SLO violated by {slo['violated']} of "
                f"{slo['met'] + slo['violated']} finished requests "
                f"(attainment {slo['attainment']:.3f}): "
                f"{', '.join(worst[:8])}")
        # invariant 6: fleet rollup exactness — shard the finished-request
        # stream over 4 virtual replicas, rebuild per-replica latency
        # sketches, merge, and require the fleet percentiles to EQUAL the
        # single-stream read-out (the HistogramSketch mergeability contract
        # ROADMAP item 2c's router gates on). Wall-derived values, but the
        # equality itself is exact by construction, so the boolean is stable.
        finished_recs = [r for r in tracer.requests
                         if r.get("status") == "finished"]
        if finished_recs and len(finished_recs) == tracer.finished:
            from ..utils.cluster import fleet_latency_summary
            from .request_trace import HistogramSketch, LATENCY_METRICS
            replicas = [{m: HistogramSketch() for m in LATENCY_METRICS}
                        for _ in range(4)]
            for i, rec in enumerate(finished_recs):
                for m in LATENCY_METRICS:
                    replicas[i % 4][m].add(rec.get(m))
            bundles = [{"latency_sketches":
                        {m: h[m].to_dict() for m in LATENCY_METRICS
                         if h[m].count}} for h in replicas]
            fleet = fleet_latency_summary(bundles, ps=(50, 90, 99))
            single = tracer.latency_summary(ps=(50, 90, 99))
            fleet_merge_exact = fleet == single
            if not fleet_merge_exact:
                failures.append(
                    "fleet histogram-sketch merge diverged from the "
                    "single-stream percentiles")

    if args.dump_ledger:
        tracer.dump(args.dump_ledger)

    cache_stats = (engine.prefix_cache.stats()
                   if engine.prefix_cache is not None else None)

    if args.json_out:
        report = _report(args, trace, outputs, logs, tracer, waste, slo,
                         failures, cache_stats=cache_stats,
                         ttft_compare=ttft_compare,
                         fleet_merge_exact=fleet_merge_exact,
                         spec_summary=spec_summary,
                         steps_compare=steps_compare)
        blob = json.dumps(report, sort_keys=True, separators=(",", ":"))
        if args.json_out == "-":
            print(blob)
        else:
            with open(args.json_out, "w") as f:
                f.write(blob)

    session.close()

    print(f"serve-sim: {len(finished)} finished / {len(refused)} refused "
          f"of {len(trace)} requests over {len(logs)} iterations")
    print(f"  tokens generated : {tokens}")
    print(f"  preemptions      : {preempts}")
    if ttfts:
        print(f"  TTFT iters       : mean {sum(ttfts) / len(ttfts):.1f} "
              f"max {max(ttfts)}")
    print(f"  programs watched : {len(serve_names)} "
          f"(recompiles after warmup: {total_recompiles})")
    if mirror_on:
        print(f"  oracle lockstep  : {engine.mirror_checks} bitwise checks, "
              f"all identical")
    if args.sharding > 1:
        shard_note = (" (token-identical to single-chip)"
                      if args.verify_unsharded and not failures else "")
        print(f"  sharding         : model={args.sharding} ways by attention "
              f"head{shard_note}")
    if cache_stats is not None:
        print(f"  prefix cache     : hit-rate {cache_stats['hit_rate']:.1%} "
              f"({cache_stats['hits']} hits), "
              f"{cache_stats['hit_tokens']} prompt tokens remapped "
              f"({cache_stats['cached_token_fraction']:.1%} of looked-up), "
              f"{cache_stats['evictions']} evictions")
    if ttft_compare is not None:
        print(f"  TTFT p50 iters   : cache-on {ttft_compare['cache_on']} vs "
              f"cache-off {ttft_compare['cache_off']}")
    if spec_summary is not None:
        print(f"  speculation      : K={args.speculate}, acceptance "
              f"{spec_summary['spec_acceptance_rate']:.1%} "
              f"({spec_summary['accepted_tokens']} of "
              f"{spec_summary['drafted_tokens']} drafts), "
              f"{spec_summary['target_steps_per_token']:.3f} "
              f"target steps/token")
    if steps_compare is not None:
        print(f"  target steps     : speculative "
              f"{steps_compare['speculative']} vs plain "
              f"{steps_compare['plain']} (token-identical)")
    if args.replay:
        print("  replay           : byte-identical schedule + outputs")
    if waste is not None:
        print(f"  token waste      : {waste['replayed_tokens']} of "
              f"{waste['scheduled_tokens']} scheduled tokens replayed "
              f"({waste['waste_fraction']:.1%})")
        pcts = tracer.percentiles()
        for m in ("ttft_ms", "tpot_ms"):
            if m in pcts:
                p = pcts[m]
                print(f"  {m:<16} : p50 {p['p50']:.2f} p90 {p['p90']:.2f} "
                      f"p99 {p['p99']:.2f}")
    if slo and slo["configured"]:
        print(f"  SLO              : {slo['met']} met / {slo['violated']} "
              f"violated (attainment {slo['attainment']:.3f})")
    if args.dump_ledger:
        print(f"  ledger           : {args.dump_ledger}")
    print(f"  scalars          : {session.monitor.log_dir}/scalars.jsonl")

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("serve-sim: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
