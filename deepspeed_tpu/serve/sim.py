"""``ds-tpu serve-sim`` — deterministic request-replay driver for the engine.

Replays a seeded synthetic trace (mixed prompt/generation lengths, staggered
arrivals, a sprinkle of beam-search requests) through InferenceEngine on the
CPU mesh and asserts the three serving invariants:

1. **Zero recompiles after warmup** — every serve:* program compiles exactly
   once for the whole trace (compile watchdog through TelemetrySession).
2. **Bit-exact paging** — the engine runs with ``mirror=True``, so every
   prefill chunk and decode step is compared bitwise against the dense-cache
   oracle (serve/oracle.py); one diverging ulp fails the run.
3. **Deterministic schedule** — with ``--replay``, the whole trace is run
   twice on fresh engines and the per-iteration schedule logs must be
   byte-identical (json.dumps) and the outputs token-identical.

Serving/* scalars (occupancy, TTFT, goodput) land in the TelemetrySession's
scalars.jsonl. Exit 0 = all invariants held.
"""

import argparse
import json
import sys


def synth_trace(n, *, vocab_size, max_model_len, seed, beam_every=7,
                include_infeasible=False):
    """Seeded mixed trace: prompts 1..~ML/2, generations 1..~ML/4, arrivals
    staggered 0-2 iterations apart, every ``beam_every``-th request beam-4."""
    import numpy as np
    from .scheduler import Request

    rng = np.random.RandomState(seed)
    reqs, arrival = [], 0
    for i in range(n):
        arrival += int(rng.randint(0, 3))
        T0 = int(rng.randint(1, max(2, max_model_len // 2)))
        L = int(rng.randint(1, max(2, max_model_len // 4)))
        if T0 + L > max_model_len:          # keep the trace feasible
            L = max_model_len - T0
        K = 4 if (beam_every and i % beam_every == beam_every - 1) else 1
        prompt = rng.randint(0, vocab_size, size=T0).tolist()
        reqs.append(Request(f"req{i:03d}", prompt, L, arrival=arrival,
                            num_beams=K))
    if include_infeasible:
        prompt = rng.randint(0, vocab_size, size=max_model_len).tolist()
        reqs.append(Request("req-too-long", prompt, max_model_len,
                            arrival=0))
    return reqs


def _build(args, telemetry):
    import jax
    import jax.numpy as jnp

    from ..models.gpt2 import GPT2Config, GPT2Model
    from .engine import InferenceEngine

    cfg = GPT2Config(vocab_size=args.vocab_size, n_positions=args.max_model_len,
                     n_embd=args.n_embd, n_layer=args.n_layer,
                     n_head=args.n_head, compute_dtype=jnp.float32,
                     loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))
    engine = InferenceEngine(
        model, params, num_slots=args.slots, block_size=args.block_size,
        num_blocks=args.num_blocks, max_model_len=args.max_model_len,
        prefill_chunk=args.prefill_chunk, use_pallas=args.pallas,
        telemetry=telemetry, mirror=not args.no_mirror)
    return engine


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds-tpu serve-sim",
        description="deterministic serving-engine replay with bitwise oracle "
                    "+ zero-recompile assertions")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=257)
    ap.add_argument("--max-model-len", type=int, default=256)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--vocab-size", type=int, default=128)
    ap.add_argument("--n-embd", type=int, default=32)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=2)
    ap.add_argument("--no-mirror", action="store_true",
                    help="skip the dense-oracle bitwise lockstep (faster)")
    ap.add_argument("--pallas", action="store_true",
                    help="use the Pallas paged-decode kernel (interpret mode "
                         "on CPU)")
    ap.add_argument("--replay", action="store_true",
                    help="run the trace twice and assert byte-identical "
                         "schedules")
    ap.add_argument("--include-infeasible", action="store_true",
                    help="append a request that can never fit (exercises "
                         "admission refusal)")
    ap.add_argument("--output", default="serve_sim_telemetry",
                    help="TelemetrySession output dir for Serving/* scalars")
    args = ap.parse_args(argv)

    from ..utils.telemetry import TelemetrySession

    trace = synth_trace(args.requests, vocab_size=args.vocab_size,
                        max_model_len=args.max_model_len, seed=args.seed,
                        include_infeasible=args.include_infeasible)

    session = TelemetrySession(output_path=args.output, job_name="serve_sim")
    engine = _build(args, session)
    outputs, logs = engine.run(trace)

    finished = [o for o in outputs if o.status == "finished"]
    refused = [o for o in outputs if o.status == "refused"]
    tokens = sum(len(o.tokens) for o in finished)
    preempts = sum(len(l["preempted"]) for l in logs)
    ttfts = [o.ttft_iters for o in finished if o.ttft_iters is not None]

    failures = []

    # invariant 1: one compile per program, zero recompiles, whole trace
    serve_names = sorted(n for n in session.watchdog.records
                         if n.startswith("serve:"))
    total_recompiles = 0
    for name in serve_names:
        n_c = session.watchdog.compiles(name)
        n_r = session.watchdog.recompiles(name)
        total_recompiles += n_r
        if n_r:
            failures.append(f"{name}: {n_r} recompile(s) after warmup")
    if not serve_names:
        failures.append("no serve:* programs reached the compile watchdog")

    # invariant 2: the oracle lockstep actually ran
    if not args.no_mirror and engine.mirror_checks == 0:
        failures.append("mirror enabled but no bitwise checks executed")

    # invariant 3 (optional): byte-identical replay on a fresh engine
    if args.replay:
        engine2 = _build(args, None)
        outputs2, logs2 = engine2.run(
            synth_trace(args.requests, vocab_size=args.vocab_size,
                        max_model_len=args.max_model_len, seed=args.seed,
                        include_infeasible=args.include_infeasible))
        if json.dumps(logs) != json.dumps(logs2):
            failures.append("replay schedule log diverged")
        toks1 = [(o.req_id, o.status, o.tokens) for o in outputs]
        toks2 = [(o.req_id, o.status, o.tokens) for o in outputs2]
        if toks1 != toks2:
            failures.append("replay outputs diverged")

    session.close()

    print(f"serve-sim: {len(finished)} finished / {len(refused)} refused "
          f"of {len(trace)} requests over {len(logs)} iterations")
    print(f"  tokens generated : {tokens}")
    print(f"  preemptions      : {preempts}")
    if ttfts:
        print(f"  TTFT iters       : mean {sum(ttfts) / len(ttfts):.1f} "
              f"max {max(ttfts)}")
    print(f"  programs watched : {len(serve_names)} "
          f"(recompiles after warmup: {total_recompiles})")
    if not args.no_mirror:
        print(f"  oracle lockstep  : {engine.mirror_checks} bitwise checks, "
              f"all identical")
    if args.replay:
        print("  replay           : byte-identical schedule + outputs")
    print(f"  scalars          : {session.monitor.log_dir}/scalars.jsonl")

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("serve-sim: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
