"""Version / provenance info.

Analog of the reference's ``deepspeed/git_version_info.py`` (setup.py:320-324 writes
``git_version_info_installed.py`` at install time with version+git hash+installed ops).
A checkout with a live ``.git`` computes the fields from git (so editable installs
never report a stale hash); a regular install reads the generated module. Everything
is lazy (PEP 562): importing the package does not shell out to git — the subprocess
cost is only paid when ``version``/``git_hash`` is actually read.

``installed_ops`` reports which native/kernel ops this host can serve:
- ``cpu_adam``: the C++ host-tier Adam (built lazily at first use; requires g++ —
  False means the numpy fallback will serve)
- ``flash_attention`` / ``block_sparse_attention`` / ``transformer``: Pallas/XLA
  kernels, always shipped (they compile with jax, no separate toolchain)
"""

import os
import subprocess

_FIELDS = ("version", "git_hash", "git_branch", "installed_ops")
_cache = None


def _live():
    here = os.path.dirname(os.path.abspath(__file__))

    def git(cmd):
        try:
            out = subprocess.check_output(["git", *cmd], stderr=subprocess.DEVNULL, cwd=here)
            return out.decode().strip()
        except (OSError, subprocess.CalledProcessError):
            return "unknown"

    try:
        with open(os.path.join(here, "..", "version.txt")) as fd:
            base = fd.read().strip()
    except OSError:
        base = "0.0.0"
    import shutil
    git_hash = git(["rev-parse", "--short", "HEAD"])
    return {
        "version": f"{base}+{git_hash}",
        "git_hash": git_hash,
        "git_branch": git(["rev-parse", "--abbrev-ref", "HEAD"]),
        "installed_ops": {
            "cpu_adam": shutil.which("g++") is not None,
            "flash_attention": True,
            "block_sparse_attention": True,
            "transformer": True,
        },
    }


def _info():
    global _cache
    if _cache is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if os.path.isdir(os.path.join(repo_root, ".git")):
            # live checkout (incl. editable installs): git is the truth — the
            # install-time snapshot would go stale at the very next commit
            _cache = _live()
        else:
            try:
                from . import git_version_info_installed as gi
                _cache = {f: getattr(gi, f) for f in _FIELDS}
            except ImportError:
                _cache = _live()
    return _cache


def __getattr__(name):
    if name in _FIELDS:
        return _info()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
