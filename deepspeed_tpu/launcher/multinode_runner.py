"""Multi-node launch backends (pdsh / OpenMPI / MVAPICH).

TPU-native analog of ``deepspeed/launcher/multinode_runner.py:35-189``: each backend
turns the active resource map into one fan-out command that runs
``deepspeed_tpu.launcher.launch`` on every host. The env exports forwarded here are
the libtpu/JAX/XLA family (see constants.EXPORT_ENVS) rather than NCCL's.
"""

import os
import shlex
import shutil
import subprocess
import sys
import warnings
from abc import ABC, abstractmethod

from .constants import MVAPICH_TMP_HOSTFILE, PDSH_MAX_FAN_OUT


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args


class PDSHRunner(MultiNodeRunner):
    """Parallel-ssh fan-out; %n expands to the pdsh node index = node_rank."""

    def backend_exists(self):
        return shutil.which("pdsh")

    def parse_user_args(self):
        return [x if x.startswith("-") else f"'{x}'" for x in self.args.user_args]

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())

        pdsh_cmd_args = ["pdsh", "-f", str(PDSH_MAX_FAN_OUT), "-w", active_workers]
        if self.args.launcher_args:
            pdsh_cmd_args += self.args.launcher_args.split()

        # quote values: XLA_FLAGS et al. routinely contain spaces
        exports = "".join(f"export {key}={shlex.quote(val)}; " for key, val in self.exports.items())
        launch_cmd = [
            exports,
            f"cd {os.path.abspath('.')};",
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            "--node_rank=%n",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
        ]
        return pdsh_cmd_args + launch_cmd + [self.user_script] + self.user_arguments


class OpenMPIRunner(MultiNodeRunner):
    """mpirun fan-out: one MPI rank per slot; ranks discover their identity via the
    OMPI_COMM_WORLD_* env that runtime.dist.init_distributed also understands."""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("UCX_TLS", "tcp")

    def backend_exists(self):
        return shutil.which("ompi_info")

    def get_cmd(self, environment, active_resources):
        assert self.args.include == "" and self.args.exclude == "", \
            "openmpi backend does not support worker include/exclusion"
        assert self.args.num_nodes == -1 and self.args.num_gpus == -1, \
            "openmpi backend does not support limiting num nodes/chips"
        total_process_count = sum(self.resource_pool.values())

        mpirun_cmd = ["mpirun", "-n", f"{total_process_count}",
                      "-hostfile", f"{self.args.hostfile}",
                      "--mca", "btl", "^openib",
                      "--mca", "btl_tcp_if_include", "eth0"]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()

        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-x", f"{k}={v}"]
        export_cmd += ["-x", f"DS_COORDINATOR_ADDRESS={self.args.master_addr}:{self.args.master_port}"]

        return mpirun_cmd + export_cmd + [sys.executable, "-u", self.user_script] + self.user_arguments


class MVAPICHRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("MV2_SMP_USE_CMA", "0")
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")
        self.add_export("MV2_ENABLE_AFFINITY", "0")
        self.add_export("MV2_SUPPORT_DL", "1")

    def backend_exists(self):
        mpiname = shutil.which("mpiname")
        if not mpiname:
            warnings.warn("mpiname does not exist, mvapich is not installed properly")
            return False
        results = subprocess.check_output("mpiname", shell=True).decode("utf-8").strip()
        if "MVAPICH2" in results:
            return True
        warnings.warn(f"Expected MVAPICH2 from mpiname but received {results}")
        return False

    def get_cmd(self, environment, active_resources):
        assert self.args.include == "" and self.args.exclude == "", \
            "mvapich backend does not support worker include/exclusion"
        assert self.args.num_nodes == -1 and self.args.num_gpus == -1, \
            "mvapich backend does not support limiting num nodes/chips"
        devices_per_node = self.resource_pool.values()
        total_process_count = sum(devices_per_node)
        process_per_node = list(devices_per_node)[0]
        assert all(n == process_per_node for n in devices_per_node), \
            "mvapich requires same number of devices per node"

        with open(MVAPICH_TMP_HOSTFILE, "w") as fd:
            for host in self.resource_pool.keys():
                fd.write(f"{host}\n")

        mpirun_cmd = ["mpirun", "-np", f"{total_process_count}",
                      "-ppn", f"{process_per_node}",
                      "--hostfile", f"{MVAPICH_TMP_HOSTFILE}"]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()

        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-env", f"{k}={v}"]
        export_cmd += ["-env", f"DS_COORDINATOR_ADDRESS={self.args.master_addr}:{self.args.master_port}"]
        # MVAPICH exposes rank/size as MV2_COMM_WORLD_* / PMI_* in the children;
        # runtime.dist._env_identity reads those to complete the identity triple.

        return mpirun_cmd + export_cmd + [sys.executable, "-u", self.user_script] + self.user_arguments
