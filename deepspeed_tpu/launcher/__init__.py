"""Cluster launch front-end (reference deepspeed/launcher/).

``runner`` is the user-facing CLI (hostfile → fan-out), ``launch`` the per-node
process spawner, ``multinode_runner`` the pdsh/mpirun backends.
"""

from . import constants  # noqa: F401
