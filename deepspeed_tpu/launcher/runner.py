"""Front-end launcher for multi-host TPU training jobs.

TPU-native analog of ``deepspeed/launcher/runner.py:251-357``: parses an MPI-style
hostfile (``worker-0 slots=4``), applies ``--include/--exclude`` node/slot filters
(reference runner.py:143-242), encodes the active resource map as urlsafe base64
(runner.py:245-248), and either execs the per-node launcher locally or fans out over
pdsh/mpirun. Differences from the reference are deliberate and TPU-shaped:

- "slots" are TPU chips (or processes-per-host); on a Cloud TPU pod each host
  usually runs ONE process owning all local chips (``--num_procs_per_node 1``).
- the rendezvous is the jax.distributed coordinator (rank-0 host:port), not
  torch.distributed MASTER_*; both env spellings are exported for script parity.
- with no hostfile we launch single-process on the local JAX platform, which is
  the common single-host TPU-VM case.
"""

import argparse
import base64
import collections
import json
import os
import shutil
import subprocess
import sys
from copy import deepcopy

from ..utils import logger
from .constants import (DEFAULT_COORDINATOR_PORT, DLTS_HOSTFILE, EXPORT_ENVS,
                        DEEPSPEED_ENVIRONMENT_NAME, MVAPICH_LAUNCHER, OPENMPI_LAUNCHER,
                        PDSH_LAUNCHER)
from .multinode_runner import MVAPICHRunner, OpenMPIRunner, PDSHRunner

DEEPSPEED_ENVIRONMENT_PATHS = [os.path.expanduser("~"), "."]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu runner: launch distributed multi-host TPU training jobs.")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="MPI-style hostfile defining the resource pool "
                             "(e.g. 'worker-0 slots=4', slots = TPU chips / procs per host)")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Resources to use: NODE_SPEC[@NODE_SPEC ...] where "
                             "NODE_SPEC=NAME[:SLOT[,SLOT ...]]. Omitting :SLOT takes the whole host.")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Resources to skip; same syntax as --include, mutually exclusive with it.")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Use only the first N hosts of the (filtered) pool.")
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int, default=-1,
                        help="Max chips/slots per host; uses slot ids [0:N).")
    parser.add_argument("--master_port", default=DEFAULT_COORDINATOR_PORT, type=int,
                        help="Port for the jax.distributed coordinator on node 0.")
    parser.add_argument("--master_addr", default="", type=str,
                        help="Address of node 0 (coordinator); inferred via ssh `hostname -I` if empty.")
    parser.add_argument("--launcher", default=PDSH_LAUNCHER, type=str,
                        help="Multi-node backend: pdsh, openmpi, or mvapich.")
    parser.add_argument("--launcher_args", default="", type=str,
                        help="Backend-specific arguments, as one quoted string.")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat the job as multi-node even with a single host entry.")
    parser.add_argument("user_script", type=str,
                        help="User training script, followed by its arguments.")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse 'host slots=N' lines into an ordered {host: slot_count} map
    (reference runner.py:115-140). Returns None when the file is absent."""
    if not os.path.isfile(hostfile_path):
        logger.warning("no hostfile found; falling back to the local host's devices")
        return None
    resource_pool = collections.OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError as err:
                logger.error("bad hostfile line (expected '<host> slots=<n>'); aborting launch")
                raise err
            if hostname in resource_pool:
                logger.error("hostfile lists the same host twice; aborting launch")
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Filter {host: [slot ids]} by an include or exclude spec (reference runner.py:143-242).

    Spec syntax: NODE_SPEC[@NODE_SPEC ...], NODE_SPEC = NAME[:SLOT[,SLOT ...]].
    Include builds the pool from scratch; exclude removes from a copy. Order of the
    original host_info is preserved so ranks map deterministically.
    """
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive.")
    if not include_str and not exclude_str:
        return host_info

    filtered_hosts = dict()
    if include_str:
        parse_str = include_str
    else:
        filtered_hosts = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split("@"):
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            slots = [int(x) for x in slots.split(",")]
            if hostname not in host_info:
                raise ValueError(f"include/exclude filter references {hostname!r}, which the hostfile does not define")
            for s in slots:
                if s not in host_info[hostname]:
                    raise ValueError(f"No slot '{s}' specified on host '{hostname}'")
            if include_str:
                filtered_hosts[hostname] = slots
            else:
                for s in slots:
                    logger.info(f"removing {s} from {hostname}")
                    filtered_hosts[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"include/exclude filter references {hostname!r}, which the hostfile does not define")
            if include_str:
                filtered_hosts[hostname] = host_info[hostname]
            else:
                filtered_hosts[hostname] = []

    # Drop duplicates and emptied hosts, then restore hostfile ordering.
    del_keys = []
    for hostname in filtered_hosts:
        filtered_hosts[hostname] = sorted(set(filtered_hosts[hostname]))
        if len(filtered_hosts[hostname]) == 0:
            del_keys.append(hostname)
    for name in del_keys:
        del filtered_hosts[name]

    ordered_hosts = collections.OrderedDict()
    for host in host_info:
        if host in filtered_hosts:
            ordered_hosts[host] = filtered_hosts[host]
    return ordered_hosts


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """{host: slot_count} → filtered {host: [slot ids]} (reference runner.py:235-242)."""
    active_resources = collections.OrderedDict()
    for hostname, slots in resource_pool.items():
        active_resources[hostname] = list(range(slots))
    return parse_resource_filter(active_resources, include_str=inclusion, exclude_str=exclusion)


def encode_world_info(world_info) -> str:
    """urlsafe-base64 JSON of the {host: [slots]} map (reference runner.py:245-248)."""
    world_info_json = json.dumps(world_info).encode("utf-8")
    return base64.urlsafe_b64encode(world_info_json).decode("utf-8")


def decode_world_info(world_info_base64: str):
    return json.loads(base64.urlsafe_b64decode(world_info_base64))


def _local_device_count() -> int:
    """Local chip count for the hostfile-less path. Avoids initializing the TPU
    runtime in the front-end process (which would hold the chips before the child
    spawns): env overrides first, then libtpu device files, else 1 process."""
    env = os.environ.get("DS_NUM_CHIPS") or os.environ.get("TPU_NUM_DEVICES")
    if env:
        return int(env)
    # Cloud TPU VMs expose one accel device file per chip.
    accel = [d for d in os.listdir("/dev") if d.startswith("accel")] if os.path.isdir("/dev") else []
    if accel:
        return len(accel)
    return 1


def main(args=None):
    args = parse_args(args)

    if (args.num_nodes >= 0 or args.num_gpus >= 0) and (args.include or args.exclude):
        raise ValueError("Cannot specify num_nodes/num_gpus with include/exclude")

    multi_node_exec = True
    resource_pool = fetch_hostfile(args.hostfile)
    from_hostfile = bool(resource_pool)  # a comments-only hostfile declares nothing
    if not resource_pool:
        resource_pool = {"localhost": _local_device_count()}
        args.master_addr = "127.0.0.1"
        multi_node_exec = False

    if not multi_node_exec and args.num_nodes > 1:
        raise ValueError("--num_nodes > 1 requires a hostfile listing the extra nodes")

    active_resources = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    env = os.environ.copy()

    if not args.master_addr:
        first_host = list(active_resources.keys())[0]
        result = subprocess.check_output([f"ssh {first_host} hostname -I"], shell=True)
        args.master_addr = result.decode("utf-8").split()[0]
        logger.info(f"resolved {first_host} -> {args.master_addr} as the coordinator address")

    if args.num_nodes > 0:
        active_resources = collections.OrderedDict(
            (h, s) for i, (h, s) in enumerate(active_resources.items()) if i < args.num_nodes)
    if args.num_gpus > 0:
        if from_hostfile:
            # cap to slots the hostfile actually declares — fabricating ids would
            # fail chip pinning at runtime instead of erroring here
            for h, slots in active_resources.items():
                if args.num_gpus > len(slots):
                    raise ValueError(f"--num_gpus {args.num_gpus} exceeds the {len(slots)} "
                                     f"slots declared for host '{h}'")
            active_resources = collections.OrderedDict(
                (h, slots[:args.num_gpus]) for h, slots in active_resources.items())
        else:
            # localhost slot count is a heuristic, not a declaration — honor the
            # explicit request (reference runner.py:295-299 behavior)
            active_resources = collections.OrderedDict(
                (h, list(range(args.num_gpus))) for h in active_resources)

    world_info_base64 = encode_world_info(active_resources)
    multi_node_exec = args.force_multi or len(active_resources) > 1

    if not multi_node_exec:
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={world_info_base64}",
               f"--master_addr={args.master_addr}",
               f"--master_port={args.master_port}",
               args.user_script] + args.user_args
    else:
        launcher = args.launcher.lower()
        if launcher == PDSH_LAUNCHER:
            runner = PDSHRunner(args, world_info_base64)
        elif launcher == OPENMPI_LAUNCHER:
            runner = OpenMPIRunner(args, world_info_base64, resource_pool)
        elif launcher == MVAPICH_LAUNCHER:
            runner = MVAPICHRunner(args, world_info_base64, resource_pool)
        else:
            raise NotImplementedError(f"Unknown launcher {args.launcher}")
        if not runner.backend_exists():
            raise RuntimeError(f"launcher '{args.launcher}' not installed.")

        curr_path = os.path.abspath(".")
        env["PYTHONPATH"] = curr_path + ":" + env["PYTHONPATH"] if "PYTHONPATH" in env else curr_path

        for var in env:
            if any(var.startswith(name) for name in EXPORT_ENVS):
                runner.add_export(var, env[var])

        # Propagate user-pinned env via ~/.deepspeed_env or ./.deepspeed_env
        # (reference runner.py:345-351).
        for environ_path in DEEPSPEED_ENVIRONMENT_PATHS:
            environ_file = os.path.join(environ_path, DEEPSPEED_ENVIRONMENT_NAME)
            if os.path.isfile(environ_file):
                with open(environ_file, "r") as fd:
                    for var in fd.readlines():
                        var = var.strip()
                        if not var or var.startswith("#") or "=" not in var:
                            continue
                        key, val = var.split("=", 1)
                        runner.add_export(key, val)

        cmd = runner.get_cmd(env, active_resources)

    logger.info("cmd = {}".format(" ".join(cmd)))
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
