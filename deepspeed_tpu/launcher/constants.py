"""Launcher constants (reference deepspeed/launcher/constants.py).

The default port doubles as the JAX distributed coordinator port: the runner's
``--master_addr/--master_port`` become ``coordinator_address`` for
``jax.distributed.initialize`` instead of torch.distributed's MASTER_* rendezvous.
"""

# Coordinator (rank-0) port used for jax.distributed service rendezvous.
DEFAULT_COORDINATOR_PORT = 29500
# Kept as an alias for scripts written against the reference name.
TORCH_DISTRIBUTED_DEFAULT_PORT = DEFAULT_COORDINATOR_PORT

PDSH_LAUNCHER = "pdsh"
PDSH_MAX_FAN_OUT = 1024

OPENMPI_LAUNCHER = "openmpi"

MVAPICH_LAUNCHER = "mvapich"
MVAPICH_TMP_HOSTFILE = "/tmp/deepspeed_tpu_mvapich_hostfile"

# Hostfile default location (reference launcher/runner.py:26).
DLTS_HOSTFILE = "/job/hostfile"

# Env prefixes forwarded to remote nodes (reference EXPORT_ENVS had NCCL/PYTHON/MV2/UCX;
# the TPU-relevant set is the libtpu/JAX/XLA family).
EXPORT_ENVS = ["TPU", "JAX", "XLA", "LIBTPU", "PYTHON", "TF_CPP", "MV2", "UCX"]

DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
