"""Per-node process spawner.

TPU-native analog of ``deepspeed/launcher/launch.py:65-128``. The reference spawned
one process per GPU, pinning ``CUDA_VISIBLE_DEVICES`` and torch.distributed MASTER_*
env. Here each slot becomes one JAX process: we pin the libtpu chip-visibility env
(``TPU_VISIBLE_DEVICES`` plus process bounds) and export the jax.distributed
coordinator triple (address, process count, process id) that
``deepspeed_tpu.runtime.dist.init_distributed`` consumes. RANK/WORLD_SIZE/LOCAL_RANK
and MASTER_ADDR/PORT are exported too so scripts written against the reference's env
contract keep working.

The common TPU-pod deployment is ONE slot per host (a single process owning every
local chip) — the hostfile then says ``slots=1`` and no chip pinning is emitted.
"""

import base64
import json
import os
import subprocess
import sys
from argparse import REMAINDER, ArgumentParser
from collections import defaultdict

from ..utils import logger
from .constants import DEFAULT_COORDINATOR_PORT


def parse_args(args=None):
    parser = ArgumentParser(description="deepspeed_tpu per-node launcher: spawns one JAX "
                                        "process per local slot.")
    parser.add_argument("--node_rank", type=int, default=0,
                        help="Rank of this node in the world-info host list.")
    parser.add_argument("--master_addr", default="127.0.0.1", type=str,
                        help="Coordinator (node 0) address for jax.distributed.")
    parser.add_argument("--master_port", default=DEFAULT_COORDINATOR_PORT, type=int,
                        help="Coordinator port.")
    parser.add_argument("--world_info", default="None", type=str,
                        help="base64-encoded {host: [slot ids]} dictionary.")
    parser.add_argument("training_script", type=str,
                        help="User training script (launched once per local slot).")
    parser.add_argument("training_script_args", nargs=REMAINDER)
    return parser.parse_args(args=args)


def build_rank_mapping(world_info: dict):
    """Global rank assignment: hosts in world-info order, slots in-order within a host
    (reference launch.py:90-101). Returns ({host: [global ranks]}, world_size)."""
    global_rank_mapping = defaultdict(list)
    rank = 0
    for node_id, gids in world_info.items():
        for _ in gids:
            global_rank_mapping[node_id].append(rank)
            rank += 1
    return dict(global_rank_mapping), rank


def child_env(base_env: dict, world_info: dict, node_rank: int, local_rank: int,
              master_addr: str, master_port: int) -> dict:
    """Environment for one spawned process. Pure function for testability.

    Exports both the jax.distributed triple (DS_COORDINATOR_ADDRESS /
    DS_NUM_PROCESSES / DS_PROCESS_ID) and the reference-compatible
    RANK/WORLD_SIZE/LOCAL_RANK/MASTER_* spellings.
    """
    node_list = list(world_info.keys())
    local_node = node_list[node_rank]
    local_slot_ids = world_info[local_node]
    mapping, world_size = build_rank_mapping(world_info)
    dist_rank = mapping[local_node][local_rank]

    env = dict(base_env)
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    env["WORLD_SIZE"] = str(world_size)
    env["RANK"] = str(dist_rank)
    env["LOCAL_RANK"] = str(local_rank)
    env["DS_COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
    env["DS_NUM_PROCESSES"] = str(world_size)
    env["DS_PROCESS_ID"] = str(dist_rank)

    num_local = len(local_slot_ids)
    if num_local > 1:
        # Multiple processes sharing one host's chips: pin this process to its chip
        # and give libtpu the full per-process topology it needs to form a donut.
        chip = str(local_slot_ids[local_rank])
        env["TPU_VISIBLE_DEVICES"] = chip
        env["CUDA_VISIBLE_DEVICES"] = chip  # GPU/CPU-cluster parity
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
        port_base = int(env.get("TPU_PROCESS_PORT_BASE", "8476"))
        # every process needs a DISTINCT local port, and all processes need the full
        # address list (host:port per process, world order = rank order)
        env["TPU_PROCESS_PORT"] = str(port_base + local_rank)
        addresses = []
        for node_id, gids in world_info.items():
            for i in range(len(gids)):
                addresses.append(f"{node_id if len(world_info) > 1 else '127.0.0.1'}:{port_base + i}")
        env["TPU_PROCESS_ADDRESSES"] = ",".join(addresses)
        env["CLOUD_TPU_TASK_ID"] = str(dist_rank)
        # Physical process bounds depend on slice topology; 1x1xN covers the common
        # v5e/v4 single-row cases and is overridable via env for larger slices.
        env.setdefault("TPU_PROCESS_BOUNDS", f"1,1,{world_size}")
    return env


def main(args=None):
    args = parse_args(args)
    current_env = os.environ.copy()

    assert args.world_info != "None", "must provide world info dict"
    world_info = json.loads(base64.urlsafe_b64decode(args.world_info))
    logger.info(f"WORLD INFO DICT: {world_info}")

    node_list = list(world_info.keys())
    local_node = node_list[args.node_rank]
    num_local_procs = len(world_info[local_node])
    mapping, world_size = build_rank_mapping(world_info)
    logger.info(f"nnodes={len(node_list)}, num_local_procs={num_local_procs}, "
                f"node_rank={args.node_rank}, world_size={world_size}")

    processes = []
    for local_rank in range(num_local_procs):
        env = child_env(current_env, world_info, args.node_rank, local_rank,
                        args.master_addr, args.master_port)
        cmd = [sys.executable, "-u", args.training_script,
               f"--local_rank={local_rank}"] + args.training_script_args
        processes.append(subprocess.Popen(cmd, env=env))

    exit_code = 0
    for process in processes:
        process.wait()
        exit_code = exit_code or process.returncode
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
