"""Mixture-of-Experts with expert parallelism over a mesh axis.

Beyond the reference's feature set (DeepSpeed v0.3.0 has no MoE; DeepSpeed-MoE
arrived later) — included because expert parallelism is the 5th parallelism
dimension a complete TPU framework needs next to dp/tp/pp/sp. The design is the
GShard/Switch-Transformer recipe expressed TPU-first:

- **Static shapes everywhere**: top-1 (switch) or top-2 (GShard) routing with a
  fixed per-expert capacity ``C = ceil(top_k * tokens/E * capacity_factor)``
  (GShard scales capacity with k, else second choices mostly drop); slot
  assignment is one-hot + cumsum queueing (no dynamic shapes), tokens over
  capacity are DROPPED and ride the residual connection (standard switch
  semantics). The ``[E, C, H]`` dispatch buffer is built either by the dense
  one-hot ``[N,E,C]×[N,H]`` einsums (``dispatch="einsum"``, the default —
  N·E·C·H MXU flops) or by a row scatter-add on flat slot ids with a
  gather-based combine (``"scatter"`` — O(N·H) HBM traffic); both produce
  identical outputs and gradients, and on TPU the einsum measures FASTER
  (see the dispatch comment in ``__init__``).
- **Expert parallelism**: experts shard over a mesh axis. Inside ``shard_map``
  each rank holds ``E / ep`` experts; the ``[E, C, H]`` dispatch buffer is
  exchanged with ONE ``lax.all_to_all`` (rank r keeps the slices for its local
  experts from every peer — the NCCL AllToAll of every MoE system, riding ICI),
  experts run as one batched einsum over their leading axis (MXU-friendly), and
  a second all_to_all returns expert outputs to the token owners.
- **Load-balancing loss** (Switch eq. 4): ``E * sum_e f_e * p_e`` where ``f_e``
  is the fraction of tokens routed to expert e and ``p_e`` the mean router
  probability — computed over the GLOBAL batch via a psum so every rank adds the
  same auxiliary term.

``MoELayer`` follows the repo's pure-function module convention (init/apply) so
it slots into ``PipelineModule`` stacks and the engine unchanged.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MODEL_AXIS, axis_size, shard_map


class MoELayer:
    """Switch-style top-1 MoE FFN: ``[.., H] -> [.., H]`` with E expert MLPs.

    Args:
      hidden: model width H.
      ffn_dim: expert MLP inner width.
      num_experts: E (must divide by the expert-parallel degree when sharded).
      capacity_factor: per-expert capacity multiplier (1.0 = perfectly balanced).
      expert_axis: mesh axis name experts shard over when applied inside
        shard_map (None = single-program dense dispatch, still capacity-based).
      group_size: route tokens in fixed-size groups (the GShard convention, e.g.
        one sequence row per group). The dense dispatch/combine tensors are
        [N, E, C] with C ∝ N·cf/E — UNGROUPED that is O(N²·cf) elements and
        exhausts HBM at real batch·seq sizes; grouping bounds it at
        O(N·group_size·cf). None = one group (fine for small N / unit tests).
    """

    def __init__(self, hidden: int, ffn_dim: int, num_experts: int,
                 capacity_factor: float = 1.25,
                 expert_axis: Optional[str] = None,
                 group_size: Optional[int] = None,
                 top_k: int = 1,
                 dispatch: str = "einsum"):
        assert top_k in (1, 2), "top_k must be 1 (switch) or 2 (GShard)"
        assert dispatch in ("scatter", "einsum"), dispatch
        self.hidden = hidden
        self.ffn_dim = ffn_dim
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.expert_axis = expert_axis
        self.group_size = group_size
        self.top_k = top_k
        # "einsum" (default): the dense one-hot [N,E,C]x[N,H] contractions —
        # N*E*C*H MXU flops. "scatter": each kept token owns exactly one slot per
        # routed expert, so dispatch is a row scatter-add into the [E*C, H]
        # buffer and combine a row gather — O(N*H) HBM traffic, asymptotically
        # cheaper, but on the v5e chip XLA's row scatter/gather lowering LOSES
        # to the MXU einsum end-to-end (1.62 vs 1.28 ms/layer at the PERF.md
        # config, slope-timed) — wasted flops on a systolic array beat serialized
        # memory ops. Both modes are output- and gradient-identical.
        self.dispatch = dispatch

    # ------------------------------------------------------------------ params
    def init(self, rng, x=None):
        kg, k1, k2 = jax.random.split(rng, 3)
        H, F, E = self.hidden, self.ffn_dim, self.num_experts
        scale = 1.0 / math.sqrt(H)
        return {
            "gate_w": jax.random.normal(kg, (H, E), jnp.float32) * scale,
            # experts stacked on a leading E axis — the dim that shards over
            # the expert-parallel mesh axis
            "w_in": jax.random.normal(k1, (E, H, F), jnp.float32) * scale,
            "b_in": jnp.zeros((E, F), jnp.float32),
            "w_out": jax.random.normal(k2, (E, F, H), jnp.float32) / math.sqrt(F),
            "b_out": jnp.zeros((E, H), jnp.float32),
        }

    def param_shardings(self, mesh: Mesh, axis: Optional[str] = None):
        """Expert-sharded layouts (leading E axis over ``axis``); gate replicated."""
        axis = axis or self.expert_axis or MODEL_AXIS
        ex = NamedSharding(mesh, P(axis))
        return {"gate_w": NamedSharding(mesh, P()),
                "w_in": ex, "b_in": ex, "w_out": ex, "b_out": ex}

    # ---------------------------------------------------------------- routing
    def _route_plan(self, x2, gate_w, capacity):
        """ONE source of truth for the slot assignment (both dispatch encodings
        decode from this): top-1 (switch) or top-2 (GShard — second choices
        queue after every KEPT first choice per expert; a saturated router's
        phantom second pick is masked; gate weights normalized by p1+p2 even
        when the second pick drops, so the first is not re-normalized to 1).

        Returns (picks, (f, p)) where picks is a list of ``top_k`` tuples
        ``(expert [N] int32, pos [N] int32, keep [N] bool, weight [N] fp32)``
        — weight is the gate coefficient for the combine, NOT yet keep-masked —
        plus the Switch load-balancing statistics (callers under shard_map
        pmean (f, p) so the aux term is global)."""
        E, C = self.num_experts, capacity
        logits = jnp.dot(x2.astype(jnp.float32), gate_w.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)                     # [N, E]
        expert1 = jnp.argmax(probs, axis=-1)                        # [N]
        onehot1 = jax.nn.one_hot(expert1, E, dtype=jnp.float32)     # [N, E]
        pos1 = jnp.sum(jnp.cumsum(onehot1, axis=0) * onehot1 - onehot1, axis=-1)
        keep1 = pos1 < C
        p1 = jnp.sum(probs * onehot1, axis=-1)                      # [N]
        f = jnp.mean(onehot1, axis=0)                               # [E]
        p = jnp.mean(probs, axis=0)                                 # [E]
        e1 = expert1.astype(jnp.int32)
        pos1 = pos1.astype(jnp.int32)
        if self.top_k == 1:
            return [(e1, pos1, keep1, p1)], (f, p)
        probs2 = probs * (1.0 - onehot1)                            # mask the winner
        expert2 = jnp.argmax(probs2, axis=-1)
        onehot2 = jax.nn.one_hot(expert2, E, dtype=jnp.float32)
        onehot2 = onehot2 * (jnp.max(probs2, axis=-1) > 0)[:, None]
        first_counts = jnp.sum(onehot1 * keep1[:, None], axis=0)    # [E]
        pos2 = jnp.sum(jnp.cumsum(onehot2, axis=0) * onehot2 - onehot2
                       + first_counts[None, :] * onehot2, axis=-1)
        valid2 = jnp.sum(onehot2, axis=-1) > 0
        keep2 = (pos2 < C) & valid2
        p2 = jnp.sum(probs * onehot2, axis=-1)
        denom = jnp.maximum(p1 + p2, 1e-9)
        return [(e1, pos1, keep1, p1 / denom),
                (expert2.astype(jnp.int32), pos2.astype(jnp.int32), keep2,
                 p2 / denom)], (f, p)

    def _route(self, x2, gate_w, capacity):
        """Dense one-hot encoding of the plan: (dispatch [N, E, C] slot one-hot,
        combine [N, E, C] gate-weighted, (f, p))."""
        E, C = self.num_experts, capacity
        picks, fp = self._route_plan(x2, gate_w, capacity)
        dispatch = combine = 0.0
        for e, pos, keep, w in picks:
            d = (jax.nn.one_hot(e, E, dtype=jnp.float32)[:, :, None]
                 * jax.nn.one_hot(pos, C, dtype=jnp.float32)[:, None, :]
                 * keep[:, None, None])
            dispatch = dispatch + d
            combine = combine + d * w[:, None, None]
        return dispatch, combine, fp

    def _route_indexed(self, x2, gate_w, capacity):
        """Flat-slot encoding of the plan: each pick gets slot id
        ``expert * C + pos`` in ``[0, E*C)`` with ``E*C`` as the dropped/absent
        sentinel. Returns (slots [N, k] int32, weights [N, k] fp32 — zeroed on
        drop — and (f, p))."""
        E, C = self.num_experts, capacity
        picks, fp = self._route_plan(x2, gate_w, capacity)
        slots = [jnp.where(keep, e * C + pos, E * C) for e, pos, keep, _ in picks]
        weights = [(w * keep).astype(jnp.float32) for e, pos, keep, w in picks]
        return jnp.stack(slots, axis=1), jnp.stack(weights, axis=1), fp

    @staticmethod
    def _scatter_buf(x2, slots, n_slots):
        """Row scatter-add of tokens into their flat slots: [n_slots, H] buffer
        (one extra trash row swallows the drop sentinel)."""
        buf = jnp.zeros((n_slots + 1, x2.shape[-1]), x2.dtype)
        for i in range(slots.shape[1]):
            buf = buf.at[slots[:, i]].add(x2)
        return buf[:n_slots]

    @staticmethod
    def _gather_combine(out_flat, slots, weights, dtype):
        """Row gather of expert outputs back to token order, gate-weighted."""
        last = out_flat.shape[0] - 1
        y = None
        for i in range(slots.shape[1]):
            rows = out_flat[jnp.minimum(slots[:, i], last)]
            term = rows * weights[:, i][:, None].astype(out_flat.dtype)
            y = term if y is None else y + term
        return y.astype(dtype)

    @staticmethod
    def _expert_ffn(w_in, b_in, w_out, b_out, buf):
        """Batched expert MLP: ``buf [E_local, C*, H] -> [E_local, C*, H]``."""
        h = jnp.einsum("ech,ehf->ecf", buf, w_in.astype(buf.dtype),
                       preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h + b_in.astype(jnp.float32)[:, None, :])
        y = jnp.einsum("ecf,efh->ech", h.astype(buf.dtype),
                       w_out.astype(buf.dtype),
                       preferred_element_type=jnp.float32)
        return (y + b_out.astype(jnp.float32)[:, None, :]).astype(buf.dtype)

    # ------------------------------------------------------------------ apply
    def apply(self, params, x):
        """``x [.., H] -> (y [.., H], aux_loss)``; call inside shard_map when
        ``expert_axis`` is set (tokens sharded over any OTHER axis or replicated;
        expert params sharded over ``expert_axis``)."""
        orig_shape = x.shape
        H, E = self.hidden, self.num_experts
        x2 = x.reshape(-1, H)
        N = x2.shape[0]

        if self.expert_axis is None:
            g = self.group_size if (self.group_size and N % self.group_size == 0
                                    and N > self.group_size) else N
            G = N // g
            capacity = max(1, int(math.ceil(
                g / E * self.capacity_factor * self.top_k)))
            xg = x2.reshape(G, g, H)

            if self.dispatch == "scatter":
                def route_group(xr):
                    slots, w, (f, p) = self._route_indexed(xr, params["gate_w"],
                                                           capacity)
                    buf = self._scatter_buf(xr, slots, E * capacity)
                    return buf.reshape(E, capacity, H), (slots, w), f, p

                def combine_groups(out, plans):  # out [G, E, C, H]
                    slots, ws = plans
                    return jax.vmap(lambda o, s, w: self._gather_combine(
                        o.reshape(E * capacity, H), s, w, x2.dtype))(out, slots, ws)
            else:
                def route_group(xr):
                    dispatch, combine, (f, p) = self._route(xr, params["gate_w"],
                                                            capacity)
                    buf = jnp.einsum("nec,nh->ech", dispatch.astype(xr.dtype), xr)
                    return buf, combine, f, p

                def combine_groups(out, combines):
                    return jnp.einsum("gnec,gech->gnh", combines.astype(out.dtype),
                                      out)

            bufs, plans, fs, ps = jax.vmap(route_group)(xg)  # [G, E, C, H], ...
            stacked = bufs.transpose(1, 0, 2, 3).reshape(E, G * capacity, H)
            out = self._expert_ffn(params["w_in"], params["b_in"],
                                   params["w_out"], params["b_out"], stacked)
            out = out.reshape(E, G, capacity, H).transpose(1, 0, 2, 3)
            y = combine_groups(out, plans)
            # mean over groups of the per-group balancing term (Switch eq. 4
            # computed per routing group, the same convention a sharded run uses)
            aux = E * jnp.mean(jnp.sum(fs * ps, axis=-1))
            return y.reshape(orig_shape), aux

        axis = self.expert_axis
        ep = axis_size(axis)
        assert E % ep == 0, \
            f"num_experts {E} must be divisible by the expert-parallel degree {ep}"
        e_local = E // ep
        # per-RANK per-expert capacity (GShard convention): each rank may send up
        # to C of its local tokens to any expert; an expert processes ep*C slots
        # total (= the global capacity). Local overflow drops even if other ranks
        # underuse their slots — the standard static-shape trade.
        capacity = max(1, int(math.ceil(N / E * self.capacity_factor * self.top_k)))
        # shard_map hands the expert-sharded leaves as [E_local, ...] slices
        gate_w = params["gate_w"]
        if self.dispatch == "scatter":
            slots, weights, (f, p) = self._route_indexed(x2, gate_w, capacity)
            buf = self._scatter_buf(x2, slots, E * capacity).reshape(E, capacity, H)
        else:
            dispatch, combine, (f, p) = self._route(x2, gate_w, capacity)
            # local [E, C, H] buffer -> all_to_all so rank r receives its local
            # experts' slices from EVERY rank: [ep, e_local, C, H] with a peer axis
            buf = jnp.einsum("nec,nh->ech", dispatch.astype(x2.dtype), x2)
        buf = buf.reshape(ep, e_local, capacity, H)
        recv = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                  tiled=False)                 # [ep, e_local, C, H]
        stacked = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, H)
        out = self._expert_ffn(params["w_in"], params["b_in"],
                               params["w_out"], params["b_out"], stacked)
        out = out.reshape(e_local, ep, capacity, H).transpose(1, 0, 2, 3)
        back = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                  tiled=False)                 # [ep, e_local, C, H]
        back = back.reshape(E, capacity, H)
        if self.dispatch == "scatter":
            y = self._gather_combine(back.reshape(E * capacity, H), slots,
                                     weights, x2.dtype)
        else:
            y = jnp.einsum("nec,ech->nh", combine.astype(back.dtype), back)
        # global load-balance statistics (mean over the full token batch)
        f = jax.lax.pmean(f, axis)
        p = jax.lax.pmean(p, axis)
        aux = E * jnp.sum(f * p)
        return y.reshape(orig_shape), aux


def moe_apply_sharded(layer: MoELayer, mesh: Mesh, params, x,
                      tokens_axis: Optional[str] = None):
    """Convenience wrapper: run an expert-sharded MoELayer over ``mesh`` from
    global arrays. ``tokens_axis`` optionally shards the flat token batch's
    leading dim (data parallelism composes with expert parallelism)."""
    axis = layer.expert_axis
    assert axis is not None, "layer must be constructed with expert_axis"
    # ONE source of truth for the layout: derive the shard_map specs from
    # param_shardings (a new param added there is automatically honored here)
    shardings = layer.param_shardings(mesh, axis)
    pspecs = {k: s.spec for k, s in shardings.items()}
    x_spec = P(*([tokens_axis] + [None] * (x.ndim - 1))) if tokens_axis else P()

    def local(params, x):
        y, aux = layer.apply(params, x)
        if tokens_axis:
            aux = jax.lax.pmean(aux, tokens_axis)
        return y, aux

    fn = shard_map(local, mesh=mesh, in_specs=(pspecs, x_spec),
                   out_specs=(x_spec, P()), check_vma=False)
    return fn(jax.device_put(params, shardings), x)
