"""N-dimensional process/device topology.

TPU-native re-design of ``deepspeed/runtime/pipe/topology.py`` (ProcessTopology l.12,
PipeDataParallelTopology l.235, PipeModelDataParallelTopology l.246, PipelineParallelGrid
l.252). The cartesian rank math is identical; "process groups" become named axes of a
``jax.sharding.Mesh`` — a group along axis X is simply the set of devices sharing all other
mesh coordinates, and collectives over it are `psum`/`all_gather`/... with ``axis_name=X``.
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List, Optional


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates to linear global ranks.

    The ordering of axes is from outer to inner: the last axis varies fastest
    (row-major, matching the reference).
    """

    def __init__(self, axes: List[str], dims: List[int]):
        self.axes = list(axes)
        self.dims = list(dims)
        assert len(self.axes) == len(self.dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict["ProcessTopology.ProcessCoord", int] = {}
        self._rank_to_coord: List["ProcessTopology.ProcessCoord"] = []
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            named = self.ProcessCoord(**key)
            self.mapping[named] = global_rank
            self._rank_to_coord.append(named)

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError("get_rank() does not support slices, use filter_match())")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {key} not found in topology."
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-") -> str:
        """Checkpoint-name representation of a rank, omitting data/pipe axes by default."""
        omit_axes = frozenset(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        if 0 <= rank < len(self._rank_to_coord):
            return self._rank_to_coord[rank]
        raise ValueError(f"rank {rank} not found in topology.")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All communication groups along ``axis``: lists of ranks differing only in
        ``axis``. Computed by bucketing the precomputed rank table on the remaining
        coordinates — one pass, no cartesian re-enumeration. Because ranks enumerate
        coordinates row-major, bucket insertion order reproduces the conventional
        (outer-axes row-major) group ordering and each bucket is ordered by axis index."""
        if axis not in self.axes:
            return []
        ai = self.axes.index(axis)
        buckets: Dict[tuple, List[int]] = {}
        for rank, coord in enumerate(self._rank_to_coord):
            buckets.setdefault(coord[:ai] + coord[ai + 1:], []).append(rank)
        return list(buckets.values())

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all of the given axis=value filters, ascending
        (rank-table scan order is already ascending)."""
        return [rank for rank, coord in enumerate(self._rank_to_coord)
                if all(getattr(coord, ax) == val for ax, val in filter_kwargs.items())]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        size = 1
        for d in self.dims:
            size *= d
        return size

    def __str__(self):
        return str(self.mapping)


def _prime_factors(N: int) -> List[int]:
    """Prime factorization in increasing order."""
    if N <= 0:
        raise ValueError("Values must be strictly positive")
    primes = []
    while N != 1:
        for candidate in range(2, N + 1):
            if N % candidate == 0:
                primes.append(candidate)
                N //= candidate
                break
    return primes


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline + data parallelism: adjacent pipe stages land on the same
    host's devices so activations ride ICI (reference topology.py:235-244)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3-D topology for DP x PP x TP ("model"/slice) parallelism."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis bookkeeping for a 2-D/3-D grid, serving as the rebuild's ``mpu``.

    Unlike the reference (which creates NCCL process groups, topology.py:299-364), groups
    here are *rank lists* plus mesh-axis names; actual communication happens through XLA
    collectives over the corresponding mesh axis. The rank math (stage_id, data_parallel_id,
    p2p neighbors) is preserved so schedules and checkpoint layouts match.
    """

    def __init__(self, topology: Optional[ProcessTopology] = None, world_size: Optional[int] = None,
                 global_rank: int = 0):
        if world_size is None:
            world_size = topology.world_size() if topology is not None else 1
        self.global_rank = global_rank
        self.world_size = world_size
        if topology is not None:
            self._topo = topology
        else:
            # Default: split world into pipe x data using prime factors (reference l.279-287).
            num_pp = 1
            num_dp = 1
            for idx, prime in enumerate(_prime_factors(world_size)):
                if idx % 2 == 0:
                    num_pp *= prime
                else:
                    num_dp *= prime
            self._topo = PipeDataParallelTopology(num_pp=num_pp, num_dp=num_dp)
        self.data_parallel_size = max(self._topo.get_dim("data"), 1)
        self.pipe_parallel_size = max(self._topo.get_dim("pipe"), 1)
        self.model_parallel_size = max(self._topo.get_dim("model"), 1)
        assert self._is_grid_valid(), "Invalid Grid"

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()

        # Rank lists per axis (the reference's process groups).
        self.ds_model_proc_group = None
        self.ds_model_rank = -1
        for dp in range(self.data_parallel_size):
            # "model" group in DeepSpeed-speak = all non-data ranks (pipe x slice).
            ranks = sorted(self._topo.filter_match(data=dp))
            if self.global_rank in ranks:
                self.ds_model_proc_group = ranks
                self.ds_model_world_size = len(ranks)
                self.ds_model_rank = ranks.index(self.global_rank)
        assert self.ds_model_rank > -1
        assert self.ds_model_proc_group is not None

        self.dp_group = []
        self.dp_groups = self._topo.get_axis_comm_lists("data")
        for g in self.dp_groups:
            if self.global_rank in g:
                self.dp_group = g

        self.is_first_stage = self.stage_id == 0
        self.is_last_stage = self.stage_id == (self.pipe_parallel_size - 1)

        self.p2p_groups = self._build_p2p_groups()

        self.pp_group = []
        self.pipe_groups = self._topo.get_axis_comm_lists("pipe")
        for g in self.pipe_groups:
            if self.global_rank in g:
                self.pp_group = g

        self.slice_group = []
        self.slice_proc_group = None
        if "model" in self._topo.get_axis_names():
            self.mp_group = []
            self.model_groups = self._topo.get_axis_comm_lists("model")
            for g in self.model_groups:
                if self.global_rank in g:
                    self.slice_group = g
                    self.slice_proc_group = g
        else:
            self.slice_group = [self.global_rank]
            self.slice_proc_group = [self.global_rank]

    def get_stage_id(self) -> int:
        return self._topo.get_coord(rank=self.global_rank).pipe

    def get_data_parallel_id(self) -> int:
        return self._topo.get_coord(rank=self.global_rank).data

    def _build_p2p_groups(self) -> List[List[int]]:
        """Adjacent-stage rank pairs, incl. wrap-around (reference topology.py:372-387)."""
        comm_lists = self._topo.get_axis_comm_lists("pipe")
        p2p_lists = []
        for rank in range(self.world_size):
            for l in comm_lists:
                assert len(l) == self.pipe_parallel_size
                if rank in l:
                    idx = l.index(rank)
                    buddy_rank = l[(idx + 1) % self.pipe_parallel_size]
                    p2p_lists.append([rank, buddy_rank])
                    break
        assert len(p2p_lists) == self.world_size
        return p2p_lists

    def _is_grid_valid(self) -> bool:
        ranks = 1
        for ax in self._topo.get_axis_names():
            ranks *= self._topo.get_dim(ax)
        return ranks == self.world_size

    def stage_to_global(self, stage_id: int, **kwargs) -> int:
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def topology(self) -> ProcessTopology:
        return self._topo

    # -- mpu interface (reference topology.py:405-455) --
    def get_global_rank(self) -> int:
        return self.global_rank

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_pipe_parallel_group(self) -> List[int]:
        return self.pp_group

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_data_parallel_group(self) -> List[int]:
        return self.dp_group

    def get_model_parallel_rank(self) -> int:
        return self.ds_model_rank

    def get_model_parallel_world_size(self) -> int:
        return self.ds_model_world_size

    def get_model_parallel_group(self) -> List[int]:
        return self.ds_model_proc_group

    def get_slice_parallel_rank(self) -> int:
        if "model" in self._topo.get_axis_names():
            return self._topo.get_coord(rank=self.global_rank).model
        return 0

    def get_slice_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def get_slice_parallel_group(self) -> List[int]:
        return self.slice_group
