from .topology import (ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
                       PipelineParallelGrid)
from .mesh import build_mesh, single_device_mesh, data_sharding, replicated, mesh_from_mpu, \
    DATA_AXIS, MODEL_AXIS, PIPE_AXIS
