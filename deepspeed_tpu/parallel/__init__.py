from .topology import (ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
                       PipelineParallelGrid)
from .mesh import build_mesh, single_device_mesh, data_sharding, replicated, mesh_from_mpu, \
    DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from .ring_attention import (ring_attention, ring_attention_sharded,
                             ring_work_schedule, zigzag_shard, zigzag_unshard)
