"""Declarative pipeline model description.

TPU-native analog of ``deepspeed/runtime/pipe/module.py`` (LayerSpec l.23, TiedLayerSpec
l.71, PipelineModule l.85). A PipelineModule is a declarative list of layer constructors;
``partition_layers`` balances them across stages (partition_balanced, reference
runtime/utils.py:361). Unlike the reference — which instantiates only stage-local torch
modules on each rank — the single-controller JAX build instantiates pure layer functions
and stores per-stage parameter pytrees; execution happens in the pipeline engine via
shard_map over the ``pipe`` mesh axis.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from ...runtime.utils import partition_balanced, partition_uniform
from ...utils import logger
from ..topology import PipeDataParallelTopology, PipelineParallelGrid, ProcessTopology


class LayerSpec:
    """Delays construction of a layer: stores class + args, builds on demand."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(type(typename), type):
            raise RuntimeError("LayerSpec only supports classes (callables built at build())")

    def build(self, log=False):
        if log:
            logger.info(f"Building layer {self.typename.__name__}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        from ...runtime.utils import call_to_str
        return call_to_str(self.typename.__name__, *self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """LayerSpec whose parameters are shared with every other TiedLayerSpec of the same key
    (reference module.py:71-83: tied embeddings)."""

    def __init__(self, key, typename, *module_args, forward_fn=None, tied_weight_attr="embedding",
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """Declarative layer list → stage partitioning.

    Layers must be "pure-function modules": objects with ``init(rng, x) -> params`` and
    ``apply(params, x) -> y`` (flax modules qualify), or bare callables (no params).
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology: Optional[ProcessTopology] = None,
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0):
        if num_stages is None and topology is None:
            raise RuntimeError("must provide num_stages or topology")
        self._layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self._partition_method = partition_method

        if topology is None:
            topology = PipeDataParallelTopology(num_pp=num_stages, num_dp=1)
        self._topo = topology
        self.num_stages = self._topo.get_dim("pipe")
        self._grid = PipelineParallelGrid(topology=self._topo, global_rank=0)

        # build all layers (single-controller: we own every stage's params)
        self.forward_funcs: List[Callable] = []
        self.tied_modules: Dict[str, Any] = {}
        self.tied_specs: Dict[str, TiedLayerSpec] = {}
        self._built_layers: List[Any] = []
        for idx, spec in enumerate(self._layer_specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in self.tied_modules:
                    self.tied_modules[spec.key] = spec.build()
                    self.tied_specs[spec.key] = spec
                self._built_layers.append(self.tied_modules[spec.key])
            elif isinstance(spec, LayerSpec):
                self._built_layers.append(spec.build())
            elif callable(spec):
                self._built_layers.append(spec)
            else:
                raise TypeError(f"Layer spec {spec} is not callable or a LayerSpec")

        self.parts = self._partition_layers(method=self._partition_method)

    # ---------------- partitioning ----------------
    def _count_layer_params(self) -> List[int]:
        """Approximate parameter counts per layer for 'parameters' balancing."""
        counts = []
        for layer in self._built_layers:
            n = 0
            shapes = getattr(layer, "param_shapes", None)
            if callable(shapes):
                try:
                    import numpy as np
                    n = int(sum(np.prod(s) for s in shapes()))
                except Exception:
                    n = 0
            counts.append(n)
        return counts

    def _partition_layers(self, method="uniform") -> List[int]:
        num_stages = self.num_stages
        num_layers = len(self._built_layers)
        method = method.lower()
        if method == "uniform":
            parts = partition_uniform(num_items=num_layers, num_parts=num_stages)
        elif method == "parameters":
            param_counts = self._count_layer_params()
            if sum(param_counts) == 0:
                parts = partition_uniform(num_items=num_layers, num_parts=num_stages)
            else:
                parts = partition_balanced(weights=param_counts, num_parts=num_stages)
        elif method.startswith("type:"):
            layertype = method.split(":", 1)[1]
            binary_weights = [0] * num_layers
            for idx, layer in enumerate(self._built_layers):
                if re.search(layertype, type(layer).__name__, re.IGNORECASE):
                    binary_weights[idx] = 1
            parts = partition_balanced(weights=binary_weights, num_parts=num_stages)
        elif method == "profile":
            raise NotImplementedError("Partitioning method 'profile' not implemented")
        else:
            raise NotImplementedError(f"Partitioning method {method} not implemented")
        return parts

    def stage_layers(self, stage_id: int) -> List[Any]:
        return self._built_layers[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_owner(self, layer_idx: int) -> int:
        for stage in range(self.num_stages):
            if self.parts[stage] <= layer_idx < self.parts[stage + 1]:
                return stage
        raise ValueError(f"layer {layer_idx} out of range")

    def topology(self) -> ProcessTopology:
        return self._topo

    def mpu(self) -> PipelineParallelGrid:
        return self._grid

    def num_layers(self) -> int:
        return len(self._built_layers)

    # parameter init for all layers: returns list (per layer) of params pytrees
    def init_params(self, rng, sample_input):
        """Initialize every layer sequentially, threading activation shapes."""
        params = []
        x = sample_input
        tied_params: Dict[str, Any] = {}
        for idx, (spec, layer) in enumerate(zip(self._layer_specs, self._built_layers)):
            if self.seed_layers:
                rng_layer = jax.random.PRNGKey(self.base_seed + idx)
            else:
                rng, rng_layer = jax.random.split(rng)
            if hasattr(layer, "init"):
                if isinstance(spec, TiedLayerSpec) and spec.key in tied_params:
                    p = tied_params[spec.key]
                else:
                    p = layer.init(rng_layer, x)
                    if isinstance(spec, TiedLayerSpec):
                        tied_params[spec.key] = p
                if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
                    x = spec.forward_fn(layer, p, x)
                else:
                    x = layer.apply(p, x)
            else:
                p = None
                x = layer(x)
            params.append(p)
        return params
