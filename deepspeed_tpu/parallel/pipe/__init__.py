from .module import LayerSpec, TiedLayerSpec, PipelineModule
