"""Ring attention: sequence/context parallelism over a mesh axis.

TPU-first long-context capability beyond the reference's feature set (the reference's
long-sequence answer is block-sparse attention, ops/sparse_attention/*; it has no
sequence parallelism). Here the SEQUENCE dimension shards over a mesh axis: each rank
holds a [B, H, T/n, D] slice of q/k/v, k/v chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchanges), and each visit runs the local flash kernel
(ops/pallas/flash_attention.py) against the visiting chunk, combining the per-chunk
``(out, lse)`` pairs with the standard online-softmax merge. Per-chip attention state
is O(T/n) and the flash kernel only ever sees chunk-sized operands — this is the
supported path past the single-chip kernel's whole-K/V VMEM cap (T >= ~16k at d=64)
and, composed with the ``data``/``model``/``pipe`` axes, the 4th parallelism
dimension.

Differentiability comes for free: ``flash_attention_with_lse`` is differentiable in
BOTH outputs (its lse cotangent folds into the flash backward's delta term), so
``jax.grad`` of the ring — combine, ppermute rotations and all — yields the correct
backward ring (ppermute transposes to the reverse rotation; no hand-written
gradient ring). Memory note: the autodiff residuals hold each visiting k/v chunk,
i.e. O(T_total x D) per rank for k/v — linear in sequence length (the O(T^2) score
matrix never exists), matching published ring-attention implementations that save
rotated chunks; wrap the model in ``jax.checkpoint`` to trade that for a second
forward ring.

Causal mode: the diagonal chunk applies the in-kernel triangular mask (q/k offsets
are equal there); strictly-past chunks attend fully; strictly-future chunks are
neutralized by setting their lse to -inf before the merge. Future-chunk compute is
masked, not skipped — collective uniformity across ranks is worth the ~2x causal
compute overhead at this level (the per-chip flash still prunes within the diagonal
chunk).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas.flash_attention import _merge_partial, flash_attention_with_lse
from .mesh import DATA_AXIS, axis_size, shard_map


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   interpret: Optional[bool] = None,
                   dropout_rate: float = 0.0, dropout_seed=None):
    """Attention over a sequence sharded on ``axis_name`` (call inside shard_map).

    Args:
      q, k, v: LOCAL [B, H, T_local, D] shards; global sequence = n * T_local in
        ring order (rank r holds positions [r*T_local, (r+1)*T_local)).
      axis_name: mesh axis the sequence is sharded over.
      dropout_rate/dropout_seed: in-kernel attention dropout. Each rank hashes
        GLOBAL coordinates (its q offset is rank*T_local; the visiting chunk's k
        offset follows the rotation), so the sampled mask is identical to a
        single-chip kernel's over the full sequence — ``dropout_keep_reference``
        at global T stays the oracle, and the mask is invariant to ring size.
    Returns the LOCAL [B, H, T_local, D] attention output. Differentiable in q/k/v.
    """
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    T_local = q.shape[2]
    # chunks step to the NEXT rank each rotation: after r steps rank i holds the
    # k/v chunk originally at rank (i - r) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = lse = None
    kc, vc = k, v
    for r in range(n):
        if r > 0:
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
        out_r, lse_r = flash_attention_with_lse(
            q, kc, vc, causal=(causal and r == 0), sm_scale=sm_scale,
            interpret=interpret, dropout_rate=dropout_rate,
            dropout_seed=dropout_seed,
            dropout_q_offset=rank * T_local,
            dropout_k_offset=((rank - r) % n) * T_local)
        if causal and r > 0:
            src = (rank - r) % n
            keep = src < rank  # strictly-past chunks attend; future contribute zero
            lse_r = jnp.where(keep, lse_r, -jnp.inf)
            out_r = jnp.where(keep, out_r, jnp.zeros((), out_r.dtype))
        if o is None:
            o, lse = out_r.astype(jnp.float32), lse_r
        else:
            # online-softmax merge of normalized partials (shared with the
            # single-chip chunked flash path)
            o, lse = _merge_partial(o, lse, out_r, lse_r)
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = DATA_AXIS,
                           causal: bool = False, sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           dropout_rate: float = 0.0, dropout_seed=None):
    """Convenience wrapper: global [B, H, T, D] arrays, sequence sharded over
    ``seq_axis`` (dim 2). Places inputs if they aren't already sharded."""
    assert q.shape[2] % mesh.shape[seq_axis] == 0, \
        f"seq {q.shape[2]} must divide over {seq_axis}={mesh.shape[seq_axis]}"
    spec = P(None, None, seq_axis, None)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (x if getattr(x, "sharding", None) == sharding else
               jax.device_put(x, sharding) for x in (q, k, v))
    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                          sm_scale=sm_scale, interpret=interpret,
                          dropout_rate=dropout_rate, dropout_seed=dropout_seed),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)
