"""Ring attention: sequence/context parallelism over a mesh axis.

TPU-first long-context capability beyond the reference's feature set (the reference's
long-sequence answer is block-sparse attention, ops/sparse_attention/*; it has no
sequence parallelism). Here the SEQUENCE dimension shards over a mesh axis: each rank
holds a [B, H, T/n, D] slice of q/k/v, k/v chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchanges), and each visit runs the local flash kernel
(ops/pallas/flash_attention.py) against the visiting chunk, combining the per-chunk
``(out, lse)`` pairs with the standard online-softmax merge. Per-chip attention state
is O(T/n) and the flash kernel only ever sees chunk-sized operands — this is the
supported path past the single-chip kernel's whole-K/V VMEM cap (T >= ~16k at d=64)
and, composed with the ``data``/``model``/``pipe`` axes, the 4th parallelism
dimension.

Differentiability comes for free: ``flash_attention_with_lse`` is differentiable in
BOTH outputs (its lse cotangent folds into the flash backward's delta term), so
``jax.grad`` of the ring — combine, ppermute rotations and all — yields the correct
backward ring (ppermute transposes to the reverse rotation; no hand-written
gradient ring). Memory note: the autodiff residuals hold each visiting k/v chunk,
i.e. O(T_total x D) per rank for k/v — linear in sequence length (the O(T^2) score
matrix never exists), matching published ring-attention implementations that save
rotated chunks; wrap the model in ``jax.checkpoint`` to trade that for a second
forward ring.

Causal mode has two schedules:

``schedule="masked"`` (the original ring, kept as oracle): ranks hold contiguous
chunks; the diagonal chunk applies the in-kernel triangular mask, strictly-past
chunks attend fully, strictly-future chunks are computed then neutralized by
setting their lse to -inf before the merge — collective uniformity across ranks
at a ~2x causal compute tax (rank 0 sees n-1 all-future visits).

``schedule="zigzag"`` (the default causal path): the sequence is re-sharded so
rank ``i`` of an ``n``-ring holds global chunks ``i`` and ``2n-1-i`` of size
``C = T/(2n)`` (``zigzag_shard``; Brandon et al. 2023, "Striped Attention"). Each
rank's local [2C] block is an early+late interleave, so EVERY (rank, rotation)
pair contains useful work: rotation 0 is one interleaved causal flash call (the
local order is globally monotone, so the kernel's block pruning is exact), and
every later rotation is exactly two fully-unmasked C x C calls — the visiting
low chunk is always past for the local high half, and one where-routed call
covers the remaining past half-chunk (low->low for past sources, high->high for
future sources). k/v rotate as before (same ppermute count and bytes), no
compute is ever discarded, and the per-rank work is identical across ranks
(``ring_work_schedule`` is the accounting). Dropout stays exact: every call
hashes GLOBAL coordinates via the kernel's offset/segment operand.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas.flash_attention import _merge_partial, flash_attention_with_lse
from .mesh import DATA_AXIS, axis_size, shard_map

SCHEDULES = ("zigzag", "masked")


# --------------------------------------------------------------------- zigzag layout
def _zigzag_chunk_order(n: int):
    """Global chunk index (of 2n chunks) at each position of the rank-concatenated
    zigzag layout: rank i holds [chunk i, chunk 2n-1-i]."""
    order = []
    for i in range(n):
        order.extend((i, 2 * n - 1 - i))
    return order


def zigzag_shard(x, n: int, axis: int = 2):
    """Reorder a contiguous global sequence dim into the zigzag ring layout.

    Splits dim ``axis`` (length T, requires ``T % 2n == 0``) into ``2n`` chunks and
    concatenates them in rank order ``[0, 2n-1, 1, 2n-2, ...]``, so sharding the
    result contiguously over an ``n``-way mesh axis gives rank ``i`` global chunks
    ``(i, 2n-1-i)`` — every rank holds a balanced early+late mix of positions.
    A static gather; the inverse is ``zigzag_unshard``.
    """
    T = x.shape[axis]
    assert T % (2 * n) == 0, f"zigzag_shard: seq {T} must be divisible by 2n={2 * n}"
    c = T // (2 * n)
    idx = np.concatenate([np.arange(j * c, (j + 1) * c)
                          for j in _zigzag_chunk_order(n)])
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_unshard(x, n: int, axis: int = 2):
    """Inverse of ``zigzag_shard``: zigzag ring layout back to contiguous order."""
    T = x.shape[axis]
    assert T % (2 * n) == 0, f"zigzag_unshard: seq {T} must be divisible by 2n={2 * n}"
    c = T // (2 * n)
    fwd = np.concatenate([np.arange(j * c, (j + 1) * c)
                          for j in _zigzag_chunk_order(n)])
    inv = np.argsort(fwd)
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def ring_work_schedule(n: int, schedule: str = "zigzag"):
    """Per-(rotation, rank) work accounting for the causal ring, in units of
    ``C x C`` score blocks where ``C = T/(2n)`` (half a rank's local sequence).

    ``computed`` counts blocks the flash kernel actually runs (after its in-kernel
    block pruning); ``useful`` counts non-masked score blocks (diagonal blocks are
    half-masked and count 1 computed / 0.5 useful). The masked schedule computes 4
    blocks every rotation on every rank but only past-source visits are useful;
    zigzag computes exactly the useful blocks, identically on every rank.
    Returns ``{"schedule", "n", "rotations": [{"r", "computed_per_rank",
    "useful_min", "useful_max"}], "total_computed", "total_useful"}`` with totals
    per rank summed over rotations.
    """
    assert schedule in SCHEDULES, f"schedule must be one of {SCHEDULES}"
    rotations = []
    for r in range(n):
        if r == 0:
            # both schedules: one causal call on the local [2C] block — the kernel
            # prunes to 3 computed blocks (two diagonal, one full)
            computed, useful = (3.0, 2.0)
            u_min = u_max = useful
        elif schedule == "masked":
            computed = 4.0  # full [2C x 2C] visit, masked or not
            # rank i's visit r is useful iff src=(i-r)%n < i, i.e. i >= r
            u_min, u_max = 0.0, 4.0
        else:
            computed = 2.0  # two C x C calls, both fully unmasked
            u_min = u_max = 2.0
        rotations.append({"r": r, "computed_per_rank": computed,
                          "useful_min": u_min, "useful_max": u_max})
    total_computed = sum(row["computed_per_rank"] for row in rotations)
    if schedule == "masked":
        # useful totals: rank i gets 2 (diagonal) + 4*i (past visits); average over
        # ranks = 2 + 2(n-1)
        total_useful = 2.0 + 2.0 * (n - 1)
    else:
        total_useful = 2.0 + 2.0 * (n - 1)
    return {"schedule": schedule, "n": n, "rotations": rotations,
            "total_computed": total_computed, "total_useful": total_useful}


# ------------------------------------------------------------------------- schedules
def _masked_ring(q, k, v, axis_name, causal, sm_scale, interpret, rate, seed):
    """Contiguous-layout ring: rank r holds positions [r*T_local, (r+1)*T_local)."""
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    T_local = q.shape[2]
    # chunks step to the NEXT rank each rotation: after r steps rank i holds the
    # k/v chunk originally at rank (i - r) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = lse = None
    kc, vc = k, v
    for r in range(n):
        # named_scope: rotations show up as ring_rot{r} in profiler traces
        # (HLO metadata only — zero instructions, identical wire schedule)
        with jax.named_scope(f"ring_rot{r}"):
            if r > 0:
                kc = jax.lax.ppermute(kc, axis_name, perm)
                vc = jax.lax.ppermute(vc, axis_name, perm)
            out_r, lse_r = flash_attention_with_lse(
                q, kc, vc, causal=(causal and r == 0), sm_scale=sm_scale,
                interpret=interpret, dropout_rate=rate,
                dropout_seed=seed,
                dropout_q_offset=rank * T_local,
                dropout_k_offset=((rank - r) % n) * T_local)
            if causal and r > 0:
                src = (rank - r) % n
                keep = src < rank  # strictly-past chunks attend; future contribute zero
                lse_r = jnp.where(keep, lse_r, -jnp.inf)
                out_r = jnp.where(keep, out_r, jnp.zeros((), out_r.dtype))
            if o is None:
                o, lse = out_r.astype(jnp.float32), lse_r
            else:
                # online-softmax merge of normalized partials (shared with the
                # single-chip chunked flash path)
                o, lse = _merge_partial(o, lse, out_r, lse_r)
    return o.astype(q.dtype)


def _zigzag_ring(q, k, v, axis_name, sm_scale, interpret, rate, seed):
    """Zigzag-layout causal ring: rank i holds global chunks (i, 2n-1-i), each of
    size C = T_local/2. See the module docstring for the schedule; the masked
    schedule above is the oracle it must match after ``zigzag_unshard``."""
    n = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    T_local = q.shape[2]
    assert T_local % 2 == 0, f"zigzag needs an even local seq, got {T_local}"
    C = T_local // 2
    perm = [(i, (i + 1) % n) for i in range(n)]

    lo_off = rank * C                 # global start of the local low (early) chunk
    hi_off = (2 * n - 1 - rank) * C   # global start of the local high (late) chunk
    q_lo, q_hi = q[:, :, :C], q[:, :, C:]

    # rotation 0: ONE interleaved causal call over the whole local [2C] block. The
    # local order is globally monotone (chunk i entirely precedes chunk 2n-1-i) and
    # q/k segment maps are identical, so the kernel's local causal pruning is exact;
    # the segment operand puts mask + dropout in global coordinates.
    with jax.named_scope("ring_rot0"):
        out0, lse0 = flash_attention_with_lse(
            q, k, v, causal=True, sm_scale=sm_scale, interpret=interpret,
            dropout_rate=rate, dropout_seed=seed,
            q_segments=(lo_off, hi_off), k_segments=(lo_off, hi_off))
        o_lo, lse_lo = out0[:, :, :C].astype(jnp.float32), lse0[:, :, :C]
        o_hi, lse_hi = out0[:, :, C:].astype(jnp.float32), lse0[:, :, C:]

    kc, vc = k, v
    for r in range(1, n):
      with jax.named_scope(f"ring_rot{r}"):
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        src = (rank - r) % n
        k_lo, k_hi = kc[:, :, :C], kc[:, :, C:]
        v_lo, v_hi = vc[:, :, :C], vc[:, :, C:]
        src_lo = src * C
        src_hi = (2 * n - 1 - src) * C

        # call A: q_hi x src's low chunk — ALWAYS fully past (src <= n-1 implies
        # src*C + C <= n*C <= hi_off), so no mask and no wasted work on any rank.
        out_a, lse_a = flash_attention_with_lse(
            q_hi, k_lo, v_lo, causal=False, sm_scale=sm_scale, interpret=interpret,
            dropout_rate=rate, dropout_seed=seed,
            dropout_q_offset=hi_off, dropout_k_offset=src_lo)
        o_hi, lse_hi = _merge_partial(o_hi, lse_hi, out_a, lse_a)

        # call B: the remaining past half-chunk, where-routed so every rank issues
        # the same shapes (uniform SPMD program). Past source (src < rank): its low
        # chunk strictly precedes ours -> q_lo x k_lo. Future source: its HIGH
        # chunk strictly precedes our high chunk (2n-1-src < 2n-1-rank) ->
        # q_hi x k_hi. Both are fully unmasked; dropout offsets route with them.
        past = src < rank
        q_b = jnp.where(past, q_lo, q_hi)
        k_b = jnp.where(past, k_lo, k_hi)
        v_b = jnp.where(past, v_lo, v_hi)
        out_b, lse_b = flash_attention_with_lse(
            q_b, k_b, v_b, causal=False, sm_scale=sm_scale, interpret=interpret,
            dropout_rate=rate, dropout_seed=seed,
            dropout_q_offset=jnp.where(past, lo_off, hi_off),
            dropout_k_offset=jnp.where(past, src_lo, src_hi))
        # route the partial into the half it belongs to; the -inf lse gates the
        # other half's merge to a no-op (grad-safe — same mechanism the masked
        # schedule uses to neutralize future chunks)
        zero = jnp.zeros((), out_b.dtype)
        o_lo, lse_lo = _merge_partial(o_lo, lse_lo,
                                      jnp.where(past, out_b, zero),
                                      jnp.where(past, lse_b, -jnp.inf))
        o_hi, lse_hi = _merge_partial(o_hi, lse_hi,
                                      jnp.where(past, zero, out_b),
                                      jnp.where(past, -jnp.inf, lse_b))
    return jnp.concatenate([o_lo, o_hi], axis=2).astype(q.dtype)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   interpret: Optional[bool] = None,
                   dropout_rate: float = 0.0, dropout_seed=None,
                   schedule: str = "zigzag"):
    """Attention over a sequence sharded on ``axis_name`` (call inside shard_map).

    Args:
      q, k, v: LOCAL [B, H, T_local, D] shards. Layout depends on the causal
        schedule: the non-causal ring and ``schedule="masked"`` use ring order
        (rank r holds positions [r*T_local, (r+1)*T_local)); the default causal
        ``schedule="zigzag"`` expects the ``zigzag_shard`` layout (rank i holds
        global chunks i and 2n-1-i of size T_local/2).
      axis_name: mesh axis the sequence is sharded over.
      dropout_rate/dropout_seed: in-kernel attention dropout. Each call hashes
        GLOBAL coordinates (via scalar offsets or the zigzag segment operand), so
        the sampled mask is identical to a single-chip kernel's over the full
        sequence — ``dropout_keep_reference`` at global T stays the oracle, and
        the mask is invariant to ring size and schedule.
      schedule: causal schedule, ``"zigzag"`` (balanced, no masked-compute tax;
        default) or ``"masked"`` (contiguous layout, kept as the oracle).
        Ignored when ``causal=False``.
    Returns the LOCAL [B, H, T_local, D] attention output (same layout as the
    inputs). Differentiable in q/k/v.
    """
    assert schedule in SCHEDULES, f"schedule must be one of {SCHEDULES}, got {schedule!r}"
    if causal and schedule == "zigzag":
        return _zigzag_ring(q, k, v, axis_name, sm_scale, interpret,
                            dropout_rate, dropout_seed)
    return _masked_ring(q, k, v, axis_name, causal, sm_scale, interpret,
                        dropout_rate, dropout_seed)


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = DATA_AXIS,
                           causal: bool = False, sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           dropout_rate: float = 0.0, dropout_seed=None,
                           schedule: str = "zigzag"):
    """Convenience wrapper: global [B, H, T, D] arrays in natural sequence order,
    sharded over ``seq_axis`` (dim 2). Places inputs if they aren't already
    sharded. For the causal zigzag schedule the wrapper converts to/from the
    zigzag layout (two cheap static gathers), so callers always see natural
    order — the layout is an internal detail of the ring."""
    n = mesh.shape[seq_axis]
    assert q.shape[2] % n == 0, \
        f"seq {q.shape[2]} must divide over {seq_axis}={n}"
    zig = causal and schedule == "zigzag"
    if zig:
        assert q.shape[2] % (2 * n) == 0, \
            f"zigzag needs seq {q.shape[2]} divisible by 2*{n} (use schedule='masked')"
        q, k, v = (zigzag_shard(x, n, axis=2) for x in (q, k, v))
    spec = P(None, None, seq_axis, None)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (x if getattr(x, "sharding", None) == sharding else
               jax.device_put(x, sharding) for x in (q, k, v))
    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                          sm_scale=sm_scale, interpret=interpret,
                          dropout_rate=dropout_rate, dropout_seed=dropout_seed,
                          schedule=schedule),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    out = fn(q, k, v)
    if zig:
        # the unshard gather drops the sequence sharding; pin it back so callers
        # keep the same layout contract as the masked path
        out = jax.device_put(zigzag_unshard(out, n, axis=2), sharding)
    return out
