"""SPMD pipeline parallelism: the multi-chip pipe-axis executor.

This is the TPU-native execution path for pipeline parallelism, replacing the reference's
per-stage processes + blocking p2p broadcasts (``deepspeed/runtime/pipe/p2p.py``) with a
single jitted program over the mesh:

- stage weights are *stacked* along a leading axis sharded over ``pipe`` — each device
  holds only its stage's parameters (true pipeline memory scaling, unlike replication);
- micro-batches stream through ``jax.lax.scan``; stage→stage transfer is a single
  ``lax.ppermute`` over the ``pipe`` axis riding ICI (reference p2p.send/recv);
- the loop is **differentiable**: ``jax.grad`` of the scan yields the reverse pipeline
  (ppermute transposes to the reverse ring), so the backward schedule needs no separate
  instruction stream — XLA derives it. Combined with ``jax.checkpoint`` on the stage
  body, activation memory matches GPipe (inputs-per-microbatch only);
- the data axis composes orthogonally: micro-batches stay sharded over ``data``, so DP
  gradient reduction is still emitted by XLA → this file + zero/sharding.py is the 3-D
  (pipe x data x model) story (reference PipeModelDataParallelTopology, topology.py:246).

Schedule/memory note (vs the reference's 1F1B, runtime/pipe/schedule.py:182-289): the
scan realizes a GPipe-order schedule with jax.checkpoint on the stage body, so the
forward stores only each scan step's STAGE INPUT (one [mb, T, E] tensor per step), not
per-layer activations. Measured on the compiled program (8-virtual-device CPU,
GPT-2 8L/256E/S=4, bf16): temp memory grows ~2.3 MB per extra micro-batch ≈ 0.9x the
stage-input size per step, while 1F1B WITHOUT remat holds up to S in-flight
micro-batches x full per-layer activations (~12x stage-input per stage for 2-layer
stages) regardless of M. For the training configs this engine targets (M <= ~4S
micro-batches per accumulation window), GPipe+remat live memory is at or below
1F1B-without-remat. At M >> S, ``pipeline_apply`` automatically splits the window
into rematerialized SEGMENTS of <= 4S micro-batches, restoring the bound: measured
at M = 16S (GPT-2 2L/128E/S=2, T=512, mb-batch 16, grad of the full loss, peak RSS
on the 8-virtual-device CPU) single flush 4529 MB vs segmented 2287 MB. By default
the segments are STREAMED (``_streamed_apply``): the pipe buffer is a scan carry
across the checkpoint segments, so the whole window pays the (S-1)-step fill ONCE —
the reference 1F1B's single-fill discipline (schedule.py:182-289) — instead of per
flush: at M=16S, S=8, cap=4S the lockstep step count drops 156 -> 135 (bubble 17.9%
-> 5.2%; ``flush_schedule`` is the accounting). The legacy drain-per-flush schedule
(``_flushed_apply``) stays available via ``stream_segments=False`` as a comparison
oracle.

Requires homogeneous stages (equal per-stage blocks) — the layout GPT/BERT stacks
naturally have. Heterogeneous first/last work (embedding, LM head, loss) runs inside the
same shard_map: ``first_stage_fn``/``post_fn`` may use pipe-axis collectives, so large
IO parameters (the embedding table) can be SHARDED over ``pipe`` instead of replicated —
see GPT2Pipe's vocab-parallel embedding/head, which stores 1/S of the vocab table per
pipe rank (the reference replicated tied embeddings on first+last stage and all-reduced
their grads across the tied group, runtime/pipe/module.py TiedLayerSpec; sharding the
table over pipe makes the tie free and the memory ∝ 1/S).
"""

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, PIPE_AXIS, axis_size, shard_map


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees into leading-axis-S leaves (shard over pipe)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stacked_param_sharding(mesh: Mesh, stacked_tree):
    """NamedShardings placing each stage's slice on its pipe rank."""
    def leaf(x):
        spec = [PIPE_AXIS] + [None] * (x.ndim - 1)
        return NamedSharding(mesh, P(*spec))
    return jax.tree_util.tree_map(leaf, stacked_tree)


def flush_schedule(M: int, S: int, cap: int, streamed: bool = True):
    """Compiled-step accounting for an M-micro-batch window on an S-stage pipe with
    checkpoint segments of ``cap`` micro-batches (the memory bound).

    ``ideal_steps`` is the single-fill optimum ``M + S - 1`` (the reference 1F1B's
    per-optimizer-step discipline, reference schedule.py:182-289). The STREAMED
    schedule achieves it exactly — the pipe buffer is carried across checkpoint
    segments so segment i+1's fill IS segment i's drain. The legacy per-flush
    schedule drains every flush: ``(M / cap) * (cap + S - 1)`` steps.

    Returns ``{steps, ideal_steps, n_segments, bubble_fraction}`` where
    bubble_fraction = 1 - M / steps (fraction of lockstep steps in which at least
    one stage computes no real micro-batch)."""
    assert M % cap == 0, f"window M={M} must divide into segments of {cap}"
    n = M // cap
    steps = (M + S - 1) if streamed else n * (cap + S - 1)
    return {"steps": steps, "ideal_steps": M + S - 1, "n_segments": n,
            "bubble_fraction": 1.0 - M / steps}


def _infer_specs(stacked_params, x_microbatches, last_stage_args, first_stage_args,
                 last_stage_args_specs, first_stage_args_specs, stacked_param_specs, M):
    """Default shard_map specs shared by the unsplit and streamed paths: stacked
    params over pipe, micro-batches data-sharded on dim 1, everything else
    replicated. A last_stage_args leaf that LOOKS micro-batched ([M, batch, ...]
    — e.g. labels, but equally a weight whose leading dim happens to equal M)
    is ambiguous, and guessing data-sharded would silently mis-shard the weight
    case; like the drain-per-flush schedule (which additionally CHUNKS
    micro-batched args), refuse and demand explicit last_stage_args_specs."""
    x_spec = P(*([None, DATA_AXIS] + [None] * (x_microbatches.ndim - 2)))
    stacked_spec = (stacked_param_specs if stacked_param_specs is not None
                    else jax.tree_util.tree_map(
                        lambda a: P(*([PIPE_AXIS] + [None] * (a.ndim - 1))),
                        stacked_params))

    if last_stage_args_specs is None:
        for path, a in jax.tree_util.tree_flatten_with_path(last_stage_args)[0]:
            if hasattr(a, "ndim") and a.ndim >= 2 and a.shape[0] == M:
                raise ValueError(
                    f"pipeline_apply: last_stage_args leaf "
                    f"'{jax.tree_util.keystr(path) or '<root>'}' (shape {a.shape}) has "
                    f"leading dim == M={M} and could be either a micro-batched input "
                    "(P(None, 'data')) or a replicated weight (P()) — pass explicit "
                    "last_stage_args_specs instead of relying on shape inference.")
    last_spec = (last_stage_args_specs if last_stage_args_specs is not None
                 else jax.tree_util.tree_map(lambda _: P(), last_stage_args))
    first_spec = (first_stage_args_specs if first_stage_args_specs is not None
                  else jax.tree_util.tree_map(lambda _: P(), first_stage_args))
    return x_spec, stacked_spec, last_spec, first_spec


def _streamed_apply(stage_fn, stacked_params, x_microbatches, cap, *, mesh,
                    last_stage_fn, last_stage_args, first_stage_fn, first_stage_args,
                    last_stage_args_specs, first_stage_args_specs, stacked_param_specs,
                    last_stage_collective):
    """Checkpoint-segmented pipeline WITHOUT per-segment drain: the pipe buffer is a
    scan carry across segments, so micro-batches stream continuously and the whole
    window pays the (S-1)-step fill exactly once — the single-fill discipline of the
    reference's 1F1B (schedule.py:182-289) with GPipe-order remat memory (backward
    replays one ``cap``-micro-batch segment at a time; live memory is one segment's
    stage inputs + the running grads, same bound as ``_flushed_apply``).

    vs. the per-flush schedule this removes (M/cap - 1) * (S-1) lockstep steps:
    at M=16S, cap=4S, the step count drops 156 -> 135 (S=8) — see flush_schedule."""
    M = x_microbatches.shape[0]
    S = mesh.shape[PIPE_AXIS]
    n = M // cap

    x_spec, stacked_spec, last_spec, first_spec = _infer_specs(
        stacked_params, x_microbatches, last_stage_args, first_stage_args,
        last_stage_args_specs, first_stage_args_specs, stacked_param_specs, M)

    def inner(stacked_local, x_mb, last_args, first_args):
        # ONE shard_map for the whole window: the pipe buffer lives entirely
        # inside it (segments are an inner checkpointed scan), so its cotangent
        # never crosses a shard_map boundary — routing it through per-segment
        # shard_map calls dropped/corrupted exactly the boundary micro-batches'
        # first-stage grads (measured: mbs {cap-S+1 mod cap} wrong, loss exact).
        s = jax.lax.axis_index(PIPE_AXIS)
        is_first = s == 0
        is_last = s == S - 1
        my_params = jax.tree_util.tree_map(lambda a: a[0], stacked_local)

        def ingest(g):
            x0 = x_mb[jnp.clip(g, 0, M - 1)]
            if first_stage_fn is not None:
                x0 = first_stage_fn(x0, *first_args)
            return x0

        def step(ingest_real):
            def body(carry, g):
                buf, loss_acc = carry
                if ingest_real:  # static: the drain never ingests
                    # ingest runs UNCONDITIONALLY on every rank (it may contain
                    # pipe collectives — vocab-parallel embedding — which must
                    # stay uniform); only the SELECT is rank-dependent
                    x_ing = ingest(g)
                    x_in = jnp.where(is_first, x_ing, buf) if x_ing.ndim == 0 else \
                        jax.lax.select(jnp.broadcast_to(is_first, ()), x_ing, buf)
                else:
                    x_in = buf
                y = stage_fn(my_params, x_in)
                mb = g - (S - 1)
                valid = jnp.logical_and(mb >= 0, mb < M)
                if last_stage_collective:
                    def do_head(_):
                        y_b = jax.lax.psum(
                            jnp.where(is_last, 1.0, 0.0).astype(y.dtype) * y, PIPE_AXIS)
                        return last_stage_fn(y_b, *last_args, jnp.clip(mb, 0, M - 1))

                    loss_acc = loss_acc + jax.lax.cond(
                        valid, do_head, lambda _: jnp.zeros((), jnp.float32),
                        operand=None)
                else:
                    take = jnp.logical_and(is_last, valid)
                    loss_acc = loss_acc + jax.lax.cond(
                        take,
                        lambda _: last_stage_fn(y, *last_args, jnp.clip(mb, 0, M - 1)),
                        lambda _: jnp.zeros((), jnp.float32), operand=None)
                perm = [(i, (i + 1) % S) for i in range(S)]
                return (jax.lax.ppermute(y, PIPE_AXIS, perm), loss_acc), None

            return body

        @jax.checkpoint
        def segment(carry, f):
            # cap lockstep steps; backward replays ONE segment's forward at a
            # time — the same live-memory bound as the per-flush schedule, but
            # the (buf, loss) carry streams on so the pipe never drains
            carry, _ = jax.lax.scan(step(True), carry, f * cap + jnp.arange(cap))
            return carry, None

        x0_example = jax.eval_shape(ingest, jax.ShapeDtypeStruct((), jnp.int32))
        carry0 = (jnp.zeros(x0_example.shape, x0_example.dtype),
                  jnp.zeros((), jnp.float32))
        carry, _ = jax.lax.scan(segment, carry0, jnp.arange(n))
        if S > 1:
            carry, _ = jax.lax.scan(step(False), carry, M + jnp.arange(S - 1))
        _, loss_acc = carry
        if last_stage_collective:
            # the collective head already accumulates uniformly over pipe
            return jax.lax.pmean(loss_acc / M, DATA_AXIS)
        loss = jax.lax.psum(jnp.where(is_last, loss_acc, 0.0), PIPE_AXIS) / M
        return jax.lax.pmean(loss, DATA_AXIS)

    fn = shard_map(inner, mesh=mesh,
                   in_specs=(stacked_spec, x_spec, last_spec, first_spec),
                   out_specs=P(), check_vma=False)
    return fn(stacked_params, x_microbatches, last_stage_args, first_stage_args)


def _flushed_apply(stage_fn, stacked_params, x_microbatches, cap, *, mesh,
                   last_stage_fn, last_stage_args, first_stage_fn, first_stage_args,
                   last_stage_args_specs, first_stage_args_specs, stacked_param_specs,
                   last_stage_collective):
    """Split an M-micro-batch window into M/cap pipeline flushes and scan over them
    with a ``jax.checkpoint``-wrapped flush body.

    The scan serializes the flushes (a Python-unrolled loop lets the runtime
    overlap independent flush recomputations, which RAISES peak memory) and the
    checkpoint discards each flush's interior residuals, so backward live memory is
    one flush's stage inputs + the running grads — bounded in M. Measured (8-virtual-
    device CPU peak RSS, 256-step scan analog): whole 1291 MB vs scanned flushes
    657 MB; Python-unrolled flushes regressed to 1625 MB."""
    M = x_microbatches.shape[0]
    n = M // cap

    def is_microbatched(a, spec):
        # micro-batched last_stage_args (labels) scan with the flushes; weights and
        # scalars ride the closure. ONLY a leading None in the explicit spec marks
        # the micro-batch dim (P() means replicated — a weight whose leading dim
        # happens to equal M must NOT be chunked), and a [M] 1-D leaf (per-micro-
        # batch weights) qualifies.
        if not (hasattr(a, "ndim") and a.ndim >= 1 and a.shape and a.shape[0] == M):
            return False
        return len(spec) > 0 and spec[0] is None

    flat_args, args_treedef = jax.tree_util.tree_flatten(last_stage_args)
    if last_stage_args_specs is None and flat_args:
        # A shape heuristic here (leading dim == M) would silently chunk a weight
        # whose leading dim coincides with M across flushes — demand the explicit
        # contract instead of guessing.
        raise ValueError(
            f"pipeline_apply: the {M}-micro-batch window splits into flushes of "
            f"{cap}, which requires explicit last_stage_args_specs to tell "
            "micro-batched leaves (leading-None PartitionSpec, e.g. P(None, 'data')) "
            "from per-flush constants (P()). Pass last_stage_args_specs, or "
            "max_microbatches_per_flush=0 to disable splitting.")
    if last_stage_args_specs is not None:
        # specs may be a PREFIX tree (one P covering a whole subtree, as shard_map
        # accepts): broadcast each prefix leaf over its matching args subtree
        is_p = lambda x: isinstance(x, P)
        broadcast = jax.tree_util.tree_map(
            lambda spec, sub: jax.tree_util.tree_map(lambda _: spec, sub),
            last_stage_args_specs, last_stage_args, is_leaf=is_p)
        flat_specs = jax.tree_util.tree_leaves(broadcast, is_leaf=is_p)
    else:
        flat_specs = [P()] * len(flat_args)
    mb_flags = [is_microbatched(a, sp) for a, sp in zip(flat_args, flat_specs)]

    x_chunks = x_microbatches.reshape((n, cap) + x_microbatches.shape[1:])
    scanned = [a.reshape((n, cap) + a.shape[1:]) for a, f in zip(flat_args, mb_flags) if f]

    @jax.checkpoint
    def flush(acc, chunk_and_mb):
        chunk, mb_leaves = chunk_and_mb
        it = iter(mb_leaves)
        largs = jax.tree_util.tree_unflatten(
            args_treedef, [next(it) if f else a for a, f in zip(flat_args, mb_flags)])
        loss = pipeline_apply(
            stage_fn, stacked_params, chunk, mesh=mesh,
            last_stage_fn=last_stage_fn, last_stage_args=largs,
            first_stage_fn=first_stage_fn, first_stage_args=first_stage_args,
            last_stage_args_specs=last_stage_args_specs,
            first_stage_args_specs=first_stage_args_specs,
            stacked_param_specs=stacked_param_specs,
            last_stage_collective=last_stage_collective,
            max_microbatches_per_flush=0)
        return acc + loss, None

    total, _ = jax.lax.scan(flush, jnp.zeros((), jnp.float32),
                            (x_chunks, tuple(scanned)))
    return total / n


def pipeline_apply(stage_fn: Callable,
                   stacked_params,
                   x_microbatches,
                   *,
                   mesh: Mesh,
                   last_stage_fn: Callable = None,
                   last_stage_args=(),
                   first_stage_fn: Callable = None,
                   first_stage_args=(),
                   last_stage_args_specs=None,
                   first_stage_args_specs=None,
                   stacked_param_specs=None,
                   last_stage_collective: bool = False,
                   max_microbatches_per_flush: int = None,
                   stream_segments: bool = True):
    """Run micro-batches through the pipe-axis pipeline inside shard_map.

    When the window exceeds ``max_microbatches_per_flush`` (default ``4 * n_stages``,
    the M <= ~4S regime where GPipe+remat live memory matches 1F1B — see module
    docstring), the loss path automatically splits into ``ceil(M / cap)``
    ``jax.checkpoint`` segments: the backward of segment i replays only segment i's
    forward, so live memory is bounded by one segment's stage inputs regardless of M.
    With ``stream_segments=True`` (default) the pipe buffer is CARRIED across
    segments — micro-batches stream continuously and the whole window pays the
    (S-1)-step fill exactly once (the reference 1F1B's single-fill discipline,
    schedule.py:182-289; see ``flush_schedule`` for the step accounting). With
    ``stream_segments=False`` each segment drains fully before the next fills (the
    legacy per-flush schedule: (M/cap)(cap+S-1) steps — kept as a comparison
    oracle). Pass ``max_microbatches_per_flush=0`` to disable splitting.

    Args:
      stage_fn: homogeneous per-stage function ``(stage_params, x) -> y``; applied by
        every pipe rank to its own parameter slice.
      stacked_params: pytree with leading dim = n_stages on every leaf (see
        ``stack_stage_params``), sharded over ``pipe``.
      x_microbatches: [M, ...] micro-batched activations entering stage 0 (replicated
        over pipe, sharded over data on the batch dim).
      last_stage_fn: optional ``(y, *last_stage_args, mb_index) -> scalar`` applied to
        each micro-batch's final activation at the last stage (e.g. head+loss). Returns
        the mean over micro-batches, psum-broadcast over pipe. When None, returns the
        [M, ...] outputs broadcast over pipe.
      first_stage_fn: optional ``(x_mb, *first_stage_args) -> activation`` applied at
        stage 0 before the first block (e.g. embedding lookup inside the pipeline).
        Runs inside shard_map on every pipe rank, so it MAY use pipe-axis collectives
        over pipe-sharded first_stage_args (vocab-parallel embedding).
      first_stage_args_specs: optional PartitionSpecs for first_stage_args (defaults to
        replicated); pass P(pipe, ...) leaves to shard IO params over the pipe axis.
        first_stage_args must NOT be micro-batched ([M, ...]-leading): they ride the
        flush closure whole and are never scanned — put per-micro-batch inputs in
        ``x_microbatches`` (or labels-like data in ``last_stage_args``) instead.
      last_stage_collective: when True, last_stage_fn runs on EVERY pipe rank against
        the per-step psum-broadcast final activation and MAY use pipe-axis collectives
        over pipe-sharded last_stage_args (the vocab-parallel tied head+loss). Only one
        [mb, ...] activation is live per step — no [M, ...] buffer.

    Differentiable in stacked_params / x_microbatches / *args.
    """
    M = x_microbatches.shape[0]
    S = mesh.shape[PIPE_AXIS]
    cap = 4 * S if max_microbatches_per_flush is None else max_microbatches_per_flush
    if last_stage_fn is not None and cap > 0 and M > cap:
        # equal-size flushes so the global mean is the mean of flush means; the
        # largest divisor of M <= cap keeps one compile and one scan shape
        cap_eff = max(d for d in range(1, cap + 1) if M % d == 0)
        if cap_eff < max(2, cap // 2):
            # M has no divisor near the cap (prime/awkward window): either the
            # memory bound silently lapses (cap_eff < 2 -> unsplit) or tiny flushes
            # crater pipeline utilization — surface it instead of both
            import logging
            logging.getLogger("DeepSpeedTPU").warning(
                f"pipeline flush split: window M={M} has no divisor near the cap "
                f"{cap} (best {cap_eff}); %s. Choose M a multiple of a value <= "
                f"{cap} for the documented memory bound.",
                "running a SINGLE unsplit flush (memory grows with M)"
                if cap_eff < 2 else f"running {M // cap_eff} flushes of {cap_eff}")
        if cap_eff >= 2:
            impl = _streamed_apply if stream_segments else _flushed_apply
            return impl(
                stage_fn, stacked_params, x_microbatches, cap_eff, mesh=mesh,
                last_stage_fn=last_stage_fn, last_stage_args=last_stage_args,
                first_stage_fn=first_stage_fn, first_stage_args=first_stage_args,
                last_stage_args_specs=last_stage_args_specs,
                first_stage_args_specs=first_stage_args_specs,
                stacked_param_specs=stacked_param_specs,
                last_stage_collective=last_stage_collective)

    def inner(stacked_local, x_mb, last_args, first_args):
        S = axis_size(PIPE_AXIS)
        s = jax.lax.axis_index(PIPE_AXIS)
        is_first = s == 0
        is_last = s == S - 1
        # shard_map gives leading dim 1 for the pipe-sharded stack; take our slice
        my_params = jax.tree_util.tree_map(lambda a: a[0], stacked_local)

        total_steps = M + S - 1
        act_shape = None

        def ingest(t):
            idx = jnp.clip(t, 0, M - 1)
            x0 = x_mb[idx]
            if first_stage_fn is not None:
                x0 = first_stage_fn(x0, *first_args)
            return x0

        # abstract-eval only: ingest may contain pipe collectives (vocab-parallel
        # embedding) that must not execute just to size the carry buffers
        x0_example = jax.eval_shape(ingest, jax.ShapeDtypeStruct((), jnp.int32))
        carry_init = (jnp.zeros(x0_example.shape, x0_example.dtype),  # arriving activation
                      jnp.zeros((), jnp.float32),            # loss accumulator (last stage)
                      (jnp.zeros((M,) + x0_example.shape, x0_example.dtype)
                       if last_stage_fn is None else jnp.zeros((), jnp.float32)))

        def step(carry, t):
            buf, loss_acc, out_acc = carry
            # stage 0 ingests micro-batch t; others use the activation permuted to them
            x_in = jnp.where(is_first, ingest(t), buf) if x0_example.ndim == 0 else \
                jax.lax.select(jnp.broadcast_to(is_first, ()), ingest(t), buf)
            y = stage_fn(my_params, x_in)
            # last stage finishes micro-batch mb = t - (S - 1)
            mb = t - (S - 1)
            valid = jnp.logical_and(mb >= 0, mb < M)
            take = jnp.logical_and(is_last, valid)
            if last_stage_fn is None:
                out_acc = jax.lax.cond(
                    take,
                    lambda o: o.at[jnp.clip(mb, 0, M - 1)].set(y),
                    lambda o: o,
                    out_acc)
            elif last_stage_collective:
                # run the broadcast + collective head on every rank, but only on
                # steps that finish a micro-batch: ``valid`` depends only on the scan
                # counter (uniform across ranks), so lax.cond keeps collective
                # execution uniform while skipping the S-1 warmup/drain steps' head
                def do_head(_):
                    y_b = jax.lax.psum(
                        jnp.where(is_last, 1.0, 0.0).astype(y.dtype) * y, PIPE_AXIS)
                    return last_stage_fn(y_b, *last_args, jnp.clip(mb, 0, M - 1))

                contrib = jax.lax.cond(valid, do_head,
                                       lambda _: jnp.zeros((), jnp.float32),
                                       operand=None)
                loss_acc = loss_acc + contrib
            else:
                contrib = jax.lax.cond(
                    take,
                    lambda _: last_stage_fn(y, *last_args, jnp.clip(mb, 0, M - 1)),
                    lambda _: jnp.zeros((), jnp.float32),
                    operand=None)
                loss_acc = loss_acc + contrib
            # rotate activations one stage forward over ICI
            perm = [(i, (i + 1) % S) for i in range(S)]
            buf_next = jax.lax.ppermute(y, PIPE_AXIS, perm)
            return (buf_next, loss_acc, out_acc), None

        (buf, loss_acc, out_acc), _ = jax.lax.scan(step, carry_init, jnp.arange(total_steps))

        if last_stage_fn is None:
            # broadcast last stage's outputs to every pipe rank (differentiable psum)
            mask = jnp.where(is_last, 1.0, 0.0)
            out = jax.lax.psum(out_acc * mask.astype(out_acc.dtype), PIPE_AXIS)
            return out
        if last_stage_collective:
            # the collective head already made loss_acc uniform over pipe
            return jax.lax.pmean(loss_acc / M, DATA_AXIS)
        loss = jax.lax.psum(jnp.where(is_last, loss_acc, 0.0), PIPE_AXIS) / M
        # the user's last_stage_fn returns a mean over its LOCAL batch shard; average the
        # equal-sized shards to the global mean (and replicate over data for out_spec P())
        loss = jax.lax.pmean(loss, DATA_AXIS)
        return loss

    # shardings: stacked params split over pipe (caller-provided layouts, e.g.
    # model-axis TP dims, pass through); everything else replicated over pipe
    # (data-dim sharding of the micro-batches is preserved by P(None, 'data', ...)).
    x_spec, stacked_spec, last_spec, first_spec = _infer_specs(
        stacked_params, x_microbatches, last_stage_args, first_stage_args,
        last_stage_args_specs, first_stage_args_specs, stacked_param_specs, M)
    out_spec = P() if last_stage_fn is not None else x_spec

    fn = shard_map(inner, mesh=mesh,
                   in_specs=(stacked_spec, x_spec, last_spec, first_spec),
                   out_specs=out_spec,
                   check_vma=False)
    return fn(stacked_params, x_microbatches, last_stage_args, first_stage_args)
