"""Device-mesh construction for {data, model, pipe} parallelism.

This is the TPU-native heart of what the reference scattered across NCCL process-group
creation (``deepspeed/runtime/pipe/topology.py:299-364``, ``runtime/engine.py:70-86``): one
``jax.sharding.Mesh`` with named axes, over which every collective in the framework runs
(``psum`` for DP allreduce, ``psum_scatter`` for ZeRO reduce-scatter, ``all_gather`` for
param regather, ``ppermute`` for pipeline p2p).

Axis order is (pipe, data, model): pipe outermost so adjacent stages sit on contiguous
device blocks (DCN-friendly), model innermost so TP collectives ride the fastest ICI links
— the standard TPU mesh recipe.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: the top-level API when this jax has it,
    else the ``jax.experimental`` spelling (where ``check_vma`` was ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def set_mesh(mesh: Mesh):
    """``jax.set_mesh`` across jax versions: on older jax the ``Mesh`` object is
    itself the context manager that installs the global resource env."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def axis_size(axis_name: str):
    """``jax.lax.axis_size`` across jax versions — older ones use the psum-of-one
    idiom, which constant-folds to the same static size under tracing."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def build_mesh(data: Optional[int] = None,
               model: int = 1,
               pipe: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (pipe, data, model) mesh over the given devices.

    ``data=None`` means "use all remaining devices" after model/pipe are placed.
    """
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None:
        assert n % (model * pipe) == 0, f"{n} devices not divisible by model*pipe={model * pipe}"
        data = n // (model * pipe)
    total = data * model * pipe
    assert total <= n, f"mesh needs {total} devices, only {n} available"
    if not explicit_devices and total != n:
        # Never silently strand devices; a submesh must be an explicit choice.
        raise ValueError(f"mesh shape (pipe={pipe}, data={data}, model={model}) covers {total} of {n} "
                         f"devices; pass devices=... explicitly to build a submesh")
    dev_array = np.asarray(devices[:total]).reshape(pipe, data, model)
    return Mesh(dev_array, axis_names=(PIPE_AXIS, DATA_AXIS, MODEL_AXIS))


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    dev = device or jax.devices()[0]
    return build_mesh(data=1, model=1, pipe=1, devices=[dev])


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis (leading dim)."""
    return NamedSharding(mesh, P(DATA_AXIS))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_from_mpu(mpu) -> Mesh:
    """Build a mesh matching an mpu/grid object's (pipe, data, model) sizes."""
    return build_mesh(data=mpu.get_data_parallel_world_size(),
                      model=mpu.get_slice_parallel_world_size(),
                      pipe=mpu.get_pipe_parallel_world_size())
