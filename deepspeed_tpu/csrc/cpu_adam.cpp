// Host-side SIMD Adam for ZeRO-Offload.
//
// TPU-native analog of the reference's csrc/adam/cpu_adam.cpp (AVX512/AVX256 + OpenMP
// Adam over fp32 host arrays, cpu_adam.cpp:21,151,336) and its fused
// ds_adam_step_plus_copy (cpu_adam.cpp:592): on a TPU-VM the offloaded optimizer state
// lives in host DRAM and the updated parameters are pushed back to HBM in bf16, so the
// fused variant converts fp32 -> bf16 (round-to-nearest-even) in the same pass instead
// of fp16.
//
// Vectorization strategy: instead of the reference's hand-written AVX intrinsic ladder,
// the loops are written to be trivially auto-vectorizable (restrict pointers, no
// branches in the hot path) and compiled with -O3 -march=native -fopenmp; gcc emits the
// same fused AVX2/AVX512 code the intrinsics would, and the source stays portable to
// any TPU-VM host ISA (x86 or ARM).

#include <cmath>
#include <cstdint>

extern "C" {

// One Adam/AdamW step over a flat fp32 buffer. All state updated in place.
//   adamw != 0    -> decoupled weight decay: p -= lr * (m_hat/denom + wd * p)
//   adamw == 0    -> classic L2 Adam (torch.optim.Adam): wd*p is folded into the
//                    gradient BEFORE the moment updates, no separate decay term
//   bias_correction != 0 -> m_hat = m/(1-b1^t), v_hat = v/(1-b2^t)
//   grad_scale    -> g[i] is multiplied by this before use (fuses loss-scale
//                    unscaling + gradient clipping into the update pass)
void ds_adam_step(float* __restrict__ p,
                  const float* __restrict__ g,
                  float* __restrict__ m,
                  float* __restrict__ v,
                  int64_t n,
                  int32_t step,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  float grad_scale,
                  int32_t adamw,
                  int32_t bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - powf(beta1, (float)step);
    bc2 = 1.0f - powf(beta2, (float)step);
  }
  const float inv_bc1 = 1.0f / bc1;
  const float inv_sqrt_bc2 = 1.0f / sqrtf(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  // branchless mode select keeps the loop auto-vectorizable
  const float l2_factor = adamw ? 0.0f : weight_decay;        // into the gradient
  const float wd_factor = adamw ? lr * weight_decay : 0.0f;   // decoupled decay

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const float grad = grad_scale * g[i] + l2_factor * p[i];
    const float mi = beta1 * m[i] + omb1 * grad;
    const float vi = beta2 * v[i] + omb2 * grad * grad;
    m[i] = mi;
    v[i] = vi;
    const float denom = sqrtf(vi) * inv_sqrt_bc2 + eps;
    const float update = (mi * inv_bc1) / denom;
    p[i] = p[i] - lr * update - wd_factor * p[i];
  }
}

static inline uint16_t fp32_to_bf16_rne(float x) {
  union {
    float f;
    uint32_t u;
  } bits;
  bits.f = x;
  const uint32_t rounding = 0x7FFFu + ((bits.u >> 16) & 1u);
  return (uint16_t)((bits.u + rounding) >> 16);
}

// Fused step + bf16 cast of the updated parameters (analog of ds_adam_step_plus_copy,
// cpu_adam.cpp:592: the reference overlaps an async H2D fp16 copy; here the bf16 staging
// buffer is handed to jax.device_put which owns the H2D DMA).
void ds_adam_step_copy(float* __restrict__ p,
                       const float* __restrict__ g,
                       float* __restrict__ m,
                       float* __restrict__ v,
                       uint16_t* __restrict__ out_bf16,
                       int64_t n,
                       int32_t step,
                       float lr,
                       float beta1,
                       float beta2,
                       float eps,
                       float weight_decay,
                       float grad_scale,
                       int32_t adamw,
                       int32_t bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - powf(beta1, (float)step);
    bc2 = 1.0f - powf(beta2, (float)step);
  }
  const float inv_bc1 = 1.0f / bc1;
  const float inv_sqrt_bc2 = 1.0f / sqrtf(bc2);
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float l2_factor = adamw ? 0.0f : weight_decay;
  const float wd_factor = adamw ? lr * weight_decay : 0.0f;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const float grad = grad_scale * g[i] + l2_factor * p[i];
    const float mi = beta1 * m[i] + omb1 * grad;
    const float vi = beta2 * v[i] + omb2 * grad * grad;
    m[i] = mi;
    v[i] = vi;
    const float denom = sqrtf(vi) * inv_sqrt_bc2 + eps;
    const float update = (mi * inv_bc1) / denom;
    const float pi = p[i] - lr * update - wd_factor * p[i];
    p[i] = pi;
    out_bf16[i] = fp32_to_bf16_rne(pi);
  }
}

}  // extern "C"
